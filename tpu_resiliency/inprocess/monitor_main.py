"""Exec entry for the rank monitor process (``python -m
tpu_resiliency.inprocess.monitor_main``).

Started by :class:`~tpu_resiliency.inprocess.monitor_process.MonitorProcess`
via exec (never fork — the training parent is JAX-threaded; see that
module's docstring).  Attaches the parent's named-shm
:class:`MonitorSharedState`, connects its own store client, marks ready,
and runs the watch loop: soft-timeout records, hard-timeout kill, parent
death cleanup.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from ..store.client import StoreClient, store_from_env
from ..utils.logging import get_logger, setup_logger
from .attribution import Interruption, InterruptionRecord
from .monitor_process import (
    MonitorSharedState,
    _pid_alive,
    _terminate_process,
)
from .store_ops import InprocStore

log = get_logger("monitor_process")


def _read_fptail(fptail_name: Optional[str]) -> list:
    """Post-mortem fingerprint: the rank's last K dispatched programs, read
    from its named-shm dispatch tail — the rank itself may be wedged inside
    a device call and unable to publish anything."""
    if not fptail_name:
        return []
    try:
        from .fingerprint import read_tail

        return read_tail(fptail_name)
    except (OSError, ValueError) as exc:
        log.warning("monitor: cannot read dispatch tail %s: %s",
                    fptail_name, exc)
        return []


def _record(ops: InprocStore, rank: int, iteration: int,
            kind: Interruption, msg: str, fptail_name: Optional[str] = None) -> None:
    try:
        ops.record_interruption(
            iteration,
            InterruptionRecord(rank=rank, interruption=kind, message=msg,
                               fingerprint=_read_fptail(fptail_name)),
        )
    except Exception as exc:  # noqa: BLE001
        log.error("monitor: failed to record interruption: %s", exc)


def _publish_fingerprint(ops: InprocStore, rank: int, iteration: int,
                         fptail_name: Optional[str]) -> None:
    """Mirror the post-mortem tail into the iteration's at-abort fingerprint
    log — the wedged rank cannot run its own FingerprintStage, so the
    monitor dumps on its behalf (the Flight-Recorder-at-abort analog)."""
    tail = _read_fptail(fptail_name)
    if not tail:
        return
    try:
        ops.record_fingerprint(iteration, rank, tail)
    except Exception as exc:  # noqa: BLE001
        log.error("monitor: failed to publish fingerprint: %s", exc)


def run_monitor(
    shared: MonitorSharedState,
    store,
    group: str,
    rank: int,
    parent_pid: int,
    soft_timeout: float,
    hard_timeout: float,
    interval: float,
    termination_grace: float,
    fptail_name: Optional[str] = None,
) -> None:
    ops = InprocStore(store, group)
    shared.mark_ready()
    soft_reported_at: Optional[float] = None
    while True:
        time.sleep(interval)
        iteration = shared.iteration
        if not _pid_alive(parent_pid):
            log.error("monitor: rank %s (pid %s) died", rank, parent_pid)
            _record(ops, rank, iteration, Interruption.TERMINATED,
                    "process died", fptail_name)
            ops.mark_terminated(rank)
            return
        if not shared.enabled:
            soft_reported_at = None
            continue
        stamp = shared.timestamp_slot.value
        age = time.time() - stamp  # tpurx: disable=TPURX016 -- cross-process shm stamp; wall clock is the only shared domain
        if age > hard_timeout:
            log.error(
                "monitor: rank %s wedged for %.1fs (> hard %.1fs) — killing",
                rank, age, hard_timeout,
            )
            _record(ops, rank, iteration, Interruption.HARD_TIMEOUT,
                    f"no progress {age:.1f}s", fptail_name)
            _publish_fingerprint(ops, rank, iteration, fptail_name)
            ops.mark_terminated(rank)
            _terminate_process(parent_pid, termination_grace)
            return
        if age > soft_timeout:
            if soft_reported_at is None or soft_reported_at < stamp:
                log.warning(
                    "monitor: rank %s stalled %.1fs (> soft %.1fs)",
                    rank, age, soft_timeout,
                )
                _record(ops, rank, iteration, Interruption.SOFT_TIMEOUT,
                        f"no progress {age:.1f}s", fptail_name)
                _publish_fingerprint(ops, rank, iteration, fptail_name)
                soft_reported_at = time.time()
        else:
            soft_reported_at = None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpurx-monitor")
    p.add_argument("--shm", required=True)
    p.add_argument("--group", required=True)
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--parent-pid", type=int, required=True)
    p.add_argument("--soft-timeout", type=float, default=60.0)
    p.add_argument("--hard-timeout", type=float, default=90.0)
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--termination-grace", type=float, default=5.0)
    p.add_argument("--fptail", default=None,
                   help="named-shm dispatch tail for post-mortem fingerprints")
    p.add_argument("--store-host", default=None)
    p.add_argument("--store-port", type=int, default=None)
    args = p.parse_args(argv)

    # own session: a killpg of the rank's process group must not take the
    # monitor with it (the reference's double-fork detach)
    try:
        os.setsid()
    except OSError:
        pass
    setup_logger()
    try:
        shared = MonitorSharedState.attach(args.shm)
    except (OSError, ValueError) as exc:
        log.error("monitor: cannot attach shared state %s: %s", args.shm, exc)
        return 1
    try:
        if args.store_host and args.store_port:
            store = StoreClient(args.store_host, args.store_port)
        else:
            store = store_from_env()
    except Exception as exc:  # noqa: BLE001
        log.error("monitor %s: cannot reach store: %s", args.rank, exc)
        shared.close()
        return 1
    try:
        run_monitor(
            shared, store, args.group, args.rank, args.parent_pid,
            args.soft_timeout, args.hard_timeout, args.interval,
            args.termination_grace, fptail_name=args.fptail,
        )
    finally:
        shared.close()
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
