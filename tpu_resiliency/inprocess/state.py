"""Restart-loop state (reference ``inprocess/state.py:23-124``)."""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Optional

from ..utils import env


class Mode(str, enum.Enum):
    INITIALIZED = "initialized"
    ACTIVE = "active"          # runs the wrapped fn
    INACTIVE = "inactive"      # healthy spare parked in reserve
    TERMINATED = "terminated"  # out of the job


@dataclasses.dataclass
class State:
    rank: int
    world_size: int
    active_rank: Optional[int] = None
    active_world_size: Optional[int] = None
    initial_rank: Optional[int] = None
    initial_world_size: Optional[int] = None
    iteration: int = 0
    mode: Mode = Mode.INITIALIZED
    fn_exception: Optional[BaseException] = None

    def __post_init__(self):
        if self.initial_rank is None:
            self.initial_rank = self.rank
        if self.initial_world_size is None:
            self.initial_world_size = self.world_size
        if self.active_rank is None:
            self.active_rank = self.rank
        if self.active_world_size is None:
            self.active_world_size = self.world_size

    @classmethod
    def from_env(cls) -> "State":
        return cls(rank=env.RANK.get(), world_size=env.WORLD_SIZE.get())

    def set_distributed_vars(self) -> None:
        """Export active rank/world for the wrapped fn's ecosystem
        (reference ``state.py:94``)."""
        if self.mode == Mode.ACTIVE and self.active_rank is not None:
            os.environ["TPURX_RANK"] = str(self.active_rank)
            os.environ["TPURX_WORLD_SIZE"] = str(self.active_world_size)
            os.environ["RANK"] = str(self.active_rank)
            os.environ["WORLD_SIZE"] = str(self.active_world_size)

    def advance(self) -> None:
        self.iteration += 1
        self.fn_exception = None

    def freeze(self) -> "FrozenState":
        return FrozenState(
            rank=self.rank,
            world_size=self.world_size,
            active_rank=self.active_rank,
            active_world_size=self.active_world_size,
            initial_rank=self.initial_rank,
            initial_world_size=self.initial_world_size,
            iteration=self.iteration,
            mode=self.mode,
        )


@dataclasses.dataclass(frozen=True)
class FrozenState:
    """Immutable snapshot handed to plugins (reference ``FrozenState``)."""

    rank: int
    world_size: int
    active_rank: Optional[int]
    active_world_size: Optional[int]
    initial_rank: Optional[int]
    initial_world_size: Optional[int]
    iteration: int
    mode: Mode
