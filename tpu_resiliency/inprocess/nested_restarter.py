"""Nested-restart bridge: in-process events → launcher-ring monitor.

Capability parity with ``inprocess/nested_restarter.py:28-107``: when the
in-process wrapper runs UNDER the elastic launcher ("layered restart"), the
rank monitor must learn that a restart is in progress so it does not treat
the quiet heartbeat gap as a hang and kill the recovering rank.

The bridge reports phase transitions as **section messages** on the existing
RankMonitorClient channel: an open ``inprocess_restart`` section tells the
monitor "busy recovering" and its (configurable) timeout bounds how long an
in-process recovery may take before the in-job ring takes over — exactly the
ring-composition contract from SURVEY.md §1.
"""

from __future__ import annotations

from ..fault_tolerance.state_machine import RestarterState, RestartStateMachine
from ..utils.logging import get_logger

log = get_logger("nested_restarter")

SECTION_NAME = "inprocess_restart"


class NestedRestarterCallback:
    """Attach to a Wrapper via its plugin hooks:

        bridge = NestedRestarterCallback(rank_monitor_client)
        Wrapper(initialize=bridge.on_initialize, abort=bridge.on_abort, ...)
    """

    def __init__(self, rank_monitor_client=None):
        self.client = rank_monitor_client
        self.machine = RestartStateMachine()
        self._section_open = False

    def _log_state(self) -> None:
        # the reference emits a parseable log protocol; keep that contract
        log.info("[NestedRestarter] name=[InProcess] state=%s", self.machine.state.value)

    def _open_section(self) -> None:
        if self.client is not None and not self._section_open:
            try:
                self.client.start_section(SECTION_NAME)
                self._section_open = True
            except Exception:  # noqa: BLE001
                log.warning("could not open restart section on rank monitor")

    def _close_section(self) -> None:
        if self.client is not None and self._section_open:
            try:
                self.client.end_section(SECTION_NAME)
            except Exception as exc:  # noqa: BLE001
                log.debug("end_section(%s) failed: %r", SECTION_NAME, exc)
            self._section_open = False

    # -- Wrapper plugin hooks ---------------------------------------------

    def on_initialize(self, state):
        if self.machine.state == RestarterState.UNINITIALIZED:
            self.machine.transition(RestarterState.INITIALIZED)
        else:
            # re-initialize after a restart: recovery finished
            self.machine.transition(RestarterState.COMPLETED)
            self._close_section()
        self._log_state()
        return state

    def on_abort(self, state):
        self.machine.transition(RestarterState.HANDLING_START)
        self._open_section()
        self._log_state()
        return state

    def on_finalize(self, state):
        self.machine.transition(RestarterState.PROCESSING)
        self._log_state()
        return state
