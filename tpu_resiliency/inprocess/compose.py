"""Plugin composition (reference ``inprocess/compose.py:39``)."""

from __future__ import annotations

from typing import Callable


class Compose:
    """Chain single-argument plugins left-to-right: Compose(f, g)(x) == g(f(x)).

    (The reference applies rightmost-first for its ABC chains; here the
    pipeline reads in execution order, which is what every call site wants.)
    """

    def __init__(self, *fns: Callable):
        self.fns = fns

    def __call__(self, arg):
        for fn in self.fns:
            arg = fn(arg)
        return arg

    def __repr__(self) -> str:
        return f"Compose({', '.join(repr(f) for f in self.fns)})"
