"""Plugin composition (reference ``inprocess/compose.py:39``)."""

from __future__ import annotations

from typing import Callable, Iterator


class Compose:
    """Chain single-argument plugins left-to-right: Compose(f, g)(x) == g(f(x)).

    (The reference applies rightmost-first for its ABC chains; here the
    pipeline reads in execution order, which is what every call site wants.)

    Nested ``Compose`` instances flatten, and the chain is iterable — so an
    :class:`~tpu_resiliency.inprocess.abort.AbortLadder` built from a
    ``Compose`` argument sees the individual plugins as rungs (each gets its
    own deadline and recorded outcome) instead of one opaque callable.

    For the ``abort=`` plugin slot specifically, prefer ``AbortLadder``
    directly: ``Compose`` runs plugins inline with no per-stage timeout, so
    one blocked plugin stalls the whole chain.
    """

    def __init__(self, *fns: Callable):
        flat: list = []
        for fn in fns:
            if isinstance(fn, Compose):
                flat.extend(fn.fns)
            else:
                flat.append(fn)
        self.fns = tuple(flat)

    def __call__(self, arg):
        for fn in self.fns:
            arg = fn(arg)
        return arg

    def __iter__(self) -> Iterator[Callable]:
        return iter(self.fns)

    def __len__(self) -> int:
        return len(self.fns)

    def __repr__(self) -> str:
        return f"Compose({', '.join(repr(f) for f in self.fns)})"
