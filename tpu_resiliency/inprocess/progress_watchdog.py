"""Progress watchdog: liveness timestamps for the monitor process.

Capability parity with ``inprocess/progress_watchdog.py:49-196``: a hybrid of
manual ``ping()`` calls from the training loop and **automatic** timestamps
proving the interpreter's main thread still executes bytecode even when user
code doesn't ping.  The reference injects a C callback with
``Py_AddPendingCall``; the pending call runs on the main thread at a
bytecode boundary, so a GIL-holding C extension or a wedged device wait
stops the auto-timestamps (exactly the hangs we must catch), while a
merely-slow loop keeps them flowing.

The callback itself is PURE C (``native/pending_stamp.c``) when the native
build is available: the monitor thread's async restart raise is delivered by
the same eval-breaker event that runs pending calls, so a Python-level
callback frame reliably eats the raise and corrupts the trampoline's error
state.  A ctypes Python callback remains as the no-toolchain fallback, with
the raise swallowed defensively (the monitor re-raises on a backoff).

Timestamps are written to a multiprocessing shared value read by the
MonitorProcess (no queue: a wedged consumer must not block the producer).
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
import threading

from ..ops.quorum import StampTripwire, wall_time_s
from ..utils.logging import get_logger

log = get_logger("progress_watchdog")

_PENDING_CALLBACK = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


class _StampRefs(ctypes.Structure):
    _fields_ = [("timestamp", ctypes.c_void_p), ("consumed", ctypes.c_void_p)]


_PINNED: list = []  # shared slots a queued C pending call may still touch


def _load_native_stamper():
    """Load the pure-C pending-call stamper via the shared build-on-demand
    loader (load-first, atomic temp build — utils/native.py); None if the
    toolchain or loader can't deliver it (fallback: ctypes callback)."""
    from ..utils.native import load_native

    lib = load_native("libtpurx-pending.so", "pending_stamp.c",
                      required_symbols=("tpurx_schedule_stamp",))
    if lib is not None:
        # idempotent re-assignment: load_native caches the CDLL per process
        lib.tpurx_schedule_stamp.argtypes = [ctypes.c_void_p]
        lib.tpurx_schedule_stamp.restype = ctypes.c_int
    return lib


class ProgressWatchdog:
    def __init__(self, interval: float = 1.0, timestamp_slot=None):
        self.interval = interval
        # 'd' = double epoch seconds; lock-free single-writer.  An external
        # ``timestamp_slot`` (a ctypes double over named shm, from
        # MonitorSharedState) lets the exec'd monitor process read the
        # stamps without fork/pickling; default stays process-local.
        if timestamp_slot is not None:
            self.timestamp = timestamp_slot
            self.timestamp.value = wall_time_s()
        else:
            self.timestamp = mp.Value("d", wall_time_s(), lock=False)
        # event-driven liveness feed: every stamp (manual ping or a consumed
        # pending call) sets the event, so a StampTripwire can park on it
        # instead of polling ``age()`` — see :meth:`watch_stale`
        self.beat_event = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # keep the callback object alive (ctypes would GC it)
        self._cb = _PENDING_CALLBACK(self._pending_call)
        self._pending_scheduled = threading.Event()
        # pure-C path: shared consumption counter + pinned refs struct
        self._native = _load_native_stamper()
        if self._native is not None:
            self._consumed = mp.Value("l", 0, lock=False)
            self._refs = _StampRefs(
                ctypes.cast(ctypes.addressof(self.timestamp), ctypes.c_void_p),
                ctypes.cast(ctypes.addressof(self._consumed), ctypes.c_void_p),
            )
            self._last_consumed = 0
            self._native_inflight = False
            # a queued pending call outlives this object's GC: the pointed-to
            # memory must never be freed (bounded: one pin per watchdog)
            _PINNED.append((self.timestamp, self._consumed, self._refs))

    # -- main-thread proof-of-life ----------------------------------------

    def _pending_call(self, _arg) -> int:
        # Runs on the MAIN thread at a bytecode boundary.  The monitor
        # thread's async RankShouldRestart can land HERE (it targets the
        # main thread, and this callback runs on it): swallow anything —
        # an exception escaping a ctypes pending-call callback corrupts the
        # eval loop's error state (SystemError leaks into user code).  The
        # monitor re-raises on a backoff until the raise lands in user code.
        try:
            self.timestamp.value = wall_time_s()
            self.beat_event.set()
            self._pending_scheduled.clear()
        # tpurx: disable=TPURX009 -- ctypes pending-call callback: an escaping raise corrupts the eval loop error state
        except BaseException:  # noqa: BLE001
            pass
        return 0

    def _schedule_pending(self) -> None:
        if self._native is not None:
            cur = self._consumed.value
            if self._native_inflight and cur == self._last_consumed:
                return  # previous one not consumed — main thread busy/stuck
            self._last_consumed = cur
            self._native_inflight = True
            res = self._native.tpurx_schedule_stamp(ctypes.addressof(self._refs))
            if res != 0:  # queue full — fine, we try again next tick
                self._native_inflight = False
            return
        if self._pending_scheduled.is_set():
            return  # previous one not consumed yet — main thread busy/stuck
        self._pending_scheduled.set()
        res = ctypes.pythonapi.Py_AddPendingCall(self._cb, None)
        if res != 0:  # queue full — fine, we try again next tick
            self._pending_scheduled.clear()

    # -- API ---------------------------------------------------------------

    def ping(self) -> None:
        """Manual liveness signal from the training loop."""
        self.timestamp.value = wall_time_s()
        self.beat_event.set()

    def age(self) -> float:
        return wall_time_s() - self.timestamp.value

    def watch_stale(self, budget_s: float, on_stale) -> StampTripwire:
        """Event-driven GIL-liveness tripwire on this watchdog's stamps.

        Parks a :class:`~tpu_resiliency.ops.quorum.StampTripwire` on
        ``beat_event`` — the native pending-call stamper proves the MAIN
        thread still reaches bytecode boundaries, so a timeout here is the
        GIL-wedge class the native beater deliberately cannot see.  The
        waiter observes staleness at wake latency (no polling read of
        ``age()``); ``on_stale(age_ms)`` fires from the watcher thread.
        Caller owns ``.stop()``."""
        return StampTripwire(
            on_stale=on_stale,
            budget_ms=budget_s * 1e3,
            event=self.beat_event,
            age_ns_fn=lambda: max(0, int(self.age() * 1e9)),
        ).start()

    def start(self) -> "ProgressWatchdog":
        self.ping()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpurx-progress-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._schedule_pending()

    def pause(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None

    resume = start

    def stop(self) -> None:
        self.pause()
