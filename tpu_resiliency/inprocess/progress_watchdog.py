"""Progress watchdog: liveness timestamps for the monitor process.

Capability parity with ``inprocess/progress_watchdog.py:49-196``: a hybrid of
manual ``ping()`` calls from the training loop and **automatic** timestamps
proving the interpreter's main thread still executes bytecode even when user
code doesn't ping.  The reference injects a C callback with
``Py_AddPendingCall``; we do the same through ctypes — the pending call runs
on the main thread at a bytecode boundary, so a GIL-holding C extension or a
wedged device wait stops the auto-timestamps (exactly the hangs we must
catch), while a merely-slow loop keeps them flowing.

Timestamps are written to a multiprocessing shared value read by the
MonitorProcess (no queue: a wedged consumer must not block the producer).
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
import threading
import time

from ..utils.logging import get_logger

log = get_logger("progress_watchdog")

_PENDING_CALLBACK = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


class ProgressWatchdog:
    def __init__(self, interval: float = 1.0):
        self.interval = interval
        # 'd' = double epoch seconds; lock-free single-writer
        self.timestamp = mp.Value("d", time.time(), lock=False)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # keep the callback object alive (ctypes would GC it)
        self._cb = _PENDING_CALLBACK(self._pending_call)
        self._pending_scheduled = threading.Event()

    # -- main-thread proof-of-life ----------------------------------------

    def _pending_call(self, _arg) -> int:
        # Runs on the MAIN thread at a bytecode boundary.
        self.timestamp.value = time.time()
        self._pending_scheduled.clear()
        return 0

    def _schedule_pending(self) -> None:
        if self._pending_scheduled.is_set():
            return  # previous one not consumed yet — main thread busy/stuck
        self._pending_scheduled.set()
        res = ctypes.pythonapi.Py_AddPendingCall(self._cb, None)
        if res != 0:  # queue full — fine, we try again next tick
            self._pending_scheduled.clear()

    # -- API ---------------------------------------------------------------

    def ping(self) -> None:
        """Manual liveness signal from the training loop."""
        self.timestamp.value = time.time()

    def age(self) -> float:
        return time.time() - self.timestamp.value

    def start(self) -> "ProgressWatchdog":
        self.ping()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpurx-progress-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._schedule_pending()

    def pause(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None

    resume = start

    def stop(self) -> None:
        self.pause()
