"""At-abort collective fingerprint: the last K dispatched device programs.

Reference analog: NVRx dumps PyTorch Flight-Recorder NCCL traces at abort
time (``inprocess/abort.py:127-160``, ``TORCH_FR_BUFFER_SIZE``) so
attribution sees *which collective* was in flight.  JAX has no per-collective
recorder, but the dispatch boundary is observable from Python: every
instrumented jitted call records its name + dispatch stamp into a tiny ring
(the :class:`DispatchTail`), and at abort each rank publishes the tail —
op names plus ages — to the store for
:func:`tpu_resiliency.attribution.trace_analyzer.analyze_fingerprints`.

Two properties drive the layout:

- **Readable while the owner is wedged.**  The tail lives in a named
  shared-memory ring (same trick as the straggler op rings,
  ``native/op_ring.c``): a rank blocked inside a device program with the
  GIL released cannot publish anything, so its *monitor process* attaches
  the segment post-mortem and folds the tail into the SOFT/HARD_TIMEOUT
  interruption record — the wedged rank's fingerprint survives its wedge.
- **µs-scale record.**  ``record()`` is two struct packs and a memoryview
  copy; it sits on the dispatch path of every instrumented step.

Concurrency: single writer (the training thread), any number of readers.
Entries are written body-first, sequence-last; a reader that observes a
torn entry (seq mismatch on re-read) drops it — the fingerprint is a
diagnostic, losing the newest entry beats locking the dispatch path.

Feeding the tail: the straggler :class:`OpCollector` records every wrapped
dispatch automatically; workloads without the collector call
:func:`record_dispatch` directly (one line per jitted step).
"""

from __future__ import annotations

import struct
import threading
import time

from ..ops.quorum import now_stamp_ns
from typing import Dict, List, Optional

from ..utils.logging import get_logger
from ..utils.shm import attach_shm, create_shm, unlink_shm

log = get_logger("inproc.fingerprint")

MAGIC = b"TPUFPT01"
NAME_LEN = 48
DEFAULT_CAPACITY = 8

_HEADER = struct.Struct("<8sII")              # magic, capacity, reserved
_ENTRY = struct.Struct(f"<Qq{NAME_LEN}s")     # seq, stamp_ms, name
HEADER_SIZE = _HEADER.size
ENTRY_SIZE = _ENTRY.size


def arena_size(capacity: int) -> int:
    return HEADER_SIZE + capacity * ENTRY_SIZE


class DispatchTail:
    """Shm-backed ring of the last K dispatched device programs.

    ``shm=None`` falls back to a process-local bytearray (same layout, no
    cross-process readability) — used when shm creation fails or for plain
    in-process snapshots.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, shm=None,
                 owner: bool = False, _buf=None):
        self.capacity = capacity
        self._shm = shm
        self._owner = owner
        if _buf is not None:
            self._buf = _buf
        elif shm is not None:
            self._buf = shm.buf
        else:
            self._buf = memoryview(bytearray(arena_size(capacity)))
        self.name = shm.name if shm is not None else None
        self._seq = 0
        self._lock = threading.Lock()
        if owner or shm is None:
            _HEADER.pack_into(self._buf, 0, MAGIC, capacity, 0)

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, capacity: int = DEFAULT_CAPACITY) -> "DispatchTail":
        """Shared-memory tail (monitor-process-readable); falls back to a
        heap tail when the host can't allocate shm."""
        try:
            shm = create_shm(arena_size(capacity))
        except OSError as exc:
            log.warning("dispatch tail shm unavailable (%s); monitor "
                        "post-mortem fingerprints disabled", exc)
            return cls(capacity)
        return cls(capacity, shm=shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "DispatchTail":
        shm = attach_shm(name)
        magic, capacity, _ = _HEADER.unpack_from(shm.buf, 0)
        if magic != MAGIC:
            shm.close()
            raise ValueError(f"shm {name} is not a dispatch-tail arena")
        return cls(capacity, shm=shm, owner=False)

    # -- writer ------------------------------------------------------------

    def record(self, name: str, stamp_ms: Optional[int] = None) -> None:
        """Record one dispatched program (called at dispatch, before any
        block).  ~µs: two packs and a slot copy."""
        if stamp_ms is None:
            stamp_ms = now_stamp_ns() // 1_000_000
        raw = name.encode(errors="replace")[: NAME_LEN - 1]
        with self._lock:
            seq = self._seq + 1
            off = HEADER_SIZE + ((seq - 1) % self.capacity) * ENTRY_SIZE
            # body first, seq last: readers treat a seq/body mismatch as torn
            _ENTRY.pack_into(self._buf, off, 0, stamp_ms, raw)
            _ENTRY.pack_into(self._buf, off, seq, stamp_ms, raw)
            self._seq = seq

    # -- readers -----------------------------------------------------------

    def snapshot(self, now_ms: Optional[int] = None) -> List[dict]:
        """Entries oldest→newest: ``[{"op", "age_ms", "seq"}, ...]``."""
        if now_ms is None:
            now_ms = now_stamp_ns() // 1_000_000
        out = []
        for i in range(self.capacity):
            off = HEADER_SIZE + i * ENTRY_SIZE
            seq, stamp_ms, raw = _ENTRY.unpack_from(self._buf, off)
            if seq == 0:
                continue
            # torn-write check: the slot for seq must still hold seq
            seq2, _, _ = _ENTRY.unpack_from(self._buf, off)
            if seq2 != seq:
                continue
            out.append({
                "op": raw.split(b"\x00", 1)[0].decode(errors="replace"),
                "age_ms": max(0, now_ms - stamp_ms),
                "seq": int(seq),
            })
        out.sort(key=lambda e: e["seq"])
        return out

    def close(self) -> None:
        if self._shm is None:
            return
        self._buf = None
        if self._owner:
            unlink_shm(self._shm)
        try:
            self._shm.close()
        except BufferError:
            # pinned by an in-flight reader: keep the object alive so its
            # __del__ doesn't retry close() and spray "Exception ignored"
            # tracebacks at interpreter exit — process teardown unmaps
            _LEAKED_SHM.append(self._shm)
        self._shm = None


# segments whose mmap stayed pinned at close (see DispatchTail.close)
_LEAKED_SHM: list = []


# -- process-global tail (one per rank) -------------------------------------

_global_tail = DispatchTail()
_global_lock = threading.Lock()


def install_tail(tail: DispatchTail) -> DispatchTail:
    """Swap the process-global tail (the wrapper installs an shm-backed one
    so the monitor process can read it).  Returns the previous tail."""
    global _global_tail
    with _global_lock:
        prev, _global_tail = _global_tail, tail
    return prev


def get_tail() -> DispatchTail:
    return _global_tail


def record_dispatch(name: str) -> None:
    """Record one dispatched device program into this rank's tail.  Wire it
    at the dispatch boundary: the straggler ``OpCollector`` calls it for
    every wrapped callable; uninstrumented workloads call it directly."""
    _global_tail.record(name)


def snapshot_tail(now_ms: Optional[int] = None) -> List[dict]:
    return _global_tail.snapshot(now_ms)


def read_tail(shm_name: str, now_ms: Optional[int] = None) -> List[dict]:
    """Attach + snapshot + detach (monitor-process post-mortem read)."""
    tail = DispatchTail.attach(shm_name)
    try:
        return tail.snapshot(now_ms)
    finally:
        tail.close()


def parse_fingerprints(raw: Optional[bytes]) -> Dict[int, List[dict]]:
    """Decode the store's at-abort fingerprint log (one JSON object per
    line: ``{"rank": r, "tail": [...]}``); later lines win per rank."""
    import json

    out: Dict[int, List[dict]] = {}
    if not raw:
        return out
    for line in raw.decode(errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
            out[int(obj["rank"])] = list(obj.get("tail", []))
        except (ValueError, KeyError, TypeError):
            continue
    return out
