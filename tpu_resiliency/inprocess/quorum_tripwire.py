"""Quorum tripwire: wire the on-device ICI hang detector into the restart rings.

The :class:`~tpu_resiliency.ops.quorum.QuorumMonitor` detects a pod-wide
stale heartbeat in milliseconds (one int32 all-reduce over ICI), but detection
that triggers nothing shortens no recovery.  This module converts a quorum
trip into the SAME signals the host-side detectors produce, so the existing
restart machinery runs — just sooner:

- **In-process ring** (:class:`QuorumTripwire`): a trip writes an
  :class:`~tpu_resiliency.inprocess.attribution.InterruptionRecord` of kind
  ``QUORUM_STALE`` into the iteration's interruption log — exactly what the
  reference's monitor thread watches (``inprocess/monitor_thread.py:157-186``).
  Every rank's :class:`MonitorThread` sees the record, runs Abort, and
  async-raises ``RankShouldRestart``; the restart loop proceeds without ever
  waiting for the soft/hard host timeouts.
- **In-job ring** (:func:`quorum_restart_requester`): a trip sends a
  ``WorkloadControlRequest(RestartWorkload)`` through the rank-monitor IPC to
  the launcher (reference ``data.py:272`` semantics), which stops the cycle's
  workers and opens a new rendezvous round immediately instead of waiting for
  the rank-heartbeat timeout.

The stale *rank* is identified in the same single collective via
age-device packing (``ops/quorum.py::pack_age_device``): the trip names the
culprit chip, mapped to the process that owns it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..ops.quorum import QuorumMonitor
from ..utils.logging import get_logger
from ..utils.profiling import ProfilingEvent, record_event
from .attribution import Interruption, InterruptionRecord
from .store_ops import InprocStore

log = get_logger("quorum_tripwire")


def device_owner_rank(mesh, device_idx: Optional[int]) -> int:
    """Map a global mesh-flat device index to the rank (process index) that
    owns it.  Single-process meshes own every device — the culprit is rank 0
    by definition of "process rank", but the device index itself still names
    the chip."""
    if device_idx is None:
        return -1
    flat = list(mesh.devices.flatten())
    if not 0 <= device_idx < len(flat):
        return -1
    return int(getattr(flat[device_idx], "process_index", 0))


class QuorumTripwire:
    """In-process-ring glue: quorum trip -> interruption record -> restart.

    One tripwire per :class:`CallWrapper` iteration.  ``beat()`` is the
    workload's progress signal (call it every step); an optional auto-beater
    covers liveness between steps.  On a trip the stale rank's interruption
    record is written at most once per iteration, by every observer (the
    store's interruption log is append-only and the monitor thread coalesces
    duplicates, reference ``wrap.py:162`` last-call-wait semantics).
    """

    def __init__(
        self,
        mesh,
        ops: InprocStore,
        rank: int,
        budget_ms: float = 50.0,
        interval: float = 0.01,
        auto_beat_interval: Optional[float] = 0.002,
        calibrate: bool = True,
        min_budget_ms: float = 2.0,
        use_pallas: Optional[bool] = None,
        fetch_workers: int = 0,
        native_beat: bool = False,
        futex_tripwire: bool = False,
        on_trip: Optional[Callable[[int, int], None]] = None,
    ):
        self.mesh = mesh
        self.ops = ops
        self.rank = rank
        self.calibrate = calibrate
        self.min_budget_ms = min_budget_ms
        self.on_trip = on_trip
        self._iteration = 0
        self._fired_iteration: Optional[int] = None
        self._lock = threading.Lock()
        self.trip_time: Optional[float] = None
        self.monitor = QuorumMonitor(
            mesh,
            budget_ms=budget_ms,
            interval=interval,
            auto_beat_interval=auto_beat_interval,
            on_stale=self._on_stale,
            use_pallas=use_pallas,
            fetch_workers=fetch_workers,
            native_beat=native_beat,
            # event/futex wait on the local beat stream: a local stamp
            # freeze is observed at wake latency and recorded through the
            # same interruption path, without waiting for a collective round
            futex_tripwire=futex_tripwire,
            identify=True,
            # pre-start calibration can only sample an idle interpreter;
            # after 256 in-vivo healthy ticks under the real workload the
            # budget is recomputed from those samples (see QuorumMonitor)
            online_recalibrate_after=256,
            online_min_budget_ms=min_budget_ms,
        )

    # -- workload API ------------------------------------------------------

    def beat(self) -> None:
        self.monitor.beat()

    def start(self, iteration: int = 0) -> "QuorumTripwire":
        self._iteration = iteration
        self._fired_iteration = None
        if self.calibrate:
            # the idle-calibrated budget is PROVISIONAL: doubled until the
            # online recalibration has seen real-workload ages, because an
            # idle sample undershoots busy-interpreter stamp lateness and
            # a too-tight early budget would fire a spurious restart
            self.monitor.calibrate(min_budget_ms=self.min_budget_ms)
            self.monitor.budget_ms *= 2.0
        self.monitor.start()
        return self

    def set_iteration(self, iteration: int) -> None:
        with self._lock:
            self._iteration = iteration
            self._fired_iteration = None
        # a restarted rank is alive by construction: refresh the stamp and
        # re-arm the liveness beater so the OLD hang's silence doesn't trip
        # the NEW iteration
        self.monitor.resume_auto_beat()

    def stop(self) -> None:
        self.monitor.stop()

    # -- trip path ---------------------------------------------------------

    def _on_stale(self, age_ms: int, device_idx: Optional[int]) -> None:
        with self._lock:
            it = self._iteration
            if self._fired_iteration == it:
                return  # at most one record per iteration from this observer
            self._fired_iteration = it
        stale_rank = device_owner_rank(self.mesh, device_idx)
        self.trip_time = time.monotonic()
        log.error(
            "quorum tripwire: heartbeat stale by %.3fms (device %s, rank %s) "
            "at iteration %s — recording interruption",
            age_ms, device_idx, stale_rank, it,
        )
        record_event(
            ProfilingEvent.HANG_DETECTED,
            source="quorum_tripwire", age_ms=age_ms,
            device=device_idx if device_idx is not None else -1,
            rank=stale_rank, iteration=it,
        )
        try:
            self.ops.record_interruption(
                it,
                InterruptionRecord(
                    rank=stale_rank,
                    interruption=Interruption.QUORUM_STALE,
                    message=f"ICI quorum: heartbeat stale {age_ms:.3f}ms "
                            f"(device {device_idx})",
                    origin_rank=self.rank,
                ),
            )
        except Exception:  # noqa: BLE001 - the tick thread must survive
            log.exception("failed recording quorum interruption")
        if self.on_trip is not None:
            try:
                self.on_trip(age_ms, stale_rank)
            except Exception:  # noqa: BLE001
                log.exception("on_trip callback failed")


def quorum_restart_requester(client, min_interval_s: float = 5.0) -> Callable:
    """In-job-ring glue: returns an ``on_stale``/``on_trip`` callback that
    asks the launcher to restart the cycle via the rank monitor IPC
    (``WorkloadControlRequest(RestartWorkload)``).

    ``client`` is a connected
    :class:`~tpu_resiliency.fault_tolerance.rank_monitor_client.RankMonitorClient`.
    Requests are rate-limited: the launcher needs one signal, not one per
    tick while the stop is in flight.
    """
    from ..fault_tolerance.data import WorkloadAction

    state = {"last": 0.0}
    lock = threading.Lock()

    def on_stale(age_ms, stale=None):
        now = time.monotonic()
        with lock:
            if now - state["last"] < min_interval_s:
                return
            state["last"] = now
        log.error(
            "quorum tripwire: requesting in-job restart (stale %sms, rank %s)",
            age_ms, stale,
        )
        try:
            client.send_workload_control_request(
                WorkloadAction.RestartWorkload,
                reason=f"ICI quorum: heartbeat stale {age_ms:.3f}ms (rank {stale})",
            )
        except Exception:  # noqa: BLE001 - detection must not kill the detector
            log.exception("failed sending quorum restart request")

    return on_stale
