"""Abort plugins: tear down auxiliary engines before a restart.

Reference analog: ``inprocess/abort.py`` — ``AbortTorchDistributed`` aborts
every NCCL backend in parallel threads.  JAX exposes no collective-abort API
(SURVEY.md §7 hard part (a)), and in-flight XLA programs cannot be cancelled
from Python; the design consequence is explicit: the **monitor process's
hard-timeout kill is the backstop** for wedged device programs, and the
in-process Abort stage handles what Python *can* release:

- :class:`AbortCheckpointWorkers` — kill persistent async-ckpt writers
  (reference ``AbortPersistentCheckpointProcesses`` ``:194``).
- :class:`AbortPeerExchange` — close local-ckpt replication sockets.
- :class:`AbortQuorumMonitor` — stop the device-quorum tick thread (it would
  otherwise keep dispatching collectives into a broken mesh).
- :class:`ClearJaxCaches` — drop compiled-executable caches so the next
  iteration re-traces against the new topology when world size changed.
"""

from __future__ import annotations

from ..utils.logging import get_logger

log = get_logger("inproc.abort")


class AbortCheckpointWorkers:
    def __init__(self, *queues):
        self.queues = queues

    def __call__(self, state=None):
        for q in self.queues:
            try:
                q.abort()
            except Exception:  # noqa: BLE001
                log.exception("failed aborting checkpoint queue")
        return state


class AbortPeerExchange:
    def __init__(self, *exchanges):
        self.exchanges = exchanges

    def __call__(self, state=None):
        for ex in self.exchanges:
            try:
                ex.close()
            except Exception:  # noqa: BLE001
                log.exception("failed closing peer exchange")
        return state


class AbortQuorumMonitor:
    def __init__(self, *monitors):
        self.monitors = monitors

    def __call__(self, state=None):
        for m in self.monitors:
            try:
                m.stop()
            except Exception:  # noqa: BLE001
                log.exception("failed stopping quorum monitor")
        return state


class ClearJaxCaches:
    def __call__(self, state=None):
        try:
            import jax

            jax.clear_caches()
        except Exception:  # noqa: BLE001
            log.exception("jax.clear_caches failed")
        return state
