"""The staged abort ladder: ordered, measured teardown before a restart.

Reference analog: ``inprocess/abort.py`` — ``AbortTorchDistributed`` aborts
every NCCL backend in parallel threads.  JAX exposes no collective-abort
API (SURVEY.md §7 hard part (a)) and in-flight XLA programs cannot be
cancelled from Python, so recovery here is a *degradation ladder* selected
at fault time from the cheapest viable tier (the Chameleon argument,
PAPERS.md): each rung is an :class:`AbortStage` with its own deadline and a
recorded outcome, and the monitor process's hard-timeout kill remains the
backstop below the bottom rung.

Stage outcomes (telemetry ``tpurx_abort_stage_outcomes_total{stage,outcome}``):

- ``released``  — the stage freed its resources within its deadline;
- ``timed_out`` — the stage was still blocked at its deadline (its worker
  thread is abandoned; the monitor-kill backstop covers whatever it held);
- ``failed``    — the stage raised (logged, ladder continues);
- ``escalate``  — the stage determined in-process recovery cannot proceed
  (``EscalateAbort``); remaining rungs are skipped and the fault falls
  through to the monitor-kill → launcher ring;
- ``skipped``   — gated off (``applicable()`` false, or after an escalate).

Built-in rungs:

- :class:`FingerprintStage` — publish this rank's dispatch-tail fingerprint
  (last K dispatched device programs + ages) to the store for attribution —
  the at-abort analog of the reference's Flight-Recorder dump
  (``abort.py:127-160``).  Always first: later rungs may block.
- :class:`AbortCheckpointWorkers` — kill persistent async-ckpt writers
  (reference ``AbortPersistentCheckpointProcesses`` ``:194``).
- :class:`AbortPeerExchange` — close local-ckpt replication sockets.
- :class:`AbortQuorumMonitor` — stop the device-quorum tick thread (it would
  otherwise keep dispatching collectives into a broken mesh).
- :class:`ShrinkMeshStage` — **opt-in, measured**: tear down the
  ``jax.distributed`` client in-process so the next iteration can re-init
  over the surviving hosts (see ``benchmarks/mesh_shrink_experiment.py``
  and the per-JAX-version result matrix in ``docs/inprocess.md``).  A
  wedged runtime can block the shutdown past any Python control — hence
  the hard per-stage deadline with automatic fallback to the backstop.
- :class:`ClearJaxCaches` — drop compiled-executable caches so the next
  iteration re-traces against the new topology when world size changed.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional

from ..telemetry import counter, flight, histogram
from ..utils import env
from ..utils.logging import get_logger

log = get_logger("inproc.abort")

EV_LADDER = flight.declare_event("abort.ladder", "name")
EV_STAGE = flight.declare_event("abort.stage", "stage", "outcome", "dur_ms")

_STAGE_OUTCOMES = counter(
    "tpurx_abort_stage_outcomes_total",
    "Abort-ladder stage outcomes per restart",
    labels=("stage", "outcome"),
)
_STAGE_NS = histogram(
    "tpurx_abort_stage_latency_ns",
    "Abort-ladder per-stage wall time",
    labels=("stage",),
)
_LADDER_RUNS = counter(
    "tpurx_abort_ladder_runs_total", "Abort-ladder executions"
)


class EscalateAbort(Exception):
    """Raised by a stage to declare in-process recovery non-viable; the
    ladder stops and the fault falls through to the monitor-kill backstop."""


RELEASED = "released"
TIMED_OUT = "timed_out"
FAILED = "failed"
ESCALATE = "escalate"
SKIPPED = "skipped"


@dataclasses.dataclass
class StageResult:
    stage: str
    outcome: str
    duration_ms: float
    detail: str = ""

    def brief(self) -> str:
        return f"{self.stage}={self.outcome}({self.duration_ms:.1f}ms)"


class AbortStage:
    """One rung of the ladder.  Subclasses override :meth:`release` (and
    optionally :meth:`applicable`).  Stages stay plain callables too, so a
    bare stage still composes with ``Compose`` and the ``abort=`` plugin
    slot exactly like the pre-ladder classes did."""

    name = "stage"
    timeout: float = 5.0

    def __init__(self, timeout: Optional[float] = None):
        if timeout is not None:
            self.timeout = timeout

    def applicable(self, state=None) -> bool:
        return True

    def release(self, state=None) -> Optional[str]:
        """Free resources; return an optional human detail string."""
        raise NotImplementedError

    def __call__(self, state=None):
        self.release(state)
        return state

    def __repr__(self) -> str:
        return f"{type(self).__name__}(name={self.name!r}, timeout={self.timeout})"


class FnStage(AbortStage):
    """Adapter wrapping a plain ``fn(state)`` plugin as a ladder rung."""

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 timeout: Optional[float] = None):
        super().__init__(timeout)
        self.fn = fn
        self.name = name or getattr(fn, "__name__", None) or type(fn).__name__

    def release(self, state=None) -> Optional[str]:
        self.fn(state)
        return None


def as_stage(obj, timeout: Optional[float] = None) -> AbortStage:
    if isinstance(obj, AbortStage):
        return obj
    return FnStage(obj, timeout=timeout)


class AbortLadder:
    """Ordered, per-stage-deadlined abort pipeline with recorded outcomes.

    Plugin-compatible: pass an instance as ``Wrapper(abort=...)``.  Each
    stage runs in a worker thread joined at its deadline — Python cannot
    cancel the thread, so a timed-out stage is *abandoned* (outcome
    recorded; the monitor-kill backstop owns whatever it was holding) and
    the ladder proceeds to the next rung.  ``last_results`` keeps the most
    recent run for the restart loop's telemetry/logging.
    """

    def __init__(self, *stages, name: str = "abort"):
        flat: List[AbortStage] = []
        for s in stages:
            # a Compose chain contributed as one argument flattens into rungs
            inner = getattr(s, "fns", None)
            if inner is not None and not isinstance(s, AbortStage):
                flat.extend(as_stage(f) for f in inner)
            else:
                flat.append(as_stage(s))
        self.stages = flat
        self.name = name
        self.last_results: List[StageResult] = []
        self._lock = threading.Lock()

    def _run_stage(self, stage: AbortStage, state) -> StageResult:
        box = {}

        def body():
            try:
                box["detail"] = stage.release(state) or ""
            except EscalateAbort as exc:
                box["escalate"] = str(exc)
            except BaseException as exc:  # noqa: BLE001 - recorded, not fatal
                box["error"] = exc

        t0 = time.monotonic_ns()
        worker = threading.Thread(
            target=body, name=f"tpurx-abort-{stage.name}", daemon=True
        )
        worker.start()
        worker.join(timeout=stage.timeout)
        dur_ms = (time.monotonic_ns() - t0) / 1e6
        if worker.is_alive():
            return StageResult(stage.name, TIMED_OUT, dur_ms,
                               f"still blocked at {stage.timeout}s deadline")
        if "escalate" in box:
            return StageResult(stage.name, ESCALATE, dur_ms, box["escalate"])
        if "error" in box:
            log.error("abort stage %s failed: %r", stage.name, box["error"])
            return StageResult(stage.name, FAILED, dur_ms, repr(box["error"]))
        return StageResult(stage.name, RELEASED, dur_ms, box.get("detail", ""))

    def __call__(self, state=None):
        with self._lock:  # one abort episode at a time per wrapper
            _LADDER_RUNS.inc()
            flight.record(EV_LADDER, self.name)
            # entering the ladder: mark the live episode's abort phase (the
            # degrade ladder runs outside any episode — phase() is a no-op
            # guarded by the episode's own lifecycle) and drop a black box
            # before teardown overwrites the pre-fault ring tail
            from ..telemetry import episode as episode_mod

            ep = episode_mod.current()
            if ep is not None:
                ep.phase("abort")
            flight.dump("abort_ladder")
            results: List[StageResult] = []
            escalated = False
            for stage in self.stages:
                t0 = time.monotonic_ns()
                if escalated or not self._applicable(stage, state):
                    res = StageResult(stage.name, SKIPPED, 0.0,
                                      "after escalate" if escalated else "gated off")
                else:
                    res = self._run_stage(stage, state)
                    _STAGE_NS.labels(stage.name).observe(
                        time.monotonic_ns() - t0
                    )
                    if res.outcome == ESCALATE:
                        escalated = True
                _STAGE_OUTCOMES.labels(stage.name, res.outcome).inc()
                flight.record(
                    EV_STAGE, stage.name, res.outcome,
                    round(res.duration_ms, 3),
                )
                results.append(res)
            self.last_results = results
            log.warning("abort ladder: %s", self.summary(results))
            return state

    @staticmethod
    def _applicable(stage: AbortStage, state) -> bool:
        try:
            return bool(stage.applicable(state))
        except Exception:  # noqa: BLE001 - a broken gate must not stall the ladder
            log.exception("abort stage %s applicable() failed; running it",
                          stage.name)
            return True

    def take_results(self) -> List[StageResult]:
        """Drain the latest run's results exactly once (blocks until an
        in-flight run finishes — bounded by the stages' own deadlines)."""
        with self._lock:
            out, self.last_results = self.last_results, []
            return out

    def summary(self, results: Optional[List[StageResult]] = None) -> str:
        results = self.last_results if results is None else results
        return " ".join(r.brief() for r in results) or "(empty)"

    def __repr__(self) -> str:
        return f"AbortLadder({', '.join(s.name for s in self.stages)})"


# -- built-in rungs ---------------------------------------------------------


class FingerprintStage(AbortStage):
    """Publish this rank's dispatch-tail fingerprint to the store so the
    trace analyzer can name the in-flight collective and the lagging rank
    (reference: FR dump at abort, ``abort.py:127-160``)."""

    name = "fingerprint"
    timeout = 2.0

    def __init__(self, ops=None, rank: Optional[int] = None,
                 iteration_fn: Optional[Callable[[], int]] = None,
                 timeout: Optional[float] = None):
        super().__init__(timeout)
        self.ops = ops
        self.rank = rank
        self.iteration_fn = iteration_fn

    def applicable(self, state=None) -> bool:
        return self.ops is not None and self.rank is not None

    def release(self, state=None) -> Optional[str]:
        from .fingerprint import snapshot_tail

        tail = snapshot_tail()
        iteration = (
            self.iteration_fn() if self.iteration_fn is not None
            else getattr(state, "iteration", 0) or 0
        )
        self.ops.record_fingerprint(iteration, self.rank, tail)
        return f"{len(tail)} entries"


class AbortCheckpointWorkers(AbortStage):
    name = "ckpt_workers"
    timeout = 10.0

    def __init__(self, *queues, timeout: Optional[float] = None):
        super().__init__(timeout)
        self.queues = queues

    def release(self, state=None) -> Optional[str]:
        n = 0
        for q in self.queues:
            try:
                q.abort()
                n += 1
            except Exception:  # noqa: BLE001
                log.exception("failed aborting checkpoint queue")
        return f"{n}/{len(self.queues)} queues"


class AbortPeerExchange(AbortStage):
    name = "peer_exchange"
    timeout = 5.0

    def __init__(self, *exchanges, timeout: Optional[float] = None):
        super().__init__(timeout)
        self.exchanges = exchanges

    def release(self, state=None) -> Optional[str]:
        n = 0
        for ex in self.exchanges:
            try:
                ex.close()
                n += 1
            except Exception:  # noqa: BLE001
                log.exception("failed closing peer exchange")
        return f"{n}/{len(self.exchanges)} exchanges"


class AbortQuorumMonitor(AbortStage):
    name = "quorum_monitor"
    timeout = 8.0

    def __init__(self, *monitors, timeout: Optional[float] = None):
        super().__init__(timeout)
        self.monitors = monitors

    def release(self, state=None) -> Optional[str]:
        n = 0
        for m in self.monitors:
            try:
                m.stop()
                n += 1
            except Exception:  # noqa: BLE001
                log.exception("failed stopping quorum monitor")
        return f"{n}/{len(self.monitors)} monitors"


class ShrinkMeshStage(AbortStage):
    """Opt-in, measured in-process mesh-shrink (SURVEY §7(a)).

    Tears down the ``jax.distributed`` client and compiled caches *inside
    the process* so the next restart iteration can re-init at the surviving
    world size without a respawn.  Whether the re-init half actually works
    is a per-JAX-version property — measured by
    ``benchmarks/mesh_shrink_experiment.py`` and recorded in
    ``docs/inprocess.md`` — so this rung is gated:

    - opt-in via constructor or ``TPURX_SHRINK_MESH=1``;
    - a hard ``timeout`` (a wedged runtime can block ``shutdown()`` in C++
      past any Python control) after which the outcome records
      ``timed_out`` and the fault falls through to the monitor-kill
      backstop — the ladder's automatic fallback, exercised by
      ``tests/test_layered_restart.py``.
    """

    name = "shrink_mesh"
    timeout = 20.0

    def __init__(self, enabled: Optional[bool] = None,
                 timeout: Optional[float] = None):
        super().__init__(timeout)
        if enabled is None:
            enabled = env.SHRINK_MESH.get()
        self.enabled = enabled

    def applicable(self, state=None) -> bool:
        return self.enabled

    def release(self, state=None) -> Optional[str]:
        import jax
        from jax._src import distributed as jax_dist

        detail = []
        state_obj = getattr(jax_dist, "global_state", None)
        initialized = (
            state_obj is not None
            and getattr(state_obj, "client", None) is not None
        )
        if initialized:
            jax.distributed.shutdown()
            detail.append("distributed client shut down")
        else:
            detail.append("no distributed client")
        jax.clear_caches()
        # the full reset (measured by benchmarks/mesh_shrink_experiment.py):
        # clearing compiled caches is NOT enough — jax.distributed refuses
        # re-init while backends are live, so the backends must go too
        try:
            import jax.extend.backend as jeb  # lazy submodule

            jeb.clear_backends()
            detail.append("caches+backends cleared")
        except Exception as exc:  # noqa: BLE001 - version-dependent API
            detail.append(f"caches cleared (clear_backends: {exc!r})")
        # reset the bootstrap helper so the next iteration's initialize
        # plugin may re-init at the surviving world size
        try:
            from ..parallel import distributed as dist_mod

            dist_mod._initialized = False
        except (ImportError, AttributeError):
            pass  # helper is optional
        return "; ".join(detail)


class ClearJaxCaches(AbortStage):
    name = "jax_caches"
    timeout = 5.0

    def release(self, state=None) -> Optional[str]:
        import jax

        jax.clear_caches()
        return None


class DegradeToShrink:
    """Targeted mesh-shrink entry point for the collective degrade ladder.

    The self-healing collective layer (``parallel/degrade.py``) reaches its
    bottom rung when retry and re-layout both failed: the implicated link
    needs the real teardown — distributed client + backends — that
    :class:`ShrinkMeshStage` owns.  This hook runs *only* the shrink rung
    (plus any stages the caller composed into ``ladder``), through the
    ladder machinery so the stage deadline / abandoned-worker / outcome
    accounting applies — a single collective's route is rebuilt without
    tripping the full restart ladder or the pod.

    The in-process :class:`~tpu_resiliency.inprocess.wrap.Wrapper` installs
    one bound to a dedicated shrink-only ladder at build time
    (:func:`install_degrade_hook`); standalone processes get a bare
    fallback from ``parallel/degrade.py``.
    """

    def __init__(self, ladder: AbortLadder):
        self.ladder = ladder
        self.trips = 0

    def __call__(self, op: str = "", axis: str = "",
                 culprits: tuple = ()) -> str:
        self.trips += 1
        log.warning(
            "degrade-to-shrink: op=%s axis=%s culprits=%s — running "
            "targeted shrink rung", op or "?", axis or "?", list(culprits),
        )
        self.ladder(None)
        return self.ladder.summary()


_degrade_hook: Optional[DegradeToShrink] = None
_degrade_hook_lock = threading.Lock()


def install_degrade_hook(hook: Optional[DegradeToShrink]) -> None:
    """Publish the process's targeted-shrink hook (``None`` uninstalls).
    Latest install wins: the hook belongs to the live wrapper."""
    global _degrade_hook
    with _degrade_hook_lock:
        _degrade_hook = hook


def get_degrade_hook() -> Optional[DegradeToShrink]:
    with _degrade_hook_lock:
        return _degrade_hook


def default_ladder(ops=None, rank: Optional[int] = None,
                   iteration_fn: Optional[Callable[[], int]] = None,
                   *extra_stages) -> AbortLadder:
    """The standard rung order: fingerprint first (later rungs may block),
    engine teardown, opt-in mesh-shrink, cache clear."""
    return AbortLadder(
        FingerprintStage(ops, rank, iteration_fn),
        *extra_stages,
        ShrinkMeshStage(),
        ClearJaxCaches(),
    )


def evacuation_ladder(victim_rank: int, rank: Optional[int] = None,
                      *extra_stages) -> Optional[AbortLadder]:
    """Victim-scoped teardown for a policy-driven evacuation.

    Unlike the reactive ``default_ladder`` (which every rank walks after a
    fault fired), an evacuation tears down ONE predicted-to-fail rank
    while the survivors keep training: only the victim gets a ladder —
    mesh-shrink force-enabled (evacuation IS a planned shrink; the opt-in
    gate guards the measured-risk reactive path, not a deliberate
    decision) plus whatever engine-teardown stages the caller composes in.
    Every other rank gets ``None`` and must not run anything.
    """
    if rank is None:
        rank = env.RANK.get()
    if rank != victim_rank:
        return None
    return AbortLadder(
        *extra_stages,
        ShrinkMeshStage(enabled=True),
        ClearJaxCaches(),
        name="evacuate",
    )
