"""Exception types for the in-process restart protocol."""


class RankShouldRestart(BaseException):
    """Asynchronously raised into the main thread to interrupt the wrapped
    function (reference ``monitor_thread.py`` async raise).  Derives from
    BaseException so generic ``except Exception`` handlers in user training
    loops cannot swallow a restart."""


class RestartAbort(BaseException):
    """Unrecoverable condition: leave the restart loop entirely."""


class HealthCheckError(Exception):
    """Raised by health-check plugins; marks this rank unfit to continue."""
