"""Monitor thread: trip on any-rank interruption and restart the main thread.

Capability parity with ``inprocess/monitor_thread.py:58-213``: a daemon
thread per iteration that blocks on the iteration's interruption-log key; on
a record appearing it

1. waits ``last_call_wait`` so concurrent faults on other ranks coalesce into
   one restart (reference ``wrap.py:162`` semantics),
2. runs the Abort plugin (cancel aux engines — the JAX analog of NCCL abort),
3. asynchronously raises :class:`RankShouldRestart` into the main thread via
   ``PyThreadState_SetAsyncExc``, repeatedly, until the wrapper catches it
   (the raise only lands at a bytecode boundary; a long device wait delays
   it, which is why the monitor *process* holds the hard-kill backstop).
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Callable, Optional

from ..utils.logging import get_logger
from .exceptions import RankShouldRestart
from .store_ops import InprocStore

log = get_logger("monitor_thread")


def async_raise(tid: int, exc_type: type) -> None:
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exc_type)
    )
    if res > 1:  # pragma: no cover
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)


class MonitorThread:
    def __init__(
        self,
        ops: InprocStore,
        iteration: int,
        main_tid: int,
        abort_fn: Optional[Callable] = None,
        last_call_wait: float = 0.2,
        poll_interval: float = 1.0,
        on_trip: Optional[Callable] = None,
    ):
        self.ops = ops.__class__(ops.store.clone(), ops.ns.split("/", 1)[1])
        self.iteration = iteration
        self.main_tid = main_tid
        self.abort_fn = abort_fn
        self.last_call_wait = last_call_wait
        self.poll_interval = poll_interval
        self.on_trip = on_trip
        self._stop = threading.Event()
        self._caught = threading.Event()
        self.tripped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"tpurx-inproc-monitor-thread-{iteration}", daemon=True
        )

    def start(self) -> "MonitorThread":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.ops.wait_any_interruption(self.iteration, timeout=self.poll_interval):
                break
        if self._stop.is_set():
            return
        # coalesce concurrent faults
        time.sleep(self.last_call_wait)
        records = self.ops.get_interruptions(self.iteration)
        log.warning(
            "iteration %s interrupted: %s",
            self.iteration,
            [(r.rank, r.interruption.value) for r in records],
        )
        self.tripped.set()
        if self.on_trip:
            try:
                self.on_trip()
            except Exception:  # noqa: BLE001
                log.exception("on_trip callback failed")
        if self.abort_fn is not None:
            try:
                self.abort_fn()
            except Exception:  # noqa: BLE001
                log.exception("abort plugin failed")
        # raise into the main thread until the wrapper acknowledges — first
        # raise immediately (a 0.5s pre-wait would put a flat half-second on
        # every detect->restart latency), then re-raise every 0.5s (fixed
        # interval) in case the raise landed somewhere it couldn't propagate.
        # A rank already in its own fault handler has mark_caught()-ed:
        # never raise into it.
        while not self._caught.is_set() and not self._stop.is_set():
            async_raise(self.main_tid, RankShouldRestart)
            if self._caught.wait(timeout=0.5):
                return

    def mark_caught(self) -> None:
        """Called by the wrapper once RankShouldRestart reached its handler."""
        self._caught.set()

    def stop(self) -> None:
        self._stop.set()
        self._caught.set()
        self._thread.join(timeout=5)
        self.ops.store.close()
