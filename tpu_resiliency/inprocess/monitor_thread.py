"""Monitor thread: trip on any-rank interruption and restart the main thread.

Capability parity with ``inprocess/monitor_thread.py:58-213``: a daemon
thread per iteration that blocks on the iteration's interruption-log key; on
a record appearing it

1. waits ``last_call_wait`` so concurrent faults on other ranks coalesce into
   one restart (reference ``wrap.py:162`` semantics),
2. runs the Abort plugin (cancel aux engines — the JAX analog of NCCL abort),
3. asynchronously raises :class:`RankShouldRestart` into the main thread via
   ``PyThreadState_SetAsyncExc``, repeatedly, until the wrapper catches it
   (the raise only lands at a bytecode boundary; a long device wait delays
   it, which is why the monitor *process* holds the hard-kill backstop).
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Callable, Optional

from ..telemetry import counter, flight, histogram
from ..utils.logging import get_logger
from .exceptions import RankShouldRestart
from .store_ops import InprocStore

log = get_logger("monitor_thread")

EV_TRIP = flight.declare_event("monitor.trip", "iteration", "interruptions")

_TRIPS = counter(
    "tpurx_monitor_trips_total",
    "Monitor-thread trips (any-rank interruption observed)",
)
_TRIP_TO_CAUGHT_NS = histogram(
    "tpurx_monitor_trip_to_caught_ns",
    "Interruption observed to RankShouldRestart acknowledged by the wrapper",
)


def cancel_async_raise(tid: int) -> None:
    """Clear ``tid``'s single-slot pending async exception (NULL cancel)."""
    ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)


def quiesce_with_retry(monitor: "MonitorThread") -> None:
    """Run ``monitor.quiesce_raises()`` under the caller-side absorbing retry
    its contract requires (the call bytecodes reaching it are delivery
    points).  Convergence is guaranteed: every pass either completes or
    absorbed a delivery, re-raises are spaced >=0.5s apart, and once
    ``mark_caught`` completes no new raise can be scheduled — so the loop is
    unbounded rather than capped (a capped loop that exhausts would fall
    through with the slot still live, silently reintroducing the race)."""
    while True:
        try:
            monitor.quiesce_raises()
            return
        except RankShouldRestart:
            continue


def async_raise(tid: int, exc_type: type) -> None:
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exc_type)
    )
    if res > 1:  # pragma: no cover
        cancel_async_raise(tid)


class MonitorThread:
    def __init__(
        self,
        ops: InprocStore,
        iteration: int,
        main_tid: int,
        abort_fn: Optional[Callable] = None,
        last_call_wait: float = 0.2,
        poll_interval: float = 1.0,
        on_trip: Optional[Callable] = None,
    ):
        self.ops = ops.__class__(ops.store.clone(), ops.ns.split("/", 1)[1])
        self.iteration = iteration
        self.main_tid = main_tid
        self.abort_fn = abort_fn
        self.last_call_wait = last_call_wait
        self.poll_interval = poll_interval
        self.on_trip = on_trip
        self._stop = threading.Event()
        self._caught = threading.Event()
        # makes check-_caught + async_raise atomic vs mark_caught: once
        # mark_caught returns, no FURTHER raise can be scheduled (at most one
        # already-scheduled raise sits undelivered in the thread's single
        # async-exc slot — quiesce_raises() cancels that one)
        self._raise_lock = threading.Lock()
        self._trip_ns: Optional[int] = None
        self.tripped = threading.Event()
        # set once the abort ladder/plugin has RUN (tripped only means the
        # trip was observed — with staged abort the duties take real time,
        # and the wrapper must not tear the monitor down under them)
        self.abort_done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"tpurx-inproc-monitor-thread-{iteration}", daemon=True
        )

    def start(self) -> "MonitorThread":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.ops.wait_any_interruption(self.iteration, timeout=self.poll_interval):
                break
        if self._stop.is_set():
            return
        # coalesce concurrent faults
        time.sleep(self.last_call_wait)
        records = self.ops.get_interruptions(self.iteration)
        log.warning(
            "iteration %s interrupted: %s",
            self.iteration,
            [(r.rank, r.interruption.value) for r in records],
        )
        _TRIPS.inc()
        flight.record(
            EV_TRIP, self.iteration,
            ",".join(f"{r.rank}:{r.interruption.value}" for r in records),
        )
        self._trip_ns = time.monotonic_ns()
        self.tripped.set()
        if self.on_trip:
            try:
                self.on_trip()
            except Exception:  # noqa: BLE001
                log.exception("on_trip callback failed")
        if self.abort_fn is not None:
            try:
                self.abort_fn()
            except Exception:  # noqa: BLE001
                log.exception("abort plugin failed")
        self.abort_done.set()
        # raise into the main thread until the wrapper acknowledges — first
        # raise immediately (a 0.5s pre-wait would put a flat half-second on
        # every detect->restart latency), then re-raise every 0.5s (fixed
        # interval) in case the raise landed somewhere it couldn't propagate.
        # A rank already in its own fault handler has mark_caught()-ed:
        # never raise into it.
        while not self._stop.is_set():
            with self._raise_lock:
                if self._caught.is_set():
                    return
                async_raise(self.main_tid, RankShouldRestart)
            if self._caught.wait(timeout=0.5):
                return

    def mark_caught(self) -> None:
        """Called by the wrapper once RankShouldRestart reached its handler.

        Acquiring the raise lock bounds the wait on an in-progress
        check-and-raise; on return no further raise will be scheduled."""
        with self._raise_lock:
            self._caught.set()
            trip_ns, self._trip_ns = self._trip_ns, None
        if trip_ns is not None:
            _TRIP_TO_CAUGHT_NS.observe(time.monotonic_ns() - trip_ns)

    def quiesce_raises(self) -> None:
        """Deterministically absorb any async raise still in flight.

        MUST be called from the monitored (main) thread.  After
        :meth:`mark_caught`, exactly one hazard remains: a raise scheduled
        *before* the lock was taken that the interpreter has not yet
        delivered.  ``PyThreadState_SetAsyncExc(tid, NULL)`` cancels that
        single-slot pending exception; delivery can still slip in at a
        bytecode boundary *before* the cancel executes, so absorb and retry.
        Two passes suffice (the slot holds at most one exception and no new
        raises are possible); loop a third for margin.

        The entry bytecodes of this method (and the CALL that reaches it)
        are delivery points too, so callers must wrap the call itself in an
        ``except RankShouldRestart: retry`` loop — after one clean return
        the slot is provably empty.  Replaces the old timed
        ``time.sleep(0.05)`` drain, which raced delivery under load
        (VERDICT r4 weak #4)."""
        if threading.get_ident() != self.main_tid:
            # hard error (not assert — -O must not strip it): a cancel from
            # another thread races delivery in the monitored thread and
            # silently reintroduces the timed-drain race
            raise RuntimeError("quiesce_raises must run on the monitored thread")
        self.mark_caught()
        while True:
            try:
                cancel_async_raise(self.main_tid)
                return
            except RankShouldRestart:
                continue

    def stop(self) -> None:
        self._stop.set()
        self.mark_caught()
        self.abort_done.set()  # unblock waiters on a never-tripped monitor
        self._thread.join(timeout=5)
        self.ops.store.close()
