"""Rank (re)assignment policies after faults.

Capability parity with ``inprocess/rank_assignment.py:46-1022``: pure policy
objects computing each surviving rank's new (active_rank, active_world_size,
mode) given the terminated set.  Policies chain with
:class:`tpu_resiliency.inprocess.compose.Compose`.

- :class:`ActivateAllRanks` — everyone alive is ACTIVE (``:126``).
- :class:`MaxActiveWorldSize` — cap actives; the rest park INACTIVE (``:149``).
- :class:`ActiveWorldSizeDivisibleBy` — keep active count a multiple of N
  (TPU: N = chips per slice keeps the mesh shape legal) (``:198``).
- :class:`FillGaps` — dead ranks' slots are back-filled by the highest
  surviving ranks; survivors otherwise keep their rank (``:786``).
- :class:`ShiftRanks` — survivors shift down preserving order (``:843``).
- :class:`ActivateWholeGroups` — only complete topology groups stay active
  (``FilterCountGroupedByKey`` ``:900`` / ``Tree`` layers ``:416-520``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Set

from .exceptions import RestartAbort
from .state import Mode, State


@dataclasses.dataclass
class RankAssignmentCtx:
    state: State
    terminated_ranks: Set[int]


class RankAssignment:
    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        raise NotImplementedError


class RankDiscontinued(RestartAbort):
    """This rank has no seat anymore (it was terminated)."""


def _surviving(ctx: RankAssignmentCtx) -> List[int]:
    world = ctx.state.initial_world_size
    return [r for r in range(world) if r not in ctx.terminated_ranks]


class ShiftRanks(RankAssignment):
    """Survivors are re-numbered 0..n-1 preserving order."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if state.initial_rank in ctx.terminated_ranks:
            raise RankDiscontinued(f"rank {state.initial_rank} terminated")
        survivors = _surviving(ctx)
        state.rank = survivors.index(state.initial_rank)
        state.world_size = len(survivors)
        state.active_rank = state.rank
        state.active_world_size = state.world_size
        state.mode = Mode.ACTIVE
        return ctx


class FillGaps(RankAssignment):
    """Dead slots are filled by the highest-numbered survivors; everyone else
    keeps their rank (minimizes re-sharding movement)."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if state.initial_rank in ctx.terminated_ranks:
            raise RankDiscontinued(f"rank {state.initial_rank} terminated")
        survivors = _surviving(ctx)
        new_world = len(survivors)
        gaps = sorted(r for r in ctx.terminated_ranks if r < new_world)
        movers = sorted((r for r in survivors if r >= new_world))
        mapping = dict(zip(movers, gaps))
        new_rank = mapping.get(state.initial_rank, state.initial_rank)
        state.rank = new_rank
        state.world_size = new_world
        state.active_rank = new_rank
        state.active_world_size = new_world
        state.mode = Mode.ACTIVE
        return ctx


class ActivateAllRanks(RankAssignment):
    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        state.active_rank = state.rank
        state.active_world_size = state.world_size
        state.mode = Mode.ACTIVE
        return ctx


class MaxActiveWorldSize(RankAssignment):
    """First ``max_active`` ranks run; the rest are INACTIVE hot spares that
    re-enter on the next restart if an active rank dies."""

    def __init__(self, max_active: Optional[int] = None):
        self.max_active = max_active

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        cap = self.max_active if self.max_active is not None else state.world_size
        cap = min(cap, state.world_size)
        if state.rank < cap:
            state.active_rank = state.rank
            state.active_world_size = cap
            state.mode = Mode.ACTIVE
        else:
            state.active_rank = None
            state.active_world_size = cap
            state.mode = Mode.INACTIVE
        return ctx


class ActivateWholeGroups(RankAssignment):
    """Keep only COMPLETE topology groups active.

    Reference analogs: ``FilterCountGroupedByKey`` (``:900``) and the ``Tree``
    layers (``:416-520``) — on TPU a partial host or slice cannot form a
    legal device mesh, so after failures only groups with every member
    surviving may stay active.  ``key_of_rank`` maps an initial rank to its
    group (e.g. ``lambda r: r // 4`` for 4 chips per host); survivors in
    complete groups are renumbered contiguously group-major; survivors in
    broken groups park INACTIVE (ready to back-fill after the next fault).
    """

    def __init__(self, key_of_rank, group_size: int, min_groups: int = 1):
        self.key_of_rank = key_of_rank
        self.group_size = group_size
        self.min_groups = min_groups

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if state.initial_rank in ctx.terminated_ranks:
            raise RankDiscontinued(f"rank {state.initial_rank} terminated")
        survivors = _surviving(ctx)
        groups: dict = {}
        for r in survivors:
            groups.setdefault(self.key_of_rank(r), []).append(r)
        complete = {
            k: sorted(members)
            for k, members in groups.items()
            if len(members) == self.group_size
        }
        if len(complete) < self.min_groups:
            raise RestartAbort(
                f"only {len(complete)} complete groups < min_groups {self.min_groups}"
            )
        ordered: List[int] = []
        for k in sorted(complete, key=lambda k: complete[k][0]):
            ordered.extend(complete[k])
        # unique renumbering across ALL survivors: actives take 0..n_active-1
        # (group-major), parked survivors continue after them — two live
        # processes must never share a state.rank
        parked = [r for r in survivors if r not in ordered]
        numbering = {r: i for i, r in enumerate(ordered + parked)}
        state.world_size = len(survivors)
        state.rank = numbering[state.initial_rank]
        if state.initial_rank in numbering and state.rank < len(ordered):
            state.active_rank = state.rank
            state.active_world_size = len(ordered)
            state.mode = Mode.ACTIVE
        else:
            state.active_rank = None
            state.active_world_size = len(ordered)
            state.mode = Mode.INACTIVE
        return ctx


class ActiveWorldSizeDivisibleBy(RankAssignment):
    """Largest active world size divisible by ``divisor`` (e.g. hosts per
    slice / chips per host, so the device mesh stays rectangular)."""

    def __init__(self, divisor: int):
        if divisor < 1:
            raise ValueError("divisor must be >= 1")
        self.divisor = divisor

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        cap = (state.world_size // self.divisor) * self.divisor
        if cap == 0:
            raise RestartAbort(
                f"world size {state.world_size} < divisor {self.divisor}"
            )
        if state.rank < cap:
            state.active_rank = state.rank
            state.active_world_size = cap
            state.mode = Mode.ACTIVE
        else:
            state.active_rank = None
            state.active_world_size = cap
            state.mode = Mode.INACTIVE
        return ctx
