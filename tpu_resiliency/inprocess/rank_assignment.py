"""Rank (re)assignment policies after faults.

Capability parity with ``inprocess/rank_assignment.py:46-1022``: pure policy
objects computing each surviving rank's new (active_rank, active_world_size,
mode) given the terminated set.  Policies chain with
:class:`tpu_resiliency.inprocess.compose.Compose`.

- :class:`ActivateAllRanks` — everyone alive is ACTIVE (``:126``).
- :class:`MaxActiveWorldSize` — cap actives; the rest park INACTIVE (``:149``).
- :class:`ActiveWorldSizeDivisibleBy` — keep active count a multiple of N
  (TPU: N = chips per slice keeps the mesh shape legal) (``:198``).
- :class:`FillGaps` — dead ranks' slots are back-filled by the highest
  surviving ranks; survivors otherwise keep their rank (``:786``).
- :class:`ShiftRanks` — survivors shift down preserving order (``:843``).
- :class:`ActivateWholeGroups` — only complete topology groups stay active
  (``FilterCountGroupedByKey`` ``:900`` / ``Tree`` layers ``:416-520``).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, Hashable, List, Optional, Sequence, Set, Union

from .exceptions import RestartAbort
from .state import Mode, State


@dataclasses.dataclass
class RankAssignmentCtx:
    """``terminated_ranks`` is the store's cumulative termination record.

    When it is an ordered sequence (``InprocStore.terminated_ranks()``
    append-log order), stateful policies replay it event-by-event so that
    every rank — regardless of how reads batch the events — computes the
    same assignment.  Stateless policies only test membership and accept
    any iterable."""

    state: State
    terminated_ranks: Union[Sequence[int], Set[int]]


class RankAssignment:
    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        raise NotImplementedError


class RankDiscontinued(RestartAbort):
    """This rank has no seat anymore (it was terminated)."""


def _surviving(ctx: RankAssignmentCtx) -> List[int]:
    world = ctx.state.initial_world_size
    return [r for r in range(world) if r not in ctx.terminated_ranks]


class ShiftRanks(RankAssignment):
    """Survivors are re-numbered 0..n-1 preserving order."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if state.initial_rank in ctx.terminated_ranks:
            raise RankDiscontinued(f"rank {state.initial_rank} terminated")
        survivors = _surviving(ctx)
        state.rank = survivors.index(state.initial_rank)
        state.world_size = len(survivors)
        state.active_rank = state.rank
        state.active_world_size = state.world_size
        state.mode = Mode.ACTIVE
        return ctx


class FillGaps(RankAssignment):
    """Dead slots are filled by the highest-numbered survivors; everyone else
    keeps their rank (minimizes re-sharding movement)."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if state.initial_rank in ctx.terminated_ranks:
            raise RankDiscontinued(f"rank {state.initial_rank} terminated")
        survivors = _surviving(ctx)
        new_world = len(survivors)
        gaps = sorted(r for r in ctx.terminated_ranks if r < new_world)
        movers = sorted((r for r in survivors if r >= new_world))
        mapping = dict(zip(movers, gaps))
        new_rank = mapping.get(state.initial_rank, state.initial_rank)
        state.rank = new_rank
        state.world_size = new_world
        state.active_rank = new_rank
        state.active_world_size = new_world
        state.mode = Mode.ACTIVE
        return ctx


class ActivateAllRanks(RankAssignment):
    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        state.active_rank = state.rank
        state.active_world_size = state.world_size
        state.mode = Mode.ACTIVE
        return ctx


class MaxActiveWorldSize(RankAssignment):
    """First ``max_active`` ranks run; the rest are INACTIVE hot spares that
    re-enter on the next restart if an active rank dies."""

    def __init__(self, max_active: Optional[int] = None):
        self.max_active = max_active

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        cap = self.max_active if self.max_active is not None else state.world_size
        cap = min(cap, state.world_size)
        if state.rank < cap:
            state.active_rank = state.rank
            state.active_world_size = cap
            state.mode = Mode.ACTIVE
        else:
            state.active_rank = None
            state.active_world_size = cap
            state.mode = Mode.INACTIVE
        return ctx


class ActivateWholeGroups(RankAssignment):
    """Keep only COMPLETE topology groups active.

    Reference analogs: ``FilterCountGroupedByKey`` (``:900``) and the ``Tree``
    layers (``:416-520``) — on TPU a partial host or slice cannot form a
    legal device mesh, so after failures only groups with every member
    surviving may stay active.  ``key_of_rank`` maps an initial rank to its
    group (e.g. ``lambda r: r // 4`` for 4 chips per host); survivors in
    complete groups are renumbered contiguously group-major; survivors in
    broken groups park INACTIVE (ready to back-fill after the next fault).
    """

    def __init__(self, key_of_rank, group_size: int, min_groups: int = 1):
        self.key_of_rank = key_of_rank
        self.group_size = group_size
        self.min_groups = min_groups

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if state.initial_rank in ctx.terminated_ranks:
            raise RankDiscontinued(f"rank {state.initial_rank} terminated")
        survivors = _surviving(ctx)
        groups: dict = {}
        for r in survivors:
            groups.setdefault(self.key_of_rank(r), []).append(r)
        complete = {
            k: sorted(members)
            for k, members in groups.items()
            if len(members) == self.group_size
        }
        if len(complete) < self.min_groups:
            raise RestartAbort(
                f"only {len(complete)} complete groups < min_groups {self.min_groups}"
            )
        ordered: List[int] = []
        for k in sorted(complete, key=lambda k: complete[k][0]):
            ordered.extend(complete[k])
        # unique renumbering across ALL survivors: actives take 0..n_active-1
        # (group-major), parked survivors continue after them — two live
        # processes must never share a state.rank
        parked = [r for r in survivors if r not in ordered]
        numbering = {r: i for i, r in enumerate(ordered + parked)}
        state.world_size = len(survivors)
        state.rank = numbering[state.initial_rank]
        if state.initial_rank in numbering and state.rank < len(ordered):
            state.active_rank = state.rank
            state.active_world_size = len(ordered)
            state.mode = Mode.ACTIVE
        else:
            state.active_rank = None
            state.active_world_size = len(ordered)
            state.mode = Mode.INACTIVE
        return ctx


class LayerFlag(enum.Flag):
    """Per-layer fault-handling policies (reference ``rank_assignment.py:416``).

    - ``RESERVE``: terminated active ranks may be replaced by INACTIVE spares
      found inside this layer's subtree; the search widens upward through
      consecutive RESERVE-flagged ancestor layers.
    - ``BACKFILL``: gaps left by terminated active ranks are filled by the
      highest-app-rank active leaf *within the same subtree* (local
      ``FillGaps`` — minimizes resharding movement inside a host/slice).
    """

    NONE = 0
    RESERVE = enum.auto()
    BACKFILL = enum.auto()


@dataclasses.dataclass
class Layer:
    """One level of the topology tree (reference ``Layer``, ``:416-520``).

    ``key_of_rank`` maps an *initial* rank to this layer's grouping key.  The
    reference exchanges per-rank keys through the store because a rank only
    knows its own hostname; on TPU the pod topology is static and derivable
    from the rank (chip = r % chips_per_host, host = r // chips_per_host,
    slice = host // hosts_per_slice), so every rank can evaluate every other
    rank's key locally — the policy stays pure, no store round-trip.  A plain
    string (e.g. ``'root'``) is a constant key.

    ``min_ranks``: if the number of healthy ranks inside one of this layer's
    subtrees drops below this, the whole subtree is terminated (a partial TPU
    host/slice cannot form a legal mesh).  ``max_ranks``: at most this many
    ACTIVE ranks per subtree; surplus healthy ranks park INACTIVE as spares.
    """

    min_ranks: int = 1
    max_ranks: Optional[int] = None
    key_of_rank: Union[str, Callable[[int], Hashable]] = "root"
    flag: LayerFlag = LayerFlag.NONE

    def key(self, rank: int) -> Hashable:
        if callable(self.key_of_rank):
            return self.key_of_rank(rank)
        return self.key_of_rank


def _sorted_keys(d: Dict) -> List:
    """Deterministic child ordering: natural sort when keys are comparable
    (ints from ``r // n``), ``repr`` fallback otherwise — every rank must
    walk the tree in the same order."""
    try:
        return sorted(d)
    except TypeError:
        return sorted(d, key=repr)


class _Node:
    """Internal topology-tree node: one subtree of one :class:`Layer`.

    ``active_n``/``healthy_n`` are maintained incrementally on every leaf
    mode transition (via :meth:`_Leaf.set_mode`) so activation and fault
    handling stay O(n·depth) on the restart critical path — a pod has
    thousands of leaves and recounting subtrees per leaf would be O(n²).
    """

    __slots__ = ("layer", "key", "children", "leaves", "parent", "depth",
                 "active_n", "healthy_n")

    def __init__(self, layer: Layer, key: Hashable, parent: Optional["_Node"], depth: int):
        self.layer = layer
        self.key = key
        self.parent = parent
        self.depth = depth
        self.children: Dict[Hashable, _Node] = {}
        self.leaves: List[_Leaf] = []  # only on deepest-layer nodes
        self.active_n = 0
        self.healthy_n = 0

    def iter_leaves(self):
        if self.leaves:
            yield from self.leaves
        for key in _sorted_keys(self.children):
            yield from self.children[key].iter_leaves()

    def has_max_headroom(self) -> bool:
        return self.layer.max_ranks is None or self.active_n < self.layer.max_ranks


class _Leaf:
    __slots__ = ("initial_rank", "mode", "app_rank", "parent")

    def __init__(self, initial_rank: int, parent: _Node):
        self.initial_rank = initial_rank
        self.mode = Mode.INITIALIZED
        self.app_rank: Optional[int] = None
        self.parent = parent
        for node in self.ancestors():
            node.healthy_n += 1

    def ancestors(self):
        node = self.parent
        while node is not None:
            yield node
            node = node.parent

    def set_mode(self, mode: Mode) -> None:
        if mode is self.mode:
            return
        d_active = (mode is Mode.ACTIVE) - (self.mode is Mode.ACTIVE)
        d_healthy = (mode is not Mode.TERMINATED) - (self.mode is not Mode.TERMINATED)
        if d_active or d_healthy:
            for node in self.ancestors():
                node.active_n += d_active
                node.healthy_n += d_healthy
        self.mode = mode


class Tree(RankAssignment):
    """Multi-layer topology-aware rank assignment (reference ``Tree``,
    ``inprocess/rank_assignment.py:416-520``).

    Builds a rooted tree whose depth equals ``len(layers)`` — e.g.
    pod → slice → host for a TPU fleet — with ranks as leaves.  Initial
    activation walks leaves depth-first and activates each while no ancestor
    subtree exceeds its ``max_ranks``; surplus healthy ranks park INACTIVE.
    On faults (cumulative terminated set from the store):

    1. *propagate*: subtrees whose healthy count falls below ``min_ranks``
       are terminated whole (children before parents);
    2. *reserve*: each gap is refilled by an INACTIVE spare from the nearest
       RESERVE-flagged ancestor subtree (search widens upward through
       consecutive RESERVE layers; candidates must not overflow their own
       ancestors' ``max_ranks``);
    3. *backfill*: remaining gaps inside BACKFILL-flagged subtrees are taken
       by that subtree's highest-app-rank active leaf;
    4. *shift*: any remaining gaps close by renumbering actives in app-rank
       order;
    5. ``world_size_filter(n_active) -> m <= n_active`` optionally deactivates
       the tail back into the spare pool (e.g. keep the mesh rectangular).

    The instance is stateful across restart iterations (like the reference).
    Correctness contract: ``wrap.py`` passes the store's cumulative
    termination *log* (one global append order), and the tree applies events
    strictly one at a time in that order — the assignment is therefore a
    pure function of the log prefix, and ranks whose store reads batch the
    same events differently still converge.  ``min_ranks`` also holds at
    initial build: an undersized subtree never activates.  ``Tree`` must not
    be composed with other rank-assignment policies.
    """

    def __init__(
        self,
        layers: List[Layer],
        world_size_filter: Optional[Callable[[int], int]] = None,
    ):
        if not layers:
            raise ValueError("Tree requires at least one Layer")
        self.layers = list(layers)
        self.world_size_filter = world_size_filter
        self._root: Optional[_Node] = None
        self._leaves: Dict[int, _Leaf] = {}
        self._applied: Set[int] = set()

    # -- construction ------------------------------------------------------

    def _build(self, world_size: int) -> None:
        root_keys = {self.layers[0].key(r) for r in range(world_size)}
        if len(root_keys) != 1:
            raise RestartAbort(
                f"all ranks must share one root-layer key, got {sorted(map(repr, root_keys))}"
            )
        self._root = _Node(self.layers[0], root_keys.pop(), None, 0)
        for rank in range(world_size):
            node = self._root
            for depth in range(1, len(self.layers)):
                layer = self.layers[depth]
                key = layer.key(rank)
                child = node.children.get(key)
                if child is None:
                    child = node.children[key] = _Node(layer, key, node, depth)
                node = child
            leaf = _Leaf(rank, node)
            node.leaves.append(leaf)
            self._leaves[rank] = leaf
        # min_ranks holds from the start: an undersized subtree (e.g. a
        # 2-chip remainder in a 4-chip-host fleet) must never activate as an
        # illegal sub-mesh, so propagation runs BEFORE activation.
        self._propagate_min_ranks(self._root)
        # Depth-first activation bounded by every ancestor's max_ranks; app
        # ranks follow activation order so a host's ranks stay contiguous.
        # Non-activated leaves are spares: marked INACTIVE here (not at
        # __call__ time) so every rank's instance sees identical modes.
        nxt = 0
        for leaf in self._root.iter_leaves():
            if leaf.mode is Mode.TERMINATED:
                continue
            if all(n.has_max_headroom() for n in leaf.ancestors()):
                leaf.set_mode(Mode.ACTIVE)
                leaf.app_rank = nxt
                nxt += 1
            else:
                leaf.set_mode(Mode.INACTIVE)
        self._apply_filter()

    # -- fault handling ----------------------------------------------------

    def _propagate_min_ranks(self, node: _Node) -> None:
        for key in _sorted_keys(node.children):
            self._propagate_min_ranks(node.children[key])
        if node.healthy_n < node.layer.min_ranks:
            for leaf in node.iter_leaves():
                leaf.set_mode(Mode.TERMINATED)
                leaf.app_rank = None

    def _reserve_candidate(self, dead: _Leaf) -> Optional[_Leaf]:
        """INACTIVE spare to take over a terminated active leaf's slot.

        The search starts at the dead leaf's nearest RESERVE-flagged ancestor
        and widens upward through consecutive RESERVE layers, so a same-host
        spare always wins over a distant one (locality = least resharding
        movement).  The dead leaf freed one active slot in every ancestor it
        shares with a candidate (the scope and above), so only the
        candidate's ancestors *below* the current scope must still have
        ``max_ranks`` headroom.
        """
        scopes: List[_Node] = []
        for node in dead.ancestors():
            if node.layer.flag & LayerFlag.RESERVE:
                scopes.append(node)
            else:
                break
        for scope in scopes:  # nearest ancestor first
            for leaf in scope.iter_leaves():
                if leaf.mode is Mode.INACTIVE and all(
                    n.has_max_headroom()
                    for n in leaf.ancestors()
                    if n.depth > scope.depth
                ):
                    return leaf
        return None

    def _backfill_mover(self, dead: _Leaf, gap_rank: int) -> Optional[_Leaf]:
        """Highest-app-rank active leaf from the consecutive BACKFILL
        ancestor chain (nearest first) — the same stop-at-unflagged-layer
        rule as the RESERVE search, keeping gap-filling local."""
        for node in dead.ancestors():
            if not (node.layer.flag & LayerFlag.BACKFILL):
                break
            movers = [
                l
                for l in node.iter_leaves()
                if l.mode is Mode.ACTIVE and l.app_rank is not None and l.app_rank > gap_rank
            ]
            if movers:
                return max(movers, key=lambda l: l.app_rank)
        return None

    @staticmethod
    def _terminate_leaf(leaf: _Leaf, gaps: List[tuple]) -> None:
        if leaf.mode is Mode.ACTIVE:
            gaps.append((leaf.app_rank, leaf))
        leaf.set_mode(Mode.TERMINATED)
        leaf.app_rank = None

    def _renumber(self) -> None:
        """Shift step: close remaining gaps, preserving app-rank order."""
        actives = sorted(
            (l for l in self._root.iter_leaves() if l.mode is Mode.ACTIVE),
            key=lambda l: l.app_rank,
        )
        for i, leaf in enumerate(actives):
            leaf.app_rank = i

    # -- policy entry ------------------------------------------------------

    def _apply_one(self, r: int) -> None:
        """Apply ONE termination event: terminate → propagate min_ranks →
        refill gaps (reserve, then backfill) → shift → world_size_filter.

        Events are applied strictly one at a time in the store log's global
        order, so the final assignment is a pure function of the log prefix:
        two ranks whose store reads batch the same events differently still
        converge.  (The tree is stateful — a batching-dependent result here
        would be a *permanent* cross-rank divergence, unlike the stateless
        policies which self-heal on the next fault.)
        """
        leaf = self._leaves[r]
        if leaf.mode is Mode.TERMINATED:
            return
        gaps: List[tuple] = []  # (vacated app rank, dead leaf)
        self._terminate_leaf(leaf, gaps)
        # min_ranks propagation only ever cascades along THIS leaf's
        # ancestor chain (other subtrees' healthy counts are untouched), so
        # a full-tree sweep per event would waste O(n) on the restart
        # critical path; the incremental healthy_n counters make each hop
        # O(1) to test, bottom-up so upper nodes see updated counts
        for node in leaf.ancestors():
            if node.healthy_n < node.layer.min_ranks:
                for l in node.iter_leaves():
                    if l.mode is not Mode.TERMINATED:
                        self._terminate_leaf(l, gaps)
        # reserve replacement, then local backfill, then global shift
        for gap, dead in sorted(gaps, key=lambda p: p[0]):
            spare = self._reserve_candidate(dead)
            if spare is not None:
                spare.set_mode(Mode.ACTIVE)
                spare.app_rank = gap
                continue
            mover = self._backfill_mover(dead, gap)
            if mover is not None:
                mover.app_rank = gap
        self._renumber()
        self._apply_filter()

    def _apply_filter(self) -> None:
        """Deactivate the active tail down to ``world_size_filter(n)``.

        Runs after _build and after EVERY event (not once per __call__):
        filtered-out leaves become reserve candidates, so deferring the
        filter to the end of a batched call would again make results depend
        on how events were batched."""
        if self.world_size_filter is None:
            return
        n_active = self._root.active_n
        keep = self.world_size_filter(n_active)
        if keep > n_active:
            raise RestartAbort(
                f"world_size_filter returned {keep} > active count {n_active}"
            )
        for leaf in self._root.iter_leaves():
            if leaf.mode is Mode.ACTIVE and leaf.app_rank is not None and leaf.app_rank >= keep:
                leaf.set_mode(Mode.INACTIVE)
                leaf.app_rank = None

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        if self._root is None:
            self._build(state.initial_world_size)
        terms = ctx.terminated_ranks
        if isinstance(terms, (set, frozenset)):
            # no arrival order available (pure-logic callers/tests): pin one
            terms = sorted(terms)
        for r in terms:
            if r not in self._applied:
                self._applied.add(r)
                self._apply_one(r)

        my = self._leaves[state.initial_rank]
        if my.mode is Mode.TERMINATED:
            raise RankDiscontinued(
                f"rank {state.initial_rank} terminated (topology tree)"
            )
        healthy = [l for l in self._root.iter_leaves() if l.mode is not Mode.TERMINATED]
        actives = sorted(
            (l for l in healthy if l.mode is Mode.ACTIVE), key=lambda l: l.app_rank
        )
        parked = sorted(
            (l for l in healthy if l.mode is not Mode.ACTIVE), key=lambda l: l.initial_rank
        )
        state.world_size = len(healthy)
        state.active_world_size = len(actives)
        if my.mode is Mode.ACTIVE:
            state.rank = my.app_rank
            state.active_rank = my.app_rank
            state.mode = Mode.ACTIVE
        else:
            state.rank = len(actives) + parked.index(my)
            state.active_rank = None
            state.mode = Mode.INACTIVE
        return ctx


def tpu_pod_layers(
    chips_per_host: int,
    hosts_per_slice: Optional[int] = None,
    min_slices: int = 1,
    max_active: Optional[int] = None,
    reserve: bool = True,
) -> List[Layer]:
    """Layers for the canonical TPU hierarchy chip → host → slice → pod.

    A host with a dead chip cannot contribute a legal sub-mesh, so the host
    layer pins ``min_ranks = max_ranks = chips_per_host``; slices likewise if
    ``hosts_per_slice`` is given.  ``min_slices`` sets the root's
    minimum-capacity floor (the job aborts below ``min_slices`` whole
    slices — or whole hosts when no slice layer is used).  ``reserve=True``
    marks every layer RESERVE so spare hosts/slices promote into gaps
    (hot-spare pattern, reference ``ft_rendezvous_barrier.py:1842-1865``).
    """

    flag = LayerFlag.RESERVE if reserve else LayerFlag.NONE
    # without an explicit slice layer, the host is the slice unit — min_slices
    # still sets the root's minimum-capacity floor either way
    slice_chips = chips_per_host * (hosts_per_slice or 1)
    layers = [
        Layer(
            min_ranks=min_slices * slice_chips,
            max_ranks=max_active,
            key_of_rank="root",
            flag=flag,
        )
    ]
    if hosts_per_slice is not None:
        layers.append(
            Layer(
                min_ranks=slice_chips,
                max_ranks=slice_chips,
                key_of_rank=lambda r, n=slice_chips: r // n,
                flag=flag,
            )
        )
    layers.append(
        Layer(
            min_ranks=chips_per_host,
            max_ranks=chips_per_host,
            key_of_rank=lambda r, n=chips_per_host: r // n,
            flag=flag,
        )
    )
    return layers


class ActiveWorldSizeDivisibleBy(RankAssignment):
    """Largest active world size divisible by ``divisor`` (e.g. hosts per
    slice / chips per host, so the device mesh stays rectangular)."""

    def __init__(self, divisor: int):
        if divisor < 1:
            raise ValueError("divisor must be >= 1")
        self.divisor = divisor

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        state = ctx.state
        cap = (state.world_size // self.divisor) * self.divisor
        if cap == 0:
            raise RestartAbort(
                f"world size {state.world_size} < divisor {self.divisor}"
            )
        if state.rank < cap:
            state.active_rank = state.rank
            state.active_world_size = cap
            state.mode = Mode.ACTIVE
        else:
            state.active_rank = None
            state.active_world_size = cap
            state.mode = Mode.INACTIVE
        return ctx
