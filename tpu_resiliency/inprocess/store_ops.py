"""Store key schema + operations for the in-process restart protocol.

Reference analog: ``inprocess/store.py:50-321`` (``StoreMixin``: interruption
records + lock, terminated ranks, heartbeats, per-iteration PrefixStore
namespaces, barriers).  Differences by design: interruption records are an
append-only log (our store's APPEND is atomic, so no record lock is needed),
and iteration fencing uses key prefixes exactly like the reference.
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..store.barrier import gc_barrier, reentrant_barrier
from .attribution import InterruptionRecord

NS = "inproc"


class InprocStore:
    """Typed operations over the shared KV store for one wrapper group."""

    def __init__(self, store, group: str = "default"):
        self.store = store
        self.ns = f"{NS}/{group}"

    # -- interruption records ---------------------------------------------

    def k_interruptions(self, iteration: int) -> str:
        return f"{self.ns}/iter/{iteration}/interruptions"

    def record_interruption(self, iteration: int, rec: InterruptionRecord) -> None:
        self.store.append(self.k_interruptions(iteration), rec.to_json() + "\n")

    def any_interruption(self, iteration: int) -> bool:
        raw = self.store.try_get(self.k_interruptions(iteration))
        return bool(raw)

    def wait_any_interruption(self, iteration: int, timeout: float) -> bool:
        from ..store.client import StoreTimeout

        try:
            self.store.wait([self.k_interruptions(iteration)], timeout=timeout)
            return True
        except StoreTimeout:
            return False

    def get_interruptions(self, iteration: int) -> List[InterruptionRecord]:
        raw = self.store.try_get(self.k_interruptions(iteration))
        if not raw:
            return []
        return [
            InterruptionRecord.from_json(line)
            for line in raw.decode().splitlines()
            if line.strip()
        ]

    # -- at-abort fingerprints ---------------------------------------------

    def k_fingerprints(self, iteration: int) -> str:
        return f"{self.ns}/iter/{iteration}/fingerprints"

    def record_fingerprint(self, iteration: int, rank: int, tail) -> None:
        """Append this rank's dispatch-tail fingerprint (last K dispatched
        programs + ages) for the iteration — the at-abort analog of the
        reference's Flight-Recorder dump (``abort.py:127-160``)."""
        import json

        self.store.append(
            self.k_fingerprints(iteration),
            json.dumps({"rank": rank, "tail": list(tail)}) + "\n",
        )

    def get_fingerprints(self, iteration: int):
        from .fingerprint import parse_fingerprints

        return parse_fingerprints(
            self.store.try_get(self.k_fingerprints(iteration))
        )

    def wait_fingerprints(
        self, iteration: int, n: int, timeout: float
    ):
        """Best-effort gather: poll until >= n ranks published or timeout;
        returns whatever arrived (attribution must never block recovery)."""
        deadline = time.monotonic() + timeout
        while True:
            got = self.get_fingerprints(iteration)
            if len(got) >= n or time.monotonic() >= deadline:
                return got
            time.sleep(0.05)

    # -- terminated ranks --------------------------------------------------

    def mark_terminated(self, rank: int) -> None:
        # atomic APPEND to one log key: every rank observes the same total
        # order of terminations (each read is a prefix of the same log).
        # Stateful rank-assignment policies (Tree) replay this order, so a
        # canonical order is load-bearing, not cosmetic.
        # tpurx: disable=TPURX013 -- lifetime log, not a round key: policies replay the full order for the group's whole life, and growth is bounded by world_size (a rank terminates once)
        self.store.append(f"{self.ns}/terminated_log", f"{rank},".encode())

    def terminated_ranks(self) -> List[int]:
        """Terminated initial ranks in global first-termination order."""
        raw = self.store.try_get(f"{self.ns}/terminated_log")
        if not raw:
            return []
        seen: set = set()
        out: List[int] = []
        for tok in raw.decode().split(","):
            if tok:
                r = int(tok)
                if r not in seen:
                    seen.add(r)
                    out.append(r)
        return out

    # -- sibling heartbeats ------------------------------------------------

    def heartbeat(self, rank: int) -> None:
        # tpurx: disable=TPURX013 -- one key per rank, overwritten in place: bounded by world_size, never grows with rounds
        self.store.set(f"{self.ns}/hb/{rank}", str(time.time()))

    def last_heartbeat(self, rank: int) -> Optional[float]:
        raw = self.store.try_get(f"{self.ns}/hb/{rank}")
        return float(raw) if raw else None

    # -- completion / barriers --------------------------------------------

    def k_completed(self, iteration: int) -> str:
        return f"{self.ns}/iter/{iteration}/any_completed"

    def mark_completed(self, iteration: int) -> None:
        self.store.set(self.k_completed(iteration), b"1")

    def any_completed(self, iteration: int) -> bool:
        return self.store.check([self.k_completed(iteration)])

    def iteration_barrier(
        self, iteration: int, rank: int, ranks: List[int], timeout: float
    ) -> None:
        """Reentrant: a rank interrupted mid-barrier re-enters safely."""
        reentrant_barrier(
            self.store,
            f"{self.ns}/iter/{iteration}/barrier",
            rank,
            len(ranks),
            timeout=timeout,
            ranks=ranks,
        )

    def initial_barrier(self, rank: int, world_size: int, timeout: float) -> None:
        reentrant_barrier(
            self.store, f"{self.ns}/initial_barrier", rank, world_size, timeout=timeout
        )

    # -- fault-episode identity -------------------------------------------

    def k_episode(self, iteration: int) -> str:
        return f"{self.ns}/iter/{iteration}/episode"

    def claim_episode(self, iteration: int, proposed: str) -> str:
        """One episode id per fault: the first detecting rank's CAS wins and
        every later claimant adopts the winner's id.  Iteration-scoped, so
        :meth:`gc_iteration` retires the claim with the fault's other keys."""
        ok, actual = self.store.compare_set_ex(
            self.k_episode(iteration), b"", proposed.encode()
        )
        if ok:
            return proposed
        winner = (actual or b"").decode()
        return winner or proposed

    # -- per-iteration key GC ---------------------------------------------

    def gc_iteration(self, iteration: int) -> None:
        """Delete a SETTLED iteration's protocol keys (idempotent).

        The per-iteration keys (interruption log, fingerprint log, completion
        marker, iteration barrier) previously accumulated for the life of the
        store — O(restarts) growth per wrapper group, found by lint rule
        TPURX013.  The wrapper calls this for iteration ``i-2`` when the
        iteration-``i`` barrier closes: by then every surviving rank has
        advanced twice past ``i-2``, so nobody can still read or re-enter its
        keys (the same two-generation settling the tree-gather GC uses).
        """
        if iteration < 0:
            return
        self.store.delete(self.k_interruptions(iteration))
        self.store.delete(self.k_fingerprints(iteration))
        self.store.delete(self.k_completed(iteration))
        self.store.delete(self.k_episode(iteration))
        gc_barrier(self.store, f"{self.ns}/iter/{iteration}/barrier")
