"""Monitor process: an external watchdog for one training rank.

Capability parity with ``inprocess/monitor_process.py:55-437``: a daemonized
process (double-fork, so it survives the parent's crash and is reparented to
init) that watches the training PID and the progress-watchdog timestamp:

- soft timeout (no progress): record a SOFT_TIMEOUT interruption in the store
  so every rank's MonitorThread trips and restarts — the process lives;
- hard timeout (still no progress after the kill budget): SIGTERM then
  SIGKILL the rank (a GIL-holding or device-wedged process cannot restart
  itself) and record HARD_TIMEOUT + terminated;
- process death: record TERMINATED + mark the rank terminated.

The monitor connects to the store with its own client (it must not share the
parent's socket).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import signal
import time
from typing import Optional

from ..utils.logging import get_logger, setup_logger
from .attribution import Interruption, InterruptionRecord
from .store_ops import InprocStore

log = get_logger("monitor_process")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    # a zombie (dead, unreaped by a slow parent) must count as dead — the
    # interpreter is gone even though the pid still answers signal 0
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
        return state != "Z"
    except (OSError, IndexError):
        return False


def _terminate_process(pid: int, grace: float) -> None:
    try:
        os.kill(pid, signal.SIGCONT)
        os.kill(pid, signal.SIGTERM)
    except OSError:
        return
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not _pid_alive(pid):
            return
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:
        pass


class MonitorProcess:
    def __init__(
        self,
        store_factory,                 # () -> StoreClient (fresh connection)
        group: str,
        rank: int,
        timestamp,                     # mp.Value('d') from ProgressWatchdog
        soft_timeout: float = 60.0,
        hard_timeout: float = 90.0,
        interval: float = 1.0,
        termination_grace: float = 5.0,
    ):
        self.store_factory = store_factory
        self.group = group
        self.rank = rank
        self.timestamp = timestamp
        self.soft_timeout = soft_timeout
        self.hard_timeout = hard_timeout
        self.interval = interval
        self.termination_grace = termination_grace
        self._iter_value = mp.Value("i", 0, lock=False)
        self._enabled = mp.Value("i", 1, lock=False)
        self._proc: Optional[mp.Process] = None
        self.parent_pid = os.getpid()

    # -- parent-side control ----------------------------------------------

    def start(self) -> "MonitorProcess":
        ctx = mp.get_context("fork")
        self._proc = ctx.Process(
            target=self._daemon_main,
            name=f"tpurx-inproc-monitor-{self.rank}",
            daemon=True,
        )
        self._proc.start()
        return self

    def set_iteration(self, iteration: int) -> None:
        self._iter_value.value = iteration

    def set_enabled(self, enabled: bool) -> None:
        """Disable hang protection during known-long phases (reference
        ``disable_hang_protection``)."""
        self._enabled.value = 1 if enabled else 0

    def stop(self) -> None:
        if self._proc is not None and self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)
        self._proc = None

    # -- monitor-side loop -------------------------------------------------

    def _daemon_main(self) -> None:
        # "double fork" effect: detach from the parent's process group so a
        # killpg of the rank does not take the monitor with it
        try:
            os.setsid()
        except OSError:
            pass
        setup_logger()
        try:
            store = self.store_factory()
        except Exception as exc:  # noqa: BLE001
            log.error("monitor %s: cannot reach store: %s", self.rank, exc)
            return
        ops = InprocStore(store, self.group)
        soft_reported_at: Optional[float] = None
        while True:
            time.sleep(self.interval)
            pid = self.parent_pid
            iteration = self._iter_value.value
            if not _pid_alive(pid):
                log.error("monitor: rank %s (pid %s) died", self.rank, pid)
                self._record(ops, iteration, Interruption.TERMINATED, "process died")
                ops.mark_terminated(self.rank)
                return
            if not self._enabled.value:
                soft_reported_at = None
                continue
            age = time.time() - self.timestamp.value
            if age > self.hard_timeout:
                log.error(
                    "monitor: rank %s wedged for %.1fs (> hard %.1fs) — killing",
                    self.rank, age, self.hard_timeout,
                )
                self._record(
                    ops, iteration, Interruption.HARD_TIMEOUT, f"no progress {age:.1f}s"
                )
                ops.mark_terminated(self.rank)
                _terminate_process(pid, self.termination_grace)
                return
            if age > self.soft_timeout:
                if soft_reported_at is None or soft_reported_at < self.timestamp.value:
                    log.warning(
                        "monitor: rank %s stalled %.1fs (> soft %.1fs)",
                        self.rank, age, self.soft_timeout,
                    )
                    self._record(
                        ops, iteration, Interruption.SOFT_TIMEOUT, f"no progress {age:.1f}s"
                    )
                    soft_reported_at = time.time()
            else:
                soft_reported_at = None

    def _record(self, ops: InprocStore, iteration: int, kind: Interruption, msg: str) -> None:
        try:
            ops.record_interruption(
                iteration,
                InterruptionRecord(rank=self.rank, interruption=kind, message=msg),
            )
        except Exception as exc:  # noqa: BLE001
            log.error("monitor: failed to record interruption: %s", exc)
