"""Monitor process: an external watchdog for one training rank.

Capability parity with ``inprocess/monitor_process.py:55-437``: a detached
process (own session, so it survives the parent's crash and a killpg of the
rank) that watches the training PID and the progress-watchdog timestamp:

- soft timeout (no progress): record a SOFT_TIMEOUT interruption in the store
  so every rank's MonitorThread trips and restarts — the process lives;
- hard timeout (still no progress after the kill budget): SIGTERM then
  SIGKILL the rank (a GIL-holding or device-wedged process cannot restart
  itself) and record HARD_TIMEOUT + terminated;
- process death: record TERMINATED + mark the rank terminated.

Process model: **exec, not fork**.  The training process is JAX-threaded by
the time the wrapper starts (the axon sitecustomize imports jax into every
interpreter), and forking a threaded parent is a documented deadlock class
on TPU hosts; multiprocessing's spawn is no better here because it re-imports
``__main__`` in the child, re-running the training script's module-level
side effects.  Instead the parent execs a dedicated entry
(``inprocess.monitor_main``) and shares the watchdog timestamp / iteration /
enabled flags through a small NAMED shared-memory block
(:class:`MonitorSharedState`) — no pickling, no inherited interpreter state.
The monitor connects to the store with its own client (endpoint from the
store factory when introspectable, else the launcher-provided env).
"""

from __future__ import annotations

import ctypes
import os
import signal
import subprocess
import sys
import time
from typing import Optional, Tuple

from ..utils.env import disarm_platform_sitecustomize
from ..utils.logging import get_logger
from ..utils.shm import attach_shm, create_shm, unlink_shm

log = get_logger("monitor_process")

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")
)

# segments whose mmap stayed pinned at close (see MonitorSharedState.close)
_LEAKED_SHM: list = []


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    # a zombie (dead, unreaped by a slow parent) must count as dead — the
    # interpreter is gone even though the pid still answers signal 0
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
        return state != "Z"
    except (OSError, IndexError):
        return False


def _terminate_process(pid: int, grace: float) -> None:
    try:
        os.kill(pid, signal.SIGCONT)
        os.kill(pid, signal.SIGTERM)
    except OSError:
        return
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not _pid_alive(pid):
            return
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:
        pass


class MonitorSharedState:
    """Named-shm state shared between the rank and its monitor process.

    Layout (32 bytes): f64 timestamp | i64 iteration | i64 enabled |
    i64 ready.  Single-writer per field (rank writes the first three, the
    monitor writes ready); plain aligned loads/stores are atomic on the
    targets we run on.  ``timestamp_slot`` exposes a ctypes double with a
    stable address — both the ProgressWatchdog and the native pending-call
    stamper write through it.
    """

    SIZE = 32

    def __init__(self, shm, owner: bool):
        self._shm = shm
        self._owner = owner
        self.name = shm.name
        self.timestamp_slot = ctypes.c_double.from_buffer(shm.buf, 0)
        self._iteration = ctypes.c_int64.from_buffer(shm.buf, 8)
        self._enabled = ctypes.c_int64.from_buffer(shm.buf, 16)
        self._ready = ctypes.c_int64.from_buffer(shm.buf, 24)

    @classmethod
    def create(cls) -> "MonitorSharedState":
        state = cls(create_shm(cls.SIZE), owner=True)
        from ..ops.quorum import wall_time_s

        state.timestamp_slot.value = wall_time_s()
        state._enabled.value = 1
        return state

    @classmethod
    def attach(cls, name: str) -> "MonitorSharedState":
        return cls(attach_shm(name), owner=False)

    @property
    def iteration(self) -> int:
        return int(self._iteration.value)

    @iteration.setter
    def iteration(self, v: int) -> None:
        self._iteration.value = v

    @property
    def enabled(self) -> bool:
        return bool(self._enabled.value)

    @enabled.setter
    def enabled(self, v: bool) -> None:
        self._enabled.value = 1 if v else 0

    @property
    def ready(self) -> bool:
        return bool(self._ready.value)

    def mark_ready(self) -> None:
        self._ready.value = 1

    def close(self) -> None:
        if self._shm is None:
            return  # idempotent: stop() and __exit__ may both close
        # unlink first (owner): even if a pinned ctypes view keeps the
        # mapping alive, the NAME must go so nothing attaches to a dead slot
        if self._owner:
            unlink_shm(self._shm)
        # ctypes views pin the buffer — drop them before closing the mmap
        self.timestamp_slot = None
        self._iteration = None
        self._enabled = None
        self._ready = None
        try:
            self._shm.close()
        except BufferError:
            # a view escaped (the watchdog pins its slot for queued pending
            # calls): keep the object alive forever so its __del__ doesn't
            # retry close() and spray "Exception ignored" at interpreter
            # exit — process teardown unmaps anyway
            _LEAKED_SHM.append(self._shm)
        self._shm = None


def _endpoint_from_factory(store_factory) -> Optional[Tuple[str, int]]:
    """(host, port) resolution so the exec'd monitor reaches the SAME store.

    Attribute introspection first (StoreFactory / bound StoreClient expose
    host/port); opaque callables (lambdas, closures — which the old
    fork-based monitor inherited for free) are CALLED once: any factory
    returning a StoreClient yields a connected client whose host/port we
    read and close.  Only factories returning host/port-less objects fall
    through to the launcher env."""
    host = getattr(store_factory, "host", None)
    port = getattr(store_factory, "port", None)
    if isinstance(host, str) and isinstance(port, int):
        return host, port
    self_obj = getattr(store_factory, "__self__", None)
    if self_obj is not None:
        return _endpoint_from_factory(self_obj)
    try:
        client = store_factory()
    except Exception as exc:  # noqa: BLE001
        log.warning("store factory probe failed (%s); monitor will use "
                    "TPURX_STORE_* env", exc)
        return None
    try:
        host = getattr(client, "host", None)
        port = getattr(client, "port", None)
        if isinstance(host, str) and isinstance(port, int):
            return host, port
    finally:
        try:
            client.close()
        except OSError:
            pass
    return None


class MonitorProcess:
    def __init__(
        self,
        store_factory,                 # () -> StoreClient (fresh connection)
        group: str,
        rank: int,
        timestamp=None,                # unused with shared state (kept for API)
        soft_timeout: float = 60.0,
        hard_timeout: float = 90.0,
        interval: float = 1.0,
        termination_grace: float = 5.0,
        shared_state: Optional[MonitorSharedState] = None,
        fptail_name: Optional[str] = None,
    ):
        self.store_factory = store_factory
        self.group = group
        self.rank = rank
        self.soft_timeout = soft_timeout
        self.hard_timeout = hard_timeout
        self.interval = interval
        self.termination_grace = termination_grace
        self.shared = shared_state or MonitorSharedState.create()
        self._owns_shared = shared_state is None
        # named-shm dispatch tail: lets the monitor fold the rank's last K
        # dispatched programs into SOFT/HARD_TIMEOUT records even when the
        # rank is wedged in a device call (at-abort fingerprint)
        self.fptail_name = fptail_name
        if timestamp is not None:
            # A legacy mp.Value timestamp the caller keeps writing would be
            # INVISIBLE to the exec'd monitor (it reads the shm slot), and
            # the monitor would hard-kill a healthy rank at hard_timeout.
            # Fail construction instead of arming a guaranteed kill.
            raise TypeError(
                "MonitorProcess no longer accepts a 'timestamp' value — "
                "create a MonitorSharedState, pass it as shared_state, and "
                "wire ProgressWatchdog(timestamp_slot=shared.timestamp_slot)"
            )
        self._proc: Optional[subprocess.Popen] = None
        self.parent_pid = os.getpid()

    # -- parent-side control ----------------------------------------------

    def start(self) -> "MonitorProcess":
        endpoint = _endpoint_from_factory(self.store_factory)
        cmd = [
            sys.executable, "-m", "tpu_resiliency.inprocess.monitor_main",
            "--shm", self.shared.name,
            "--group", self.group,
            "--rank", str(self.rank),
            "--parent-pid", str(self.parent_pid),
            "--soft-timeout", str(self.soft_timeout),
            "--hard-timeout", str(self.hard_timeout),
            "--interval", str(self.interval),
            "--termination-grace", str(self.termination_grace),
        ]
        if self.fptail_name:
            cmd += ["--fptail", self.fptail_name]
        if endpoint is not None:
            cmd += ["--store-host", endpoint[0], "--store-port", str(endpoint[1])]
        else:
            log.info(
                "monitor store endpoint not introspectable from the factory; "
                "the monitor will use TPURX_STORE_* env"
            )
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
        # the monitor is deliberately jax-free (stdlib + store client only):
        # disarm the platform sitecustomize so the child boots in ~0.3s
        # instead of paying a full jax import (seconds; a minute on a loaded
        # host with many ranks exec'ing monitors simultaneously)
        disarm_platform_sitecustomize(env)
        self._proc = subprocess.Popen(cmd, env=env)
        # Readiness handshake: the child boots a fresh interpreter (~0.3s
        # with the sitecustomize disarmed; the window stays generous for
        # loaded hosts) and then connects to the store; without this wait
        # the soft/hard clocks would silently include boot time and a hang
        # in the first seconds would be detected late.
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if self.shared.ready:
                return self
            if self._proc.poll() is not None:
                # hang protection was REQUESTED; running without it silently
                # would leave a wedged rank undetected for the whole job
                raise RuntimeError(
                    f"monitor process for rank {self.rank} exited "
                    f"rc={self._proc.returncode} at startup — store "
                    "endpoint unreachable from the monitor? (pass a "
                    "StoreFactory or set TPURX_STORE_*)"
                )
            time.sleep(0.02)
        log.warning(
            "monitor process for rank %s not ready after 60s — hang "
            "protection may lag", self.rank,
        )
        return self

    def set_iteration(self, iteration: int) -> None:
        self.shared.iteration = iteration

    def set_enabled(self, enabled: bool) -> None:
        """Disable hang protection during known-long phases (reference
        ``disable_hang_protection``)."""
        self.shared.enabled = enabled

    def stop(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._proc = None
        if self._owns_shared:
            self.shared.close()
