"""Restart health checks (run between restart iterations).

Capability parity with ``inprocess/health_check.py:73-228``:

- :class:`DeviceProbeHealthCheck` — JAX analog of ``CudaHealthCheck``'s
  threaded double ``cuda.synchronize``: run a tiny computation and
  ``block_until_ready`` it on a worker thread with a wall-clock timeout.  A
  healthy chip answers in ms; a wedged runtime hangs the probe thread (not
  the restart loop) and the check fails.
- :class:`FaultCounter` — abort after N faults on this rank (``:128``).
- Chaining via :class:`tpu_resiliency.inprocess.compose.Compose`; the
  node-level checks from :mod:`tpu_resiliency.health` can be adapted with
  :class:`NodeHealthCheckAdapter`.
"""

from __future__ import annotations

import concurrent.futures

from ..utils.logging import get_logger
from .exceptions import HealthCheckError, RestartAbort
from .state import FrozenState

log = get_logger("inproc.health")


class FaultCounterExceeded(RestartAbort):
    pass


class DeviceProbeHealthCheck:
    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpurx-devprobe"
        )

    @staticmethod
    def _probe() -> float:
        import jax
        import jax.numpy as jnp

        x = jnp.ones((128, 128))
        y = (x @ x).sum()
        jax.block_until_ready(y)
        return float(y)

    def __call__(self, state: FrozenState) -> FrozenState:
        future = self._pool.submit(self._probe)
        try:
            val = future.result(timeout=self.timeout)
        except concurrent.futures.TimeoutError as exc:
            # the probe thread is stuck on the device — replace the pool so a
            # later check doesn't queue behind the wedged probe
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpurx-devprobe"
            )
            raise HealthCheckError(
                f"device probe hung > {self.timeout}s (runtime wedged)"
            ) from exc
        except Exception as exc:  # noqa: BLE001
            raise HealthCheckError(f"device probe failed: {exc}") from exc
        if val != 128.0 * 128 * 128:
            raise HealthCheckError(f"device probe wrong result: {val}")
        return state


class FaultCounter:
    """Abort the restart loop after ``max_faults`` interruptions of this rank
    (a chip that keeps falling over should leave the job to the in-job ring)."""

    def __init__(self, max_faults: int = 3):
        self.max_faults = max_faults
        self.count = 0

    def __call__(self, state: FrozenState) -> FrozenState:
        # called on the restart path => one more fault observed
        self.count += 1
        if self.count > self.max_faults:
            raise FaultCounterExceeded(
                f"rank {state.rank}: {self.count} faults > {self.max_faults}"
            )
        return state


class NodeHealthCheckAdapter:
    """Wrap a :class:`tpu_resiliency.health.HealthCheck` as a restart check."""

    def __init__(self, check):
        self.check = check

    def __call__(self, state: FrozenState) -> FrozenState:
        result = self.check.run()
        if not result.healthy:
            raise HealthCheckError(f"{result.name}: {result.message}")
        return state
