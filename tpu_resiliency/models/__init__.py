"""Reference workloads the resiliency layer wraps and benchmarks against."""

from .transformer import TransformerConfig, init_params, forward, loss_fn, make_train_step

__all__ = [
    "TransformerConfig",
    "init_params",
    "forward",
    "loss_fn",
    "make_train_step",
]
