"""Decoder-only transformer reference workload (pure JAX, GSPMD-sharded).

This is the workload the resiliency stack wraps in benchmarks and the
driver's graft entry — NOT part of the resiliency capability surface (the
reference is workload-agnostic, SURVEY.md §2.8).  It exists so hang
detection, checkpoint overhead, and restart latency are measured against a
realistic MXU-bound training step.

TPU-first choices:
- bfloat16 activations/weights, fp32 master copy in the optimizer, so
  matmuls hit the MXU at full rate;
- dims padded to 128 multiples (MXU tiling);
- sharding via NamedSharding constraints (data on "data", heads/ffn on
  "model") — XLA inserts the all-reduces; no hand-written collectives;
- one fused train step under jit: fwd + bwd + adamw update.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 1024
    dtype: Any = None  # resolved to bf16 on TPU, f32 elsewhere

    def resolved_dtype(self):
        import jax
        import jax.numpy as jnp

        if self.dtype is not None:
            return self.dtype
        return jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32


def _specs(cfg: TransformerConfig):
    """PartitionSpecs per parameter (heads/ffn on 'model')."""
    from jax.sharding import PartitionSpec as P

    layer = {
        "wq": P(None, "model"), "wk": P(None, "model"), "wv": P(None, "model"),
        "wo": P("model", None),
        "w1": P(None, "model"), "w2": P("model", None),
        "ln1_scale": P(None), "ln2_scale": P(None),
    }
    return {
        "embed": P("model", None),        # vocab sharded over model axis
        "pos": P(None, None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "ln_f_scale": P(None),
    }


def init_params(cfg: TransformerConfig, key=None, mesh=None) -> Dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    key = key if key is not None else jax.random.PRNGKey(0)
    dt = cfg.resolved_dtype()
    k = iter(jax.random.split(key, 4 + 8 * cfg.n_layers))

    def dense(key, shape, scale=None):
        scale = scale if scale is not None else (1.0 / np.sqrt(shape[0]))
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dt)

    params: Dict[str, Any] = {
        "embed": dense(next(k), (cfg.vocab, cfg.d_model), scale=0.02),
        "pos": dense(next(k), (cfg.max_seq, cfg.d_model), scale=0.02),
        "layers": [],
        "ln_f_scale": jnp.ones((cfg.d_model,), dtype=dt),
    }
    for _ in range(cfg.n_layers):
        params["layers"].append(
            {
                "wq": dense(next(k), (cfg.d_model, cfg.d_model)),
                "wk": dense(next(k), (cfg.d_model, cfg.d_model)),
                "wv": dense(next(k), (cfg.d_model, cfg.d_model)),
                "wo": dense(next(k), (cfg.d_model, cfg.d_model)),
                "w1": dense(next(k), (cfg.d_model, cfg.d_ff)),
                "w2": dense(next(k), (cfg.d_ff, cfg.d_model)),
                "ln1_scale": jnp.ones((cfg.d_model,), dtype=dt),
                "ln2_scale": jnp.ones((cfg.d_model,), dtype=dt),
            }
        )
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_s = jax.tree_util.tree_leaves(
            _specs(cfg), is_leaf=lambda x: isinstance(x, P)
        )
        assert len(flat_p) == len(flat_s), "spec/param tree mismatch"
        placed = [
            jax.device_put(p, NamedSharding(mesh, s)) for p, s in zip(flat_p, flat_s)
        ]
        params = jax.tree_util.tree_unflatten(treedef, placed)
    return params


def _rmsnorm(x, scale):
    import jax.numpy as jnp

    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jnp.reciprocal(jnp.sqrt(var + 1e-6)).astype(x.dtype)) * scale


def forward(params: Dict, tokens, cfg: TransformerConfig, mesh=None):
    """Causal LM forward -> logits [B, T, vocab]."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def constrain(x, spec):
        if mesh is not None:
            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
        return x

    B, T = tokens.shape
    h = params["embed"][tokens] + params["pos"][:T][None, :, :]
    h = constrain(h, P("data", None, None))
    n_heads = cfg.n_heads
    head_dim = cfg.d_model // n_heads
    causal = jnp.tril(jnp.ones((T, T), dtype=bool))

    for layer in params["layers"]:
        x = _rmsnorm(h, layer["ln1_scale"])
        q = (x @ layer["wq"]).reshape(B, T, n_heads, head_dim)
        kk = (x @ layer["wk"]).reshape(B, T, n_heads, head_dim)
        v = (x @ layer["wv"]).reshape(B, T, n_heads, head_dim)
        q = constrain(q, P("data", None, "model", None))
        kk = constrain(kk, P("data", None, "model", None))
        v = constrain(v, P("data", None, "model", None))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(head_dim)
        scores = jnp.where(causal[None, None], scores, -1e9)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, cfg.d_model)
        h = h + attn @ layer["wo"]
        x = _rmsnorm(h, layer["ln2_scale"])
        ff = jax.nn.gelu(x @ layer["w1"])
        ff = constrain(ff, P("data", None, "model"))
        h = h + ff @ layer["w2"]
        h = constrain(h, P("data", None, None))

    h = _rmsnorm(h, params["ln_f_scale"])
    logits = h @ params["embed"].T  # weight tying
    return constrain(logits, P("data", None, None))


def loss_fn(params, batch, cfg: TransformerConfig, mesh=None):
    import jax
    import jax.numpy as jnp

    tokens, targets = batch
    logits = forward(params, tokens, cfg, mesh).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def init_opt_state(params):
    import jax
    import jax.numpy as jnp

    f32 = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
    any_low = any(
        leaf.dtype != jnp.float32 for leaf in jax.tree_util.tree_leaves(params)
    )
    state = {
        "mu": jax.tree_util.tree_map(f32, params),
        "nu": jax.tree_util.tree_map(f32, params),
        "count": jnp.zeros((), dtype=jnp.int32),
    }
    if any_low:
        # fp32 master copy: bf16 params would silently drop sub-ulp updates
        state["master"] = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params
        )
    return state


def make_train_step(cfg: TransformerConfig, mesh=None, lr: float = 1e-3):
    """Fused jitted train step: (params, opt_state, batch) -> (params, opt_state, loss)."""
    import jax
    import jax.numpy as jnp

    b1, b2, eps, wd = 0.9, 0.95, 1e-8, 0.01

    def step(params, opt, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh)
        )(params)
        count = opt["count"] + 1
        cf = count.astype(jnp.float32)
        has_master = "master" in opt

        def upd(p, g, mu, nu, master):
            g32 = g.astype(jnp.float32)
            mu2 = b1 * mu + (1 - b1) * g32
            nu2 = b2 * nu + (1 - b2) * jnp.square(g32)
            mu_hat = mu2 / (1 - b1 ** cf)
            nu_hat = nu2 / (1 - b2 ** cf)
            # update in fp32 against the master copy; cast down only for the
            # compute params (sub-ulp updates accumulate in the master)
            m = master if master is not None else p.astype(jnp.float32)
            m2 = m - lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + wd * m)
            return m2.astype(p.dtype), mu2, nu2, m2

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_mu = jax.tree_util.tree_leaves(opt["mu"])
        flat_nu = jax.tree_util.tree_leaves(opt["nu"])
        flat_master = (
            jax.tree_util.tree_leaves(opt["master"])
            if has_master
            else [None] * len(flat_p)
        )
        new_p, new_mu, new_nu, new_master = [], [], [], []
        for p, g, mu, nu, m in zip(flat_p, flat_g, flat_mu, flat_nu, flat_master):
            a, b, c, d = upd(p, g, mu, nu, m)
            new_p.append(a)
            new_mu.append(b)
            new_nu.append(c)
            new_master.append(d)
        new_opt = {
            "mu": jax.tree_util.tree_unflatten(treedef, new_mu),
            "nu": jax.tree_util.tree_unflatten(treedef, new_nu),
            "count": count,
        }
        if has_master:
            new_opt["master"] = jax.tree_util.tree_unflatten(treedef, new_master)
        return jax.tree_util.tree_unflatten(treedef, new_p), new_opt, loss

    return jax.jit(step, donate_argnums=(0, 1))


def make_batch(cfg: TransformerConfig, batch_size: int, seq: int, seed: int = 0, mesh=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab, size=(batch_size, seq), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)
    t = jnp.asarray(tokens)
    tt = jnp.asarray(targets)
    if mesh is not None:
        sh = NamedSharding(mesh, P("data", None))
        t, tt = jax.device_put(t, sh), jax.device_put(tt, sh)
    return t, tt
