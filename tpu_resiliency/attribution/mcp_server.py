"""MCP server exposing the attribution analyses as tools.

Reference analog: ``attribution/mcp_integration/`` (~1650 LoC over the mcp
SDK).  The protocol itself is small enough to speak directly — JSON-RPC 2.0
over stdio per the Model Context Protocol spec (2024-11-05 revision):
``initialize`` → ``tools/list`` → ``tools/call`` — so this implementation
has no SDK dependency.

    python -m tpu_resiliency.attribution.mcp_server   # serve on stdio

Tools: analyze_log, analyze_trace, analyze_combined.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, Optional

from ..utils.logging import get_logger
from .combined import analyze_combined
from .log_analyzer import LogAnalyzer
from .trace_analyzer import ProgressMarker, analyze_markers

log = get_logger("mcp")

PROTOCOL_VERSION = "2024-11-05"

TOOLS = [
    {
        "name": "analyze_log",
        "description": (
            "Classify a distributed-training failure from log text: category "
            "(oom_hbm, device_error, hang_kill, numerics, ...), culprit "
            "ranks, and whether restarting can succeed."
        ),
        "inputSchema": {
            "type": "object",
            "properties": {
                "text": {"type": "string", "description": "log text"},
                "path": {"type": "string", "description": "or: log file path"},
            },
        },
    },
    {
        "name": "analyze_trace",
        "description": (
            "Find the rank that stalled a wedged job from per-rank progress "
            "markers (step/phase/timestamp)."
        ),
        "inputSchema": {
            "type": "object",
            "properties": {
                "markers": {
                    "type": "object",
                    "description": "{rank: {rank, iteration, step, phase, ts} | null}",
                },
                "stale_after_s": {"type": "number"},
            },
            "required": ["markers"],
        },
    },
    {
        "name": "analyze_combined",
        "description": "Joint log + progress-trace verdict.",
        "inputSchema": {
            "type": "object",
            "properties": {
                "text": {"type": "string"},
                "markers": {"type": "object"},
            },
            "required": ["text", "markers"],
        },
    },
]


def _parse_markers(raw: Dict) -> Dict[int, Optional[ProgressMarker]]:
    return {
        int(r): (ProgressMarker(**m) if isinstance(m, dict) else None)
        for r, m in raw.items()
    }


def call_tool(name: str, args: Dict[str, Any]) -> Dict[str, Any]:
    if name == "analyze_log":
        analyzer = LogAnalyzer()
        if args.get("text") is not None:
            verdict = analyzer.analyze_text(args["text"])
        elif args.get("path"):
            verdict = analyzer.analyze_file(args["path"])
        else:
            raise ValueError("need 'text' or 'path'")
        return {
            "category": verdict.category.value,
            "should_resume": verdict.should_resume,
            "confidence": verdict.confidence,
            "culprit_ranks": verdict.culprit_ranks,
            "summary": verdict.summary,
            "evidence": verdict.evidence[:10],
        }
    if name == "analyze_trace":
        result = analyze_markers(
            _parse_markers(args["markers"]),
            stale_after_s=args.get("stale_after_s", 30.0),
        )
    elif name == "analyze_combined":
        result = analyze_combined(args["text"], _parse_markers(args["markers"]))
    else:
        raise ValueError(f"unknown tool {name}")
    return {
        "category": result.category,
        "should_resume": result.should_resume,
        "confidence": result.confidence,
        "culprit_ranks": result.culprit_ranks,
        "summary": result.summary,
    }


def handle_request(req: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """One JSON-RPC request -> response dict (None for notifications)."""
    method = req.get("method")
    msg_id = req.get("id")
    if method == "initialize":
        result = {
            "protocolVersion": PROTOCOL_VERSION,
            "capabilities": {"tools": {}},
            "serverInfo": {"name": "tpurx-attribution", "version": "0.1.0"},
        }
    elif method == "notifications/initialized":
        return None
    elif method == "tools/list":
        result = {"tools": TOOLS}
    elif method == "tools/call":
        params = req.get("params", {})
        try:
            out = call_tool(params.get("name", ""), params.get("arguments", {}))
            result = {
                "content": [{"type": "text", "text": json.dumps(out)}],
                "isError": False,
            }
        except Exception as exc:  # noqa: BLE001 - tool errors go to the model
            result = {
                "content": [{"type": "text", "text": f"error: {exc}"}],
                "isError": True,
            }
    elif method == "ping":
        result = {}
    else:
        if msg_id is None:
            return None  # unknown notification: ignore
        return {
            "jsonrpc": "2.0",
            "id": msg_id,
            "error": {"code": -32601, "message": f"method not found: {method}"},
        }
    if msg_id is None:
        return None
    return {"jsonrpc": "2.0", "id": msg_id, "result": result}


def serve_stdio(stdin=None, stdout=None) -> None:
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        resp = handle_request(req)
        if resp is not None:
            stdout.write(json.dumps(resp) + "\n")
            stdout.flush()


if __name__ == "__main__":
    serve_stdio()
