"""Failure attribution (reference: ``attribution/`` minus straggler).

- :class:`AttributionPipeline` — composable preprocess → attribute →
  postprocess pipeline (reference ``base.py:95-300``).
- :mod:`log_analyzer` — rule-based error extraction + root-cause + resume
  verdict from worker/cycle logs; an LLM backend (``llm.py``, reference
  ``log_analyzer/nvrx_logsage.py``) plugs in as ``llm_fn`` and is consulted
  per the analyzer's ``consult_llm`` mode.
- :class:`AnalysisEngine` — multi-analysis DAG scheduling over one failure
  submission (reference ``analyzer/engine.py``).
"""

from .base import AttributionPipeline, AttributionResult
from .engine import AnalysisEngine, AnalysisSpec, default_engine
from .llm import LLMClient, llm_from_env
from .log_analyzer import LogAnalyzer, FailureCategory, AnalysisVerdict

__all__ = [
    "AttributionPipeline",
    "AttributionResult",
    "AnalysisEngine",
    "AnalysisSpec",
    "default_engine",
    "LLMClient",
    "llm_from_env",
    "LogAnalyzer",
    "FailureCategory",
    "AnalysisVerdict",
]
