"""Failure attribution (reference: ``attribution/`` minus straggler).

- :class:`AttributionPipeline` — composable preprocess → attribute →
  postprocess pipeline (reference ``base.py:95-300``).
- :mod:`log_analyzer` — rule-based error extraction + root-cause + resume
  verdict from worker/cycle logs (the reference's LogSage/LLM analyzer is an
  optional extra there too; the rule engine is the always-on layer, and an
  LLM backend can be injected as a callable).
"""

from .base import AttributionPipeline, AttributionResult
from .log_analyzer import LogAnalyzer, FailureCategory, AnalysisVerdict

__all__ = [
    "AttributionPipeline",
    "AttributionResult",
    "LogAnalyzer",
    "FailureCategory",
    "AnalysisVerdict",
]
