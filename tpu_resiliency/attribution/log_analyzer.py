"""Rule-based log analysis: error extraction, root cause, resume verdict.

Reference analog: ``attribution/log_analyzer/`` (LogSage + langchain LLM).
The always-available layer here is a rule engine tuned for JAX/TPU failure
modes; an LLM backend can be plugged in as ``llm_fn(prompt) -> str`` and is
consulted only when rules are inconclusive (same layering the reference
uses — its LLM deps are optional extras).

Categories and their restart policy:

=================  ===========================================  ==========
category           signature examples                           resume?
=================  ===========================================  ==========
device_error       "TPU initialization failed", RESOURCE_        yes (new
                   EXHAUSTED: HBM, halted, DMA error             chip/node)
oom_host           MemoryError, Killed (oom-kill)                no
oom_hbm            RESOURCE_EXHAUSTED ... hbm / allocating       no
numerics           loss is NaN/Inf assertions                    no
data               FileNotFoundError/dataset errors              no
preemption         SIGTERM from scheduler, preemption notice     yes
network            DCN/collective timeout, socket errors         yes
hang_kill          tpurx hang detection kill markers             yes
user_code          generic Python traceback                      no
unknown            nothing matched                               yes
=================  ===========================================  ==========
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .base import AttributionPipeline, AttributionResult

log = get_logger("log_analyzer")


class FailureCategory(str, enum.Enum):
    DEVICE_ERROR = "device_error"
    OOM_HOST = "oom_host"
    OOM_HBM = "oom_hbm"
    NUMERICS = "numerics"
    DATA = "data"
    PREEMPTION = "preemption"
    NETWORK = "network"
    HANG_KILL = "hang_kill"
    USER_CODE = "user_code"
    UNKNOWN = "unknown"


@dataclasses.dataclass
class AnalysisVerdict:
    category: FailureCategory
    should_resume: bool
    confidence: float
    culprit_ranks: List[int]
    evidence: List[str]
    summary: str


# (category, resume, confidence, patterns) — first match wins per line;
# highest-confidence category across lines wins overall.
_RULES: List[Tuple[FailureCategory, bool, float, List[str]]] = [
    (FailureCategory.OOM_HBM, False, 0.95, [
        r"RESOURCE_EXHAUSTED.{0,120}(hbm|HBM|memory)",
        r"Out of memory while trying to allocate",
        r"XlaRuntimeError.{0,80}RESOURCE_EXHAUSTED",
    ]),
    (FailureCategory.OOM_HOST, False, 0.9, [
        r"\bMemoryError\b",
        r"oom-kill|Out of memory: Killed process|oom_reaper",
    ]),
    (FailureCategory.DEVICE_ERROR, True, 0.9, [
        r"TPU.{0,60}(initialization failed|halted|unavailable|unhealthy)",
        r"(DMA|SparseCore|MXU).{0,40}error",
        r"failed to query tpu|libtpu.{0,40}(error|abort)",
        r"INTERNAL:.{0,80}(device|chip)",
    ]),
    (FailureCategory.HANG_KILL, True, 0.9, [
        r"hang detected.{0,120}terminating rank",
        r"wedged for .*killing",
        r"pod heartbeat stale",
    ]),
    (FailureCategory.NUMERICS, False, 0.85, [
        r"loss (is|became) (nan|inf)",
        r"\bNaN\b.{0,40}(loss|grad)",
        r"FloatingPointError",
    ]),
    (FailureCategory.PREEMPTION, True, 0.85, [
        r"preempt(ed|ion)",
        r"received SIGTERM.{0,60}(scheduler|maintenance)",
        r"DUE TO .*MAINTENANCE",
    ]),
    (FailureCategory.NETWORK, True, 0.8, [
        r"(DEADLINE_EXCEEDED|UNAVAILABLE):.{0,120}",
        r"collective.{0,60}timed? ?out",
        r"(ConnectionResetError|BrokenPipeError|ConnectionRefusedError)",
        r"store op \w+ (failed|timed out)",
    ]),
    (FailureCategory.DATA, False, 0.8, [
        r"FileNotFoundError",
        r"(dataset|tfrecord|arrayrecord).{0,60}(corrupt|missing|error)",
    ]),
    (FailureCategory.USER_CODE, False, 0.5, [
        r"Traceback \(most recent call last\)",
    ]),
]

_RANK_RE = re.compile(r"\[r(\d+)\]|rank[=\s](\d+)", re.IGNORECASE)


class LogAnalyzer:
    """``consult_llm`` modes (reference LogSage layering,
    ``log_analyzer/nvrx_logsage.py:12-40``):

    - ``"fallback"`` (default): LLM consulted only when no rule matched;
    - ``"always"``: LLM sees the rule verdict too and may confirm (confidence
      boost) or override it (override taken only when the LLM is MORE
      confident than the rules);
    - ``"never"``: rules only, even if ``llm_fn`` is set.
    """

    def __init__(
        self,
        llm_fn: Optional[Callable[[str], str]] = None,
        context_lines: int = 3,
        consult_llm: str = "fallback",
    ):
        if consult_llm not in ("never", "fallback", "always"):
            raise ValueError(f"consult_llm must be never|fallback|always, got {consult_llm!r}")
        self.llm_fn = llm_fn
        self.context_lines = context_lines
        self.consult_llm = consult_llm
        self.pipeline = AttributionPipeline(
            attribute=self._attribute,
            preprocess=[self._extract_errors],
            name="log_analyzer",
        )

    # -- stages ------------------------------------------------------------

    def _extract_errors(self, text: str, ctx: Dict) -> List[Tuple[int, str]]:
        """Return (line_no, line) candidates worth matching (error-ish)."""
        lines = text.splitlines()
        ctx["all_lines"] = lines
        interesting = []
        for i, line in enumerate(lines):
            if re.search(
                r"error|fail|abort|kill|exceed|exhaust|timeout|traceback|nan|preempt|hang|stale",
                line, re.IGNORECASE,
            ):
                interesting.append((i, line))
        ctx["n_candidates"] = len(interesting)
        return interesting

    def _attribute(self, candidates: List[Tuple[int, str]], ctx: Dict) -> AttributionResult:
        best: Optional[Tuple[FailureCategory, bool, float]] = None
        evidence: List[str] = []
        ranks: List[int] = []
        for lineno, line in candidates:
            for category, resume, conf, patterns in _RULES:
                if any(re.search(p, line, re.IGNORECASE) for p in patterns):
                    if best is None or conf > best[2]:
                        best = (category, resume, conf)
                    evidence.append(f"L{lineno}: {line.strip()[:240]}")
                    m = _RANK_RE.search(line)
                    if m:
                        rank = int(next(g for g in m.groups() if g is not None))
                        if rank not in ranks:
                            ranks.append(rank)
                    break
        llm_on = self.llm_fn is not None and self.consult_llm != "never"
        if best is None:
            if llm_on and candidates:
                llm = self._llm_attribute(candidates, ctx, rule_verdict=None)
                if llm is not None:
                    return llm
            return AttributionResult(
                category=FailureCategory.UNKNOWN.value,
                confidence=0.1,
                summary="no known failure signature found",
                should_resume=True,
            )
        category, resume, conf = best
        result = AttributionResult(
            category=category.value,
            confidence=conf,
            culprit_ranks=sorted(ranks),
            summary=f"{category.value} ({len(evidence)} matching lines)",
            evidence=evidence[:20],
            should_resume=resume,
        )
        if llm_on and self.consult_llm == "always":
            rule_verdict = {
                "category": result.category,
                "should_resume": result.should_resume,
                "confidence": result.confidence,
            }
            llm = self._llm_attribute(candidates, ctx, rule_verdict=rule_verdict)
            if llm is not None:
                if llm.category == result.category:
                    result.confidence = min(0.99, max(result.confidence, llm.confidence) + 0.05)
                    result.summary += f"; llm concurs: {llm.summary}"
                    result.culprit_ranks = sorted(
                        set(result.culprit_ranks) | set(llm.culprit_ranks)
                    )
                elif (
                    llm.category != FailureCategory.UNKNOWN.value
                    and llm.confidence > result.confidence
                ):
                    # a hallucinated (out-of-taxonomy -> unknown) category
                    # must never displace a concrete rule verdict
                    llm.summary += f" (overrode rules' {result.category})"
                    llm.evidence = result.evidence
                    result = llm
                ctx["llm_consulted"] = True
        return result

    def _llm_attribute(self, candidates, ctx, rule_verdict=None) -> Optional[AttributionResult]:
        from .llm import build_attribution_prompt, parse_attribution_response

        try:
            answer = self.llm_fn(build_attribution_prompt(candidates, rule_verdict))
            parsed = parse_attribution_response(answer)
        except Exception:  # noqa: BLE001
            log.exception("llm attribution failed; falling back to rules")
            return None
        if parsed is None:
            log.warning("unparseable llm attribution response: %.200s", answer)
            return None
        known = parsed["category"] in FailureCategory._value2member_map_
        return AttributionResult(
            category=parsed["category"] if known else FailureCategory.UNKNOWN.value,
            confidence=parsed["confidence"],
            culprit_ranks=parsed["culprit_ranks"],
            summary=parsed["reason"] or "llm attribution",
            should_resume=parsed["should_resume"],
            extra={"source": "llm"},
        )

    # -- public ------------------------------------------------------------

    def analyze_text(self, text: str) -> AnalysisVerdict:
        result = self.pipeline.run(text)
        return AnalysisVerdict(
            category=FailureCategory(result.category)
            if result.category in FailureCategory._value2member_map_
            else FailureCategory.UNKNOWN,
            should_resume=result.should_resume,
            confidence=result.confidence,
            culprit_ranks=result.culprit_ranks,
            evidence=result.evidence,
            summary=result.summary,
        )

    def analyze_file(self, path: str, tail_bytes: int = 1 << 20) -> AnalysisVerdict:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - tail_bytes))
            text = f.read().decode(errors="replace")
        return self.analyze_text(text)
