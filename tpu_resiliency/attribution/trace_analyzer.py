"""Collective-progress trace analysis: find the rank that stalled the job.

Reference analog: ``attribution/trace_analyzer/fr_attribution.py`` (1578 LoC)
— NVRx parses PyTorch Flight-Recorder NCCL traces and finds the ranks whose
missing/mismatched collectives wedged everyone else.

JAX exposes no per-collective recorder, so the TPU design records progress at
the **step boundary**, which is where SPMD programs synchronize anyway: each
rank periodically publishes a tiny ``ProgressMarker`` (iteration, step,
phase, timestamp) through the store (or carries it in per-cycle logs).  When
the job wedges, the analyzer compares markers:

- a rank whose step lags the quorum → the straggler/wedged rank (everyone
  else is parked inside the collective waiting for it);
- ranks at the same step but a different phase → mismatched program
  (the SPMD analog of NVRx's "mismatched collective" verdict);
- a rank with no marker at all → died before reporting.
"""

from __future__ import annotations

import dataclasses
import json
import time
from collections import Counter
from typing import Dict, Optional

from ..utils.logging import get_logger
from .base import AttributionResult

log = get_logger("trace_analyzer")


@dataclasses.dataclass
class ProgressMarker:
    rank: int
    iteration: int      # restart-loop iteration (in-process ring)
    step: int           # training step
    phase: str = "step" # current phase/section name
    ts: float = 0.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, raw) -> "ProgressMarker":
        return cls(**json.loads(raw if isinstance(raw, str) else raw.decode()))


def parse_markers(raw) -> Dict[int, Optional["ProgressMarker"]]:
    """Parse the wire form ``{rank: markerObject | null}`` with validation.

    The single parser behind attrsvc's /analyze_trace, /analyze_combined and
    the analysis engine's trace analysis — raises ``ValueError`` (with a
    client-presentable message) on any malformed input.
    """
    if raw is None:
        raw = {}
    if not isinstance(raw, dict):
        raise ValueError("markers must be an object of rank -> marker|null")
    out: Dict[int, Optional[ProgressMarker]] = {}
    for r, m in raw.items():
        try:
            rank = int(r)
        except (TypeError, ValueError):
            raise ValueError(f"bad rank key {r!r}") from None
        if m is None:
            out[rank] = None
        elif isinstance(m, dict):
            try:
                out[rank] = ProgressMarker(**m)
            except TypeError as exc:
                raise ValueError(f"bad marker for rank {rank}: {exc}") from None
        else:
            raise ValueError(
                f"bad marker for rank {rank}: expected object or null"
            )
    return out


class ProgressTraceRecorder:
    """Rank-side: publish a marker every ``every`` steps (one tiny store
    write; off the step critical path when called after dispatch)."""

    def __init__(self, store, rank: int, namespace: str = "trace", every: int = 1):
        self.store = store
        self.rank = rank
        self.ns = namespace
        self.every = every

    def record(self, step: int, iteration: int = 0, phase: str = "step") -> None:
        if step % self.every:
            return
        marker = ProgressMarker(
            rank=self.rank, iteration=iteration, step=step, phase=phase,
            ts=time.time(),
        )
        self.store.set(f"{self.ns}/marker/{self.rank}", marker.to_json())


def collect_markers(store, world_size: int, namespace: str = "trace") -> Dict[int, Optional[ProgressMarker]]:
    out: Dict[int, Optional[ProgressMarker]] = {}
    for r in range(world_size):
        raw = store.try_get(f"{namespace}/marker/{r}")
        out[r] = ProgressMarker.from_json(raw) if raw else None
    return out


def analyze_markers(
    markers: Dict[int, Optional[ProgressMarker]],
    stale_after_s: float = 30.0,
    now: Optional[float] = None,
) -> AttributionResult:
    """Identify culprit ranks from a snapshot of progress markers."""
    now = time.time() if now is None else now
    present = {r: m for r, m in markers.items() if m is not None}
    missing = sorted(r for r, m in markers.items() if m is None)
    if not present:
        return AttributionResult(
            category="no_data", confidence=0.2, culprit_ranks=missing,
            summary="no rank published progress markers", should_resume=True,
        )
    steps = Counter(m.step for m in present.values())
    quorum_step, _ = steps.most_common(1)[0]
    behind = sorted(r for r, m in present.items() if m.step < quorum_step)
    stale = sorted(r for r, m in present.items() if now - m.ts > stale_after_s)
    phases_at_quorum = {m.phase for m in present.values() if m.step == quorum_step}
    evidence = [
        f"r{r}: step={m.step} phase={m.phase} age={now - m.ts:.1f}s"
        for r, m in sorted(present.items())
    ][:32]

    if missing:
        return AttributionResult(
            category="dead_rank", confidence=0.85,
            culprit_ranks=missing,
            summary=f"ranks {missing} never reported progress",
            evidence=evidence, should_resume=True,
        )
    if behind:
        return AttributionResult(
            category="lagging_rank", confidence=0.9,
            culprit_ranks=behind,
            summary=(
                f"ranks {behind} behind quorum step {quorum_step} — peers are "
                "blocked in a collective waiting for them"
            ),
            evidence=evidence, should_resume=True,
        )
    if len(phases_at_quorum) > 1:
        return AttributionResult(
            category="mismatched_program", confidence=0.7,
            culprit_ranks=[],
            summary=f"ranks at step {quorum_step} disagree on phase: {sorted(phases_at_quorum)}",
            evidence=evidence, should_resume=False,
        )
    if stale:
        return AttributionResult(
            category="collective_stall", confidence=0.75,
            culprit_ranks=stale,
            summary=f"all ranks at step {quorum_step} but {stale} stopped progressing",
            evidence=evidence, should_resume=True,
        )
    return AttributionResult(
        category="healthy", confidence=0.6, culprit_ranks=[],
        summary=f"all ranks at step {quorum_step}", evidence=evidence,
        should_resume=True,
    )


def analyze_fingerprints(
    tails: Dict[int, Optional[list]],
    min_lag_ms: float = 400.0,
) -> AttributionResult:
    """Name the wedged collective and the lagging rank from at-abort
    dispatch-tail fingerprints (``{rank: [{"op", "age_ms", "seq"}, ...]}``,
    as gathered by ``InprocStore.get_fingerprints``).

    The SPMD reading of a wedged collective: every healthy rank dispatched
    the same program and then *stopped dispatching* — parked inside it
    waiting for the laggard — so their newest entries share an op name with
    comparable ages; the culprit either stopped dispatching at least
    ``min_lag_ms`` before the freshest peer (an absolute gap: detection
    latency separates the laggard's last dispatch from its peers', and the
    gap *grows* with host slowness, so the rule is timing-robust) or never
    reached the op at all (a different newest op, or no tail published —
    died/wedged before the dump).

    This is the consumer half of the reference's Flight-Recorder pipeline
    (``attribution/trace_analyzer/fr_attribution.py``): dump at abort,
    attribute from the dumps.
    """
    present = {r: t for r, t in tails.items() if t}
    missing = sorted(r for r, t in tails.items() if not t)
    if not present:
        return AttributionResult(
            category="no_data", confidence=0.2, culprit_ranks=missing,
            summary="no rank published an at-abort fingerprint",
            should_resume=True, extra={"op": ""},
        )
    newest = {r: max(t, key=lambda e: e.get("seq", 0)) for r, t in present.items()}
    ops = Counter(e.get("op", "?") for e in newest.values())
    wedged_op, op_votes = ops.most_common(1)[0]
    evidence = [
        f"r{r}: last_op={e.get('op', '?')} age={e.get('age_ms', 0)}ms "
        f"seq={e.get('seq', 0)}"
        for r, e in sorted(newest.items())
    ][:32]
    # ranks that never reached the quorum op
    divergent = sorted(
        r for r, e in newest.items() if e.get("op", "?") != wedged_op
    )
    in_op = {r: e for r, e in newest.items() if e.get("op", "?") == wedged_op}
    ages = sorted(float(e.get("age_ms", 0)) for e in in_op.values())
    base_age = ages[0] if ages else 0.0
    laggards = sorted(
        r for r, e in in_op.items()
        if float(e.get("age_ms", 0)) - base_age >= min_lag_ms
    )
    if missing and op_votes >= max(1, len(present)):
        return AttributionResult(
            category="wedged_collective", confidence=0.85,
            culprit_ranks=missing,
            summary=(
                f"in-flight op '{wedged_op}': ranks {missing} published no "
                "fingerprint (wedged in the device call or dead) while "
                f"{sorted(in_op)} are parked in it"
            ),
            evidence=evidence, should_resume=True,
            extra={"op": wedged_op, "variant": "missing"},
        )
    if divergent:
        return AttributionResult(
            category="wedged_collective", confidence=0.8,
            culprit_ranks=divergent,
            summary=(
                f"in-flight op '{wedged_op}': ranks {divergent} never "
                f"dispatched it (last ops "
                f"{[newest[r].get('op') for r in divergent]}) — peers are "
                "blocked waiting for them"
            ),
            evidence=evidence, should_resume=True,
            extra={"op": wedged_op, "variant": "divergent"},
        )
    if laggards and len(in_op) > len(laggards):
        return AttributionResult(
            category="wedged_collective", confidence=0.85,
            culprit_ranks=laggards,
            summary=(
                f"in-flight op '{wedged_op}': ranks {laggards} stopped "
                f"dispatching >= {min_lag_ms:.0f}ms before the freshest "
                f"peer ({base_age:.0f}ms) — the lagging ranks peers are "
                "stuck on"
            ),
            evidence=evidence, should_resume=True,
            extra={"op": wedged_op, "variant": "laggards"},
        )
    return AttributionResult(
        category="collective_stall", confidence=0.5,
        culprit_ranks=missing,
        summary=(
            f"all ranks last dispatched '{wedged_op}' with comparable ages "
            "— pod-wide stall, no single laggard distinguishable"
        ),
        evidence=evidence, should_resume=True,
        extra={"op": wedged_op, "variant": "pod_wide"},
    )


# -- flight-recorder dump analysis -------------------------------------------


def analyze_flight_dump(records) -> Optional[str]:
    """One-line verdict over a flight-recorder black-box dump.

    Fed the parsed records of a single process's dump (the
    ``telemetry.flight`` dump-hook contract).  Answers the first question a
    responder asks of a black box: *what was this process doing when it
    tripped?* — the monitor section it died inside (begin without a matching
    end), the collective it dispatched but never settled, store
    retries/failovers in the tail, and the trip/abort context.  Returns
    ``None`` when the dump carries nothing actionable.
    """
    if not records:
        return None
    reason = ""
    open_sections: list = []
    pending_coll: Dict[tuple, dict] = {}
    last_hb_ns = None
    last_ns = None
    trip = None
    retries = 0
    failovers = 0
    stages: list = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        ev = rec.get("event", "")
        if ev == "_flight_meta":
            reason = str(rec.get("reason", "") or reason)
            continue
        t = rec.get("mono_ns")
        if isinstance(t, int):
            last_ns = t if last_ns is None else max(last_ns, t)
        if ev == "monitor.section_begin":
            open_sections.append(str(rec.get("section", "?")))
        elif ev == "monitor.section_end":
            name = str(rec.get("section", "?"))
            if name in open_sections:
                open_sections.remove(name)
        elif ev == "monitor.heartbeat":
            last_hb_ns = rec.get("mono_ns")
        elif ev == "collective.dispatch":
            pending_coll[(rec.get("op"), rec.get("axis"))] = rec
        elif ev == "collective.settle":
            pending_coll.pop((rec.get("op"), rec.get("axis")), None)
        elif ev == "monitor.trip":
            trip = rec
        elif ev == "store.op_retry":
            retries += 1
        elif ev == "store.failover":
            failovers += 1
        elif ev == "abort.stage":
            stages.append(f"{rec.get('stage')}={rec.get('outcome')}")
    parts = []
    if open_sections:
        parts.append(f"open section '{open_sections[-1]}'")
    if pending_coll:
        op, axis = next(reversed(pending_coll))
        parts.append(f"unsettled collective {op}@{axis}")
    if last_hb_ns is not None and last_ns is not None and last_ns > last_hb_ns:
        parts.append(
            f"last heartbeat {(last_ns - last_hb_ns) / 1e9:.1f}s before dump"
        )
    if trip is not None:
        parts.append(f"trip[{trip.get('interruptions', '')}]")
    if retries or failovers:
        parts.append(f"store retries={retries} failovers={failovers}")
    if stages:
        parts.append("abort stages: " + ",".join(stages[-4:]))
    if not parts:
        return None
    prefix = f"{reason}: " if reason else ""
    return prefix + "; ".join(parts)


# -- machine-readable degrade verdict ---------------------------------------


@dataclasses.dataclass
class DegradeVerdict:
    """The *acting* half of the at-abort verdict: which degrade-ladder rung
    the self-healing collective layer (``parallel/degrade.py``) should start
    at for the implicated op.  Consumed on the restart path by
    ``parallel.health.RouteHealth.apply_verdict`` — the first post-restart
    call of the named op starts at ``action`` instead of re-proving the
    dead rungs above it."""

    action: str                 # "retry" | "relayout" | "shrink" | "none"
    op: str = ""                # DispatchTail op identity
    axis: str = ""              # implicated mesh axis when known
    culprit_ranks: list = dataclasses.field(default_factory=list)
    reason: str = ""
    confidence: float = 0.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, raw) -> "DegradeVerdict":
        return cls(**json.loads(raw if isinstance(raw, str) else raw.decode()))


def degrade_verdict(result: AttributionResult) -> DegradeVerdict:
    """Map an :func:`analyze_fingerprints` result onto a degrade action.

    - ``wedged_collective`` with named culprits → **shrink**: a specific
      rank/link is implicated; the route needs the targeted teardown, not
      more deadline burns re-proving it;
    - ``collective_stall`` (pod-wide, no laggard distinguishable) →
      **relayout**: nothing to shrink around — re-trace/re-lane and go;
    - anything else (``no_data``, marker categories, healthy) → **none**.
    """
    op = str(result.extra.get("op", "") or "")
    if result.category == "wedged_collective" and op:
        return DegradeVerdict(
            action="shrink", op=op,
            culprit_ranks=list(result.culprit_ranks),
            reason=result.summary, confidence=result.confidence,
        )
    if result.category == "collective_stall" and op:
        return DegradeVerdict(
            action="relayout", op=op,
            culprit_ranks=list(result.culprit_ranks),
            reason=result.summary, confidence=result.confidence,
        )
    return DegradeVerdict(action="none", op=op, reason=result.summary,
                          confidence=result.confidence)
