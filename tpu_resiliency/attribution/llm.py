"""LLM attribution backend: OpenAI-compatible chat client + structured
failure-attribution prompting.

Reference analog: ``attribution/log_analyzer/nvrx_logsage.py:12-40`` — the
LogSage path (error extraction → root-cause attribution → auto-resume
decision) built on langchain/ChatOpenAI.  Rebuilt on stdlib HTTP against any
OpenAI-compatible endpoint (vLLM, llama.cpp server, a hosted API, or the
fake server in the tests), so the flagship attribution capability ships
working with zero extra dependencies.

Configuration (env, all optional — unset base URL disables the backend):

    TPURX_LLM_BASE_URL   e.g. http://localhost:8000/v1
    TPURX_LLM_API_KEY    bearer token (optional for local endpoints)
    TPURX_LLM_MODEL      model name passed through (default "default")
    TPURX_LLM_TIMEOUT_S  per-request timeout (default 30)

Usage::

    analyzer = LogAnalyzer(llm_fn=llm_from_env())       # None -> rules only
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..utils import env
from ..utils.logging import get_logger
from ..utils.retry import Retrier, RetryExhausted, RetryPolicy

log = get_logger("attribution.llm")


class LLMError(RuntimeError):
    pass


class LLMClient:
    """Minimal OpenAI-compatible ``/chat/completions`` client.

    Callable as ``client(prompt) -> str`` so it plugs directly into
    ``LogAnalyzer(llm_fn=...)``.
    """

    def __init__(
        self,
        base_url: str,
        api_key: str = "",
        model: str = "default",
        timeout_s: float = 30.0,
        max_retries: int = 2,
        temperature: float = 0.0,
        system_prompt: str = (
            "You are a distributed-training failure analyst for JAX/TPU "
            "workloads. Answer concisely and exactly in the requested format."
        ),
    ):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.model = model
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.temperature = temperature
        self.system_prompt = system_prompt

    def chat(self, messages: List[Dict[str, str]]) -> str:
        payload = json.dumps(
            {
                "model": self.model,
                "messages": messages,
                "temperature": self.temperature,
            }
        ).encode()
        headers = {"Content-Type": "application/json"}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        url = f"{self.base_url}/chat/completions"
        retrier = Retrier("llm_chat", RetryPolicy(
            max_attempts=self.max_retries + 1, base_delay=0.5, max_delay=2.0))
        while True:
            try:
                req = urllib.request.Request(url, data=payload, headers=headers)
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    body = json.loads(resp.read().decode())
                return body["choices"][0]["message"]["content"]
            except urllib.error.HTTPError as exc:
                if 400 <= exc.code < 500:
                    # misconfiguration (bad key/model/path) — retrying only
                    # adds dead time to every attribution and hides the status
                    raise LLMError(f"HTTP {exc.code} from {url}: {exc.reason}")
                self._backoff(retrier, exc)
            except (urllib.error.URLError, OSError, KeyError, IndexError,
                    json.JSONDecodeError) as exc:
                self._backoff(retrier, exc)

    @staticmethod
    def _backoff(retrier: Retrier, exc: Exception) -> None:
        try:
            retrier.backoff(exc)
        except RetryExhausted as spent:
            raise LLMError(
                f"chat completion failed after retries: {spent.last_exc!r}"
            ) from exc

    def __call__(self, prompt: str) -> str:
        return self.chat(
            [
                {"role": "system", "content": self.system_prompt},
                {"role": "user", "content": prompt},
            ]
        )


def llm_from_env() -> Optional[LLMClient]:
    """Build the client from ``TPURX_LLM_*`` env; None when unconfigured."""
    base_url = env.LLM_BASE_URL.get().strip()
    if not base_url:
        return None
    return LLMClient(
        base_url=base_url,
        api_key=env.LLM_API_KEY.get(),
        model=env.LLM_MODEL.get(),
        timeout_s=env.LLM_TIMEOUT_S.get(),
    )


# -- structured attribution prompting ----------------------------------------

ATTRIBUTION_PROMPT = """\
A distributed JAX/TPU training job failed. Below are the error-candidate log
lines (with original line numbers) extracted by a rule engine{rules_note}.

Known categories and whether an automatic restart can help:
  device_error (resume), oom_host (no), oom_hbm (no), numerics (no),
  data (no), preemption (resume), network (resume), hang_kill (resume),
  user_code (no), unknown (resume)

Respond with ONLY a JSON object, no prose:
{{"category": "<one of the categories above>",
  "should_resume": true/false,
  "confidence": <0.0-1.0>,
  "culprit_ranks": [<rank ints, [] if unknown>],
  "reason": "<one line root cause>"}}

Log lines:
{lines}
"""


def build_attribution_prompt(
    candidates: List, rule_verdict: Optional[dict] = None, max_lines: int = 60
) -> str:
    """Prompt from the rule engine's extracted candidates (and, when the
    rules DID match, their verdict — the LLM then confirms/overrides)."""
    lines = "\n".join(
        f"L{lineno}: {line.strip()[:300]}" for lineno, line in candidates[:max_lines]
    )
    rules_note = ""
    if rule_verdict:
        rules_note = (
            f"; the rule engine's own verdict was {json.dumps(rule_verdict)} "
            "— confirm or override it"
        )
    return ATTRIBUTION_PROMPT.format(rules_note=rules_note, lines=lines)


_JSON_RE = re.compile(r"\{.*\}", re.DOTALL)


def parse_attribution_response(answer: str) -> Optional[dict]:
    """Extract + validate the JSON verdict from a model response (models wrap
    JSON in prose/markdown fences routinely)."""
    m = _JSON_RE.search(answer)
    if not m:
        return None
    try:
        obj = json.loads(m.group(0))
    except json.JSONDecodeError:
        return None
    if not isinstance(obj, dict) or "category" not in obj:
        return None
    # tolerate mistyped fields (confidence: "high", culprit_ranks: null):
    # a model that produced valid JSON with a category is worth salvaging
    try:
        confidence = max(0.0, min(1.0, float(obj.get("confidence", 0.5))))
    except (TypeError, ValueError):
        confidence = 0.5
    raw_ranks = obj.get("culprit_ranks")
    if not isinstance(raw_ranks, (list, tuple)):
        raw_ranks = []
    out = {
        "category": str(obj.get("category", "unknown")).strip().lower(),
        "should_resume": bool(obj.get("should_resume", True)),
        "confidence": confidence,
        "culprit_ranks": sorted(
            int(r) for r in raw_ranks if isinstance(r, (int, float))
        ),
        "reason": str(obj.get("reason", ""))[:500],
    }
    return out
