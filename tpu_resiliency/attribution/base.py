"""Attribution pipeline framework (reference ``attribution/base.py:95-300``).

A pipeline is preprocess* → attribute → postprocess*: callables chained over
a typed payload, each stage able to annotate the shared context.  Stages are
plain callables ``(payload, ctx) -> payload``; the attribute stage returns an
:class:`AttributionResult`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import get_logger

log = get_logger("attribution")


@dataclasses.dataclass
class AttributionResult:
    category: str
    confidence: float
    culprit_ranks: List[int] = dataclasses.field(default_factory=list)
    summary: str = ""
    evidence: List[str] = dataclasses.field(default_factory=list)
    should_resume: bool = True
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


class AttributionPipeline:
    def __init__(
        self,
        attribute: Callable[[Any, Dict], AttributionResult],
        preprocess: Optional[List[Callable[[Any, Dict], Any]]] = None,
        postprocess: Optional[List[Callable[[AttributionResult, Dict], AttributionResult]]] = None,
        name: str = "attribution",
    ):
        self.name = name
        self.preprocess = preprocess or []
        self.attribute = attribute
        self.postprocess = postprocess or []

    def run(self, payload: Any, ctx: Optional[Dict] = None) -> AttributionResult:
        ctx = ctx if ctx is not None else {}
        ctx.setdefault("pipeline", self.name)
        ctx.setdefault("started_at", time.time())
        for stage in self.preprocess:
            payload = stage(payload, ctx)
        result = self.attribute(payload, ctx)
        for stage in self.postprocess:
            result = stage(result, ctx)
        ctx["finished_at"] = time.time()
        return result
