"""Attribution postprocessing: operator notifications.

Reference analog: ``attribution/postprocessing/slack.py`` — push verdicts to
a webhook so operators see failures without tailing logs.  Generic webhook
poster (Slack-compatible payload shape), usable as an
:class:`AttributionPipeline` postprocess stage:

    pipeline = AttributionPipeline(attribute=..., postprocess=[
        WebhookNotifier(os.environ["SLACK_WEBHOOK_URL"],
                        only_categories={"oom_hbm", "numerics"}),
    ])
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional, Set

from ..utils.logging import get_logger
from .base import AttributionResult

log = get_logger("notify")


def format_verdict(result: AttributionResult, job: str = "") -> str:
    lines = [
        f"*{job or 'training job'}*: `{result.category}` "
        f"(confidence {result.confidence:.0%})",
        result.summary,
        f"culprit ranks: {result.culprit_ranks or 'n/a'}",
        f"auto-resume: {'yes' if result.should_resume else 'NO — operator action needed'}",
    ]
    return "\n".join(lines)


class WebhookNotifier:
    """POSTs ``{"text": ...}`` (Slack-compatible) per verdict."""

    def __init__(
        self,
        webhook_url: str,
        job: str = "",
        only_categories: Optional[Set[str]] = None,
        min_confidence: float = 0.0,
        timeout: float = 10.0,
    ):
        self.url = webhook_url
        self.job = job
        self.only_categories = only_categories
        self.min_confidence = min_confidence
        self.timeout = timeout

    def __call__(self, result: AttributionResult, ctx=None) -> AttributionResult:
        if self.only_categories and result.category not in self.only_categories:
            return result
        if result.confidence < self.min_confidence:
            return result
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps({"text": format_verdict(result, self.job)}).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=self.timeout).read()
        except Exception as exc:  # noqa: BLE001 - notification loss is not fatal
            log.warning("webhook notification failed: %s", exc)
        return result
