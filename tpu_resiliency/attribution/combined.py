"""Combined log + progress-trace attribution.

Reference analog: ``attribution/combined_log_fr/`` (448 LoC): joins the log
analysis with the flight-recorder analysis into a single verdict.  Here the
two signals are the rule-based log verdict and the progress-marker trace
verdict; combination rules:

- agreement on culprit ranks boosts confidence;
- a non-survivable log category (OOM/NaN/data) overrides the trace's
  resume=True (restarting cannot fix a deterministic failure);
- a trace-only culprit with an "unknown" log verdict yields a device-suspect
  verdict (the wedged rank logged nothing — typical for chip hangs).
"""

from __future__ import annotations

from typing import Dict, Optional

from .base import AttributionResult
from .log_analyzer import AnalysisVerdict, FailureCategory, LogAnalyzer
from .trace_analyzer import ProgressMarker, analyze_markers


def combine(
    log_verdict: AnalysisVerdict, trace_result: AttributionResult
) -> AttributionResult:
    culprits = sorted(set(log_verdict.culprit_ranks) | set(trace_result.culprit_ranks))
    agree = bool(
        set(log_verdict.culprit_ranks) & set(trace_result.culprit_ranks)
    )
    # deterministic failures dominate regardless of what the trace suggests
    if not log_verdict.should_resume and log_verdict.confidence >= 0.8:
        return AttributionResult(
            category=log_verdict.category.value,
            confidence=max(log_verdict.confidence, trace_result.confidence),
            culprit_ranks=culprits,
            summary=f"log: {log_verdict.summary}; trace: {trace_result.summary}",
            evidence=log_verdict.evidence + trace_result.evidence,
            should_resume=False,
        )
    if (
        log_verdict.category == FailureCategory.UNKNOWN
        and trace_result.culprit_ranks
    ):
        return AttributionResult(
            category="suspected_device_hang",
            confidence=min(0.95, trace_result.confidence + 0.05),
            culprit_ranks=trace_result.culprit_ranks,
            summary=(
                f"trace blames ranks {trace_result.culprit_ranks} and the log "
                "shows no error signature — silent device/host hang"
            ),
            evidence=trace_result.evidence,
            should_resume=True,
        )
    confidence = max(log_verdict.confidence, trace_result.confidence)
    if agree:
        confidence = min(0.99, confidence + 0.1)
    return AttributionResult(
        category=log_verdict.category.value
        if log_verdict.confidence >= trace_result.confidence
        else trace_result.category,
        confidence=confidence,
        culprit_ranks=culprits,
        summary=f"log: {log_verdict.summary}; trace: {trace_result.summary}",
        evidence=log_verdict.evidence + trace_result.evidence,
        should_resume=log_verdict.should_resume and trace_result.should_resume,
    )


def analyze_combined(
    log_text: str,
    markers: Dict[int, Optional[ProgressMarker]],
    llm_fn=None,
    stale_after_s: float = 30.0,
) -> AttributionResult:
    log_verdict = LogAnalyzer(llm_fn=llm_fn).analyze_text(log_text)
    trace_result = analyze_markers(markers, stale_after_s=stale_after_s)
    return combine(log_verdict, trace_result)
