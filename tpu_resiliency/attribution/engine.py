"""Multi-analysis scheduling engine.

Reference analog: ``attribution/analyzer/engine.py`` (812 LoC) — orchestrates
several analyses over one failure submission.  Here: a small DAG executor on
a thread pool.  Each analysis declares dependencies; dependent analyses
receive upstream RESULTS (the combined verdict reuses the log + trace
verdicts instead of recomputing them), failures are isolated per analysis,
and every analysis has its own timeout.

Built-in registry (``default_engine``):

    log       rule-engine (+optional LLM) log attribution
    trace     progress-marker trace attribution
    combined  joint verdict from log + trace results

Submissions are jobs: ``submit`` returns a job id immediately; ``result``
polls/waits.  ``run_all`` is the synchronous convenience.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from ..utils.logging import get_logger
from .base import AttributionResult

log = get_logger("attribution.engine")


@dataclasses.dataclass
class AnalysisSpec:
    """One analysis: ``fn(payload, upstream_results, ctx) -> AttributionResult``.

    ``applicable(payload) -> bool`` lets an analysis skip itself when its
    input is absent (e.g. trace analysis without markers)."""

    name: str
    fn: Callable[[dict, Dict[str, AttributionResult], dict], AttributionResult]
    depends_on: List[str] = dataclasses.field(default_factory=list)
    timeout_s: float = 120.0
    applicable: Callable[[dict], bool] = lambda payload: True


@dataclasses.dataclass
class Job:
    job_id: str
    payload: dict
    requested: List[str]
    results: Dict[str, AttributionResult] = dataclasses.field(default_factory=dict)
    errors: Dict[str, str] = dataclasses.field(default_factory=dict)
    skipped: List[str] = dataclasses.field(default_factory=list)
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    started_at: float = dataclasses.field(default_factory=time.time)
    finished_at: Optional[float] = None
    # guards results/errors/skipped: the runner writes while HTTP handler
    # threads snapshot via result()
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)


class AnalysisEngine:
    def __init__(self, specs: List[AnalysisSpec], max_workers: int = 4,
                 job_ttl_s: float = 3600.0):
        self.specs = {s.name: s for s in specs}
        for s in specs:
            for dep in s.depends_on:
                if dep not in self.specs:
                    raise ValueError(f"analysis {s.name!r} depends on unknown {dep!r}")
        self.max_workers = max_workers  # concurrent analyses per job wave
        self._jobs: Dict[str, Job] = {}
        self._lock = threading.Lock()
        self.job_ttl_s = job_ttl_s
        self.leaked_threads = 0  # timed-out analyses whose thread still runs

    # -- public -------------------------------------------------------------

    def submit(self, payload: dict, analyses: Optional[List[str]] = None) -> str:
        """Schedule analyses (dependency-closed) over one payload; returns a
        job id immediately."""
        requested = self._close_over_deps(analyses or list(self.specs))
        job = Job(job_id=uuid.uuid4().hex[:16], payload=payload, requested=requested)
        with self._lock:
            self._gc_jobs()
            self._jobs[job.job_id] = job
        # orchestration gets its own thread: a job runner blocking inside
        # the analysis pool would starve the analyses it is waiting for
        threading.Thread(
            target=self._run_job, args=(job,),
            name=f"tpurx-attr-job-{job.job_id[:6]}", daemon=True,
        ).start()
        return job.job_id

    def result(self, job_id: str, timeout: Optional[float] = None) -> Optional[dict]:
        with self._lock:
            self._gc_jobs()  # an idle service must not pin expired payloads
            job = self._jobs.get(job_id)
        if job is None:
            return None
        if timeout is not None:
            job.done.wait(timeout)
        return self._job_to_dict(job)

    def run_all(self, payload: dict, analyses: Optional[List[str]] = None,
                timeout: float = 300.0) -> dict:
        job_id = self.submit(payload, analyses)
        out = self.result(job_id, timeout=timeout)
        assert out is not None
        return out

    def shutdown(self) -> None:
        """Kept for API symmetry; analysis threads are daemons and die with
        the process."""

    # -- internals ----------------------------------------------------------

    def _close_over_deps(self, names: List[str]) -> List[str]:
        out: List[str] = []
        stack = list(names)
        while stack:
            name = stack.pop()
            if name not in self.specs:
                raise ValueError(f"unknown analysis {name!r}")
            if name in out:
                continue
            out.append(name)
            stack.extend(self.specs[name].depends_on)
        return out

    def _run_job(self, job: Job) -> None:
        ctx: dict = {"job_id": job.job_id, "stage_times": {}}
        pending = {n for n in job.requested}
        try:
            while pending:
                with job.lock:
                    ready = [
                        n for n in pending
                        if all(
                            d in job.results or d in job.errors or d in job.skipped
                            for d in self.specs[n].depends_on
                        )
                    ]
                if not ready:  # unreachable with validated deps; guard anyway
                    with job.lock:
                        for n in pending:
                            job.errors[n] = "dependency cycle"
                    break
                wave = []
                for name in ready:
                    pending.discard(name)
                    spec = self.specs[name]
                    try:
                        applicable = bool(spec.applicable(job.payload))
                    except Exception as exc:  # noqa: BLE001 - user predicate
                        with job.lock:
                            job.errors[name] = f"applicable() raised: {exc!r}"
                        continue
                    if not applicable:
                        with job.lock:
                            job.skipped.append(name)
                        continue
                    with job.lock:
                        upstream_failed = any(
                            d in job.errors for d in spec.depends_on
                        )
                    if upstream_failed:
                        with job.lock:
                            job.errors[name] = "upstream analysis failed"
                        continue
                    wave.append(spec)
                # one DEDICATED daemon thread per analysis: a wedged analysis
                # leaks its thread (counted) instead of permanently occupying
                # a shared pool worker and starving every later job
                for batch_start in range(0, len(wave), self.max_workers):
                    batch = wave[batch_start:batch_start + self.max_workers]
                    threads = []
                    for spec in batch:
                        box: dict = {}
                        t = threading.Thread(
                            target=self._run_one, args=(spec, job, ctx, box),
                            name=f"tpurx-attr-{spec.name}", daemon=True,
                        )
                        t.start()
                        threads.append((spec, t, box))
                    for spec, t, box in threads:
                        t.join(timeout=spec.timeout_s)
                        with job.lock:
                            if t.is_alive():
                                self.leaked_threads += 1
                                job.errors[spec.name] = (
                                    f"timed out after {spec.timeout_s}s "
                                    "(analysis thread abandoned)"
                                )
                            elif "error" in box:
                                job.errors[spec.name] = box["error"]
                            elif box.get("result") is None:
                                job.skipped.append(spec.name)
                            else:
                                job.results[spec.name] = box["result"]
        except Exception as exc:  # noqa: BLE001 - runner must never die silently
            log.exception("job %s runner failed", job.job_id)
            with job.lock:
                for n in pending:
                    job.errors.setdefault(n, f"job runner failed: {exc!r}")
        finally:
            job.finished_at = time.time()
            job.done.set()

    def _run_one(self, spec: AnalysisSpec, job: Job, ctx: dict, box: dict):
        t0 = time.monotonic()
        try:
            with job.lock:
                upstream = dict(job.results)
            box["result"] = spec.fn(job.payload, upstream, ctx)
        except Exception as exc:  # noqa: BLE001
            log.exception("analysis %s failed", spec.name)
            box["error"] = repr(exc)
        finally:
            ctx["stage_times"][spec.name] = time.monotonic() - t0

    def _gc_jobs(self) -> None:
        cutoff = time.time() - self.job_ttl_s  # tpurx: disable=TPURX016 -- TTL cutoff against wall finished_at stamps, not a measured duration
        for jid in [
            j for j, job in self._jobs.items()
            if job.finished_at is not None and job.finished_at < cutoff
        ]:
            del self._jobs[jid]

    @staticmethod
    def _job_to_dict(job: Job) -> dict:
        def res_dict(r: AttributionResult) -> dict:
            return {
                "category": r.category,
                "should_resume": r.should_resume,
                "confidence": r.confidence,
                "culprit_ranks": r.culprit_ranks,
                "summary": r.summary,
                "evidence": r.evidence[:20],
            }

        with job.lock:
            return {
                "job_id": job.job_id,
                "done": job.done.is_set(),
                "results": {n: res_dict(r) for n, r in job.results.items()},
                "errors": dict(job.errors),
                "skipped": list(job.skipped),
                "elapsed_s": round(
                    (job.finished_at or time.time()) - job.started_at, 3
                ),
            }


# -- built-in analyses -------------------------------------------------------


def _log_analysis(payload, upstream, ctx) -> Optional[AttributionResult]:
    from .log_analyzer import LogAnalyzer

    v = LogAnalyzer(
        llm_fn=payload.get("llm_fn"),
        consult_llm=payload.get("consult_llm", "fallback"),
    ).analyze_text(payload.get("text", ""))
    return AttributionResult(
        category=v.category.value,
        confidence=v.confidence,
        culprit_ranks=v.culprit_ranks,
        summary=v.summary,
        evidence=v.evidence,
        should_resume=v.should_resume,
    )


def _trace_analysis(payload, upstream, ctx) -> Optional[AttributionResult]:
    from .trace_analyzer import analyze_markers, parse_markers

    return analyze_markers(
        parse_markers(payload.get("markers")),
        stale_after_s=payload.get("stale_after_s", 30.0),
    )


def _combined_analysis(payload, upstream, ctx) -> Optional[AttributionResult]:
    from .combined import combine
    from .log_analyzer import AnalysisVerdict, FailureCategory

    log_res = upstream.get("log")
    trace_res = upstream.get("trace")
    if log_res is None or trace_res is None:
        return None
    log_verdict = AnalysisVerdict(
        category=FailureCategory(log_res.category)
        if log_res.category in FailureCategory._value2member_map_
        else FailureCategory.UNKNOWN,
        should_resume=log_res.should_resume,
        confidence=log_res.confidence,
        culprit_ranks=log_res.culprit_ranks,
        evidence=log_res.evidence,
        summary=log_res.summary,
    )
    return combine(log_verdict, trace_res)


def default_engine(max_workers: int = 4) -> AnalysisEngine:
    return AnalysisEngine(
        [
            AnalysisSpec(
                name="log", fn=_log_analysis,
                applicable=lambda p: bool(p.get("text")),
            ),
            AnalysisSpec(
                name="trace", fn=_trace_analysis,
                applicable=lambda p: bool(p.get("markers")),
            ),
            AnalysisSpec(
                name="combined", fn=_combined_analysis,
                depends_on=["log", "trace"],
                applicable=lambda p: bool(p.get("text")) and bool(p.get("markers")),
            ),
        ],
        max_workers=max_workers,
    )
