"""Resiliency telemetry plane.

- :mod:`.registry` — process-local metrics (counters / gauges / fixed-bucket
  ns histograms) with a no-op fast path under ``TPURX_TELEMETRY=0``;
- :mod:`.exporter` — OpenMetrics text over HTTP (per-rank scrape endpoint)
  or an atomically-rewritten textfile sink (``%r``/``%h`` expansion);
- :mod:`.aggregate` — cross-rank snapshot gather through the KV store with
  job-level sum/max/min reductions and per-rank outliers;
- :mod:`.trace` — ProfilingRecorder JSONL → Chrome-trace/Perfetto JSON
  (``python -m tpu_resiliency.telemetry.trace``).

See ``docs/observability.md`` for the metric catalog.
"""

from .registry import (
    BYTE_BUCKETS,
    DEFAULT_NS_BUCKETS,
    ENV_TELEMETRY,
    NOOP,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    gauge,
    get_registry,
    histogram,
    telemetry_enabled,
    valid_metric_name,
)

__all__ = [
    "BYTE_BUCKETS",
    "DEFAULT_NS_BUCKETS",
    "ENV_TELEMETRY",
    "NOOP",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "counter",
    "gauge",
    "get_registry",
    "histogram",
    "telemetry_enabled",
    "valid_metric_name",
]
