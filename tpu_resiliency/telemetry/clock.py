"""Store-mediated per-host clock-offset estimation (RTT-midpoint, NTP-style).

Every process records flight/profiling timestamps on its own
``time.monotonic_ns()`` — a clock domain that is meaningless across hosts.
This module estimates, per process, the offset from the local monotonic
clock to a shared *reference* clock (rank 0's monotonic domain, reached
through the control-plane store), so multi-host dumps can be merged onto
ONE aligned timeline by ``telemetry/trace.py``.

Protocol (two store keys + one counter, all under ``clock/``):

- the reference host runs :class:`ClockReference` — a daemon thread that
  blocks in ``wait_ge("clock/seq", n+1)`` server-side, then answers request
  ``n`` by publishing its own ``mono_ns`` under ``clock/resp/<n>``;
- a calibrating client runs :func:`calibrate`: per round it stamps ``t0``,
  claims a sequence number with an ADD, posts ``clock/req/<n>``, blocks on
  ``clock/resp/<n>``, stamps ``t1``, and computes the NTP-style midpoint
  estimate ``offset = ref_ns - (t0 + t1) / 2``.  The round with the
  smallest RTT wins (least queueing noise); its RTT bounds the error.

The estimate is held process-global (:func:`offset`) and embedded in every
flight dump and profiling meta record, where the trace merger applies it.

``TPURX_CLOCK_TEST_SKEW_NS`` injects an artificial skew into
:func:`mono_ns` (the stamp source shared by flight/profiling) so tests can
prove the estimator actually recovers and cancels a known offset.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ..utils import env
from ..utils.logging import get_logger

log = get_logger("telemetry.clock")

_SEQ_KEY = "clock/seq"
_REQ_KEY = "clock/req/{n}"
_RESP_KEY = "clock/resp/{n}"
_GC_LAG = 64  # settled req/resp keys older than this are deleted

_TEST_SKEW = 0
try:
    _TEST_SKEW = env.CLOCK_TEST_SKEW_NS.get()
except ValueError:
    _TEST_SKEW = 0

if _TEST_SKEW:
    def mono_ns() -> int:
        return time.monotonic_ns() + _TEST_SKEW
else:
    mono_ns = time.monotonic_ns


@dataclasses.dataclass(frozen=True)
class ClockOffset:
    """``local_mono + offset_ns`` lands in the reference clock domain."""

    offset_ns: int
    rtt_ns: int      # RTT of the winning round; error bound ~ rtt/2
    ref: str = "rank0"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_offset_lock = threading.Lock()
_offset: Optional[ClockOffset] = None


def offset() -> Optional[ClockOffset]:
    """The process's calibrated offset, or None when never calibrated."""
    with _offset_lock:
        return _offset


def set_offset(off: Optional[ClockOffset]) -> None:
    global _offset
    with _offset_lock:
        _offset = off


class ClockReference:
    """Reference-side responder: one answered probe per store sequence
    number, served in order from a daemon thread.  Run on exactly one
    process per job (rank 0 by convention); requests posted before the
    thread starts are answered from the counter backlog."""

    def __init__(self, store, poll_timeout: float = 0.5):
        # clone: the responder thread must not serialize behind the
        # owning process's own store traffic on a shared client lock
        self.store = store.clone()
        self.poll_timeout = poll_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._served = 0

    def start(self) -> "ClockReference":
        self._thread = threading.Thread(
            target=self._run, name="tpurx-clock-ref", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.poll_timeout * 4)
        try:
            self.store.close()
        except OSError:
            pass

    def _run(self) -> None:
        from ..store.client import StoreError, StoreTimeout

        while not self._stop.is_set():
            n = self._served + 1
            try:
                self.store.wait_ge(_SEQ_KEY, n, timeout=self.poll_timeout)
            except StoreTimeout:
                continue
            except (OSError, StoreError):
                if self._stop.is_set():
                    return
                time.sleep(self.poll_timeout)
                continue
            try:
                # blocking get: the client's ADD may land before its SET
                self.store.get(_REQ_KEY.format(n=n), timeout=2.0)
                self.store.set(
                    _RESP_KEY.format(n=n), str(time.monotonic_ns())
                )
                self.store.delete(_REQ_KEY.format(n=n))
                if n > _GC_LAG:
                    self.store.delete(_RESP_KEY.format(n=n - _GC_LAG))
            except (OSError, StoreError):
                pass  # a lost round is the client's timeout to absorb
            self._served = n


def calibrate(
    store,
    rounds: Optional[int] = None,
    round_timeout: float = 2.0,
    set_global: bool = True,
) -> ClockOffset:
    """RTT-midpoint offset estimation against the job's ClockReference.

    Raises ``StoreError``/``StoreTimeout`` when no responder answers
    within ``round_timeout`` per round — callers on the startup path
    should treat calibration as best-effort (dumps then simply carry no
    offset and the trace merger warns).
    """
    if rounds is None:
        rounds = env.CLOCK_CAL_ROUNDS.get()
    best: Optional[ClockOffset] = None
    for _ in range(max(1, rounds)):
        n = store.add(_SEQ_KEY, 1)
        t0 = mono_ns()
        store.set(_REQ_KEY.format(n=n), b"probe")
        raw = store.get(_RESP_KEY.format(n=n), timeout=round_timeout)
        t1 = mono_ns()
        ref_ns = int(raw)
        rtt = t1 - t0
        est = ClockOffset(offset_ns=ref_ns - (t0 + t1) // 2, rtt_ns=rtt)
        if best is None or rtt < best.rtt_ns:
            best = est
    assert best is not None
    if set_global:
        set_offset(best)
    log.debug(
        "clock calibrated: offset=%dns rtt=%dns", best.offset_ns, best.rtt_ns
    )
    return best


def serve_reference(store) -> ClockReference:
    """Start (and return) the reference responder on this process."""
    return ClockReference(store).start()
