"""Cross-rank metric aggregation over the KV store.

Snapshots ride the hierarchical reduction tree (``store/tree.py``): every
rank serializes its registry snapshot, subtrees merge rank → host → job,
and rank 0 consumes O(fanout) inbound payloads per round instead of the
flat all-ranks-to-one gather's O(N).  Rank 0 reduces the merged snapshots
to job-level series:

- counters / gauges → **sum**, **max** (with the owning rank), **min**;
- histograms → bucket-wise sums (job-level latency distribution);
- per-rank **outliers** → the top-k ranks by value for any sample, so "which
  rank is dropping log lines / stalling drains" is one lookup, not a
  per-rank scrape.

``render_job_metrics`` re-exports the reduction as OpenMetrics text with an
``agg`` label (``sum`` / ``max`` / ``min``) and a ``rank`` label on ``max``,
ready to splice into an exporter endpoint.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..store.tree import combine_json_merge, tree_gather, trim_json_sampled
from .registry import Registry, get_registry

K_PREFIX = "telemetry"
K_LATEST = f"{K_PREFIX}/latest"


def k_rank(round_idx: int, rank: int) -> str:
    return f"{K_PREFIX}/round/{round_idx}/rank/{rank}"


def rank_payload(registry: Optional[Registry] = None) -> str:
    return json.dumps((registry or get_registry()).snapshot())


def _sample_key(labels: Dict[str, str]) -> str:
    return json.dumps(labels, sort_keys=True)


def aggregate_snapshots(snapshots: Dict[int, dict]) -> dict:
    """Reduce ``{rank: snapshot}`` into job-level aggregates.

    Returns ``{name: {"kind", "labels", "samples": {labels_json: agg}}}``
    where ``agg`` is ``{"sum", "max", "max_rank", "min", "per_rank"}`` for
    scalar kinds and ``{"bounds", "counts", "sum", "count"}`` for
    histograms.
    """
    out: dict = {}
    for rank in sorted(snapshots):
        for name, fam in snapshots[rank].items():
            agg_fam = out.setdefault(
                name,
                {"kind": fam["kind"], "labels": fam.get("labels", []), "samples": {}},
            )
            for sample in fam.get("samples", ()):
                key = _sample_key(sample.get("labels", {}))
                if fam["kind"] == "histogram":
                    slot = agg_fam["samples"].get(key)
                    if slot is None:
                        slot = agg_fam["samples"][key] = {
                            "labels": sample.get("labels", {}),
                            "bounds": list(sample["bounds"]),
                            "counts": [0] * len(sample["counts"]),
                            "sum": 0.0,
                            "count": 0,
                        }
                    if slot["bounds"] == list(sample["bounds"]):
                        slot["counts"] = [
                            a + b for a, b in zip(slot["counts"], sample["counts"])
                        ]
                        slot["sum"] += sample["sum"]
                        slot["count"] += sample["count"]
                else:
                    v = float(sample.get("value", 0.0))
                    slot = agg_fam["samples"].get(key)
                    if slot is None:
                        slot = agg_fam["samples"][key] = {
                            "labels": sample.get("labels", {}),
                            "sum": 0.0,
                            "max": float("-inf"),
                            "max_rank": None,
                            "min": float("inf"),
                            "per_rank": {},
                        }
                    slot["sum"] += v
                    slot["per_rank"][rank] = v
                    if v > slot["max"]:
                        slot["max"], slot["max_rank"] = v, rank
                    if v < slot["min"]:
                        slot["min"] = v
    return out


def outliers(
    aggregated: dict, name: str, labels: Optional[Dict[str, str]] = None, k: int = 3
) -> List[Tuple[int, float]]:
    """Top-k (rank, value) for one scalar sample, highest first."""
    fam = aggregated.get(name)
    if not fam or fam["kind"] == "histogram":
        return []
    key = _sample_key(labels or {})
    slot = fam["samples"].get(key)
    if slot is None:
        return []
    ranked = sorted(slot["per_rank"].items(), key=lambda kv: -kv[1])
    return ranked[:k]


def render_job_metrics(aggregated: dict, prefix: str = "") -> str:
    """Aggregates → OpenMetrics sample lines (no ``# EOF``; meant to be
    spliced into an exposition by ``MetricsHTTPServer(extra_text_fn=...)``)."""
    from .exporter import _fmt_labels, _fmt_value  # local: avoid import cycle

    lines: List[str] = []
    for name in sorted(aggregated):
        fam = aggregated[name]
        kind = fam["kind"]
        family = prefix + (name[: -len("_total")] if kind == "counter" else name)
        sample_name = family + "_total" if kind == "counter" else family
        lines.append(f"# TYPE {family} {kind}")
        for slot in fam["samples"].values():
            labels = slot["labels"]
            if kind == "histogram":
                cum = 0
                for bound, c in zip(slot["bounds"], slot["counts"][:-1]):
                    cum += c
                    lines.append(
                        f"{family}_bucket"
                        f"{_fmt_labels(labels, {'le': _fmt_value(bound)})} {cum}"
                    )
                cum += slot["counts"][-1]
                lines.append(
                    f"{family}_bucket{_fmt_labels(labels, {'le': '+Inf'})} {cum}"
                )
                lines.append(
                    f"{family}_sum{_fmt_labels(labels)} {_fmt_value(slot['sum'])}"
                )
                lines.append(f"{family}_count{_fmt_labels(labels)} {slot['count']}")
                continue
            lines.append(
                f"{sample_name}{_fmt_labels(labels, {'agg': 'sum'})} "
                f"{_fmt_value(slot['sum'])}"
            )
            if slot["max_rank"] is not None:
                lines.append(
                    f"{sample_name}"
                    f"{_fmt_labels(labels, {'agg': 'max', 'rank': slot['max_rank']})}"
                    f" {_fmt_value(slot['max'])}"
                )
                lines.append(
                    f"{sample_name}{_fmt_labels(labels, {'agg': 'min'})} "
                    f"{_fmt_value(slot['min'])}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


class CrossRankAggregator:
    """Collective gather of every rank's snapshot through the reduction
    tree (``store/tree.py``).

    Every rank calls :meth:`round` at the same cadence (e.g. alongside the
    straggler report round).  Rank 0 gets the reduction; other ranks get
    ``None``.  Subtree keys are deleted by their consuming parent and rank 0
    GCs two-rounds-stale prefixes, so multi-day jobs don't grow the store.
    Rank 0 also republishes the merged per-rank snapshots under
    :data:`K_LATEST` — the single-key observer feed ``smonsvc`` polls.
    """

    def __init__(
        self, store, rank: int, world_size: int, fanout: Optional[int] = None
    ):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.fanout = fanout
        self._round = 0

    def round(
        self, registry: Optional[Registry] = None, timeout: float = 60.0
    ) -> Optional[dict]:
        round_idx = self._round
        self._round += 1
        payload = json.dumps(
            {self.rank: (registry or get_registry()).snapshot()}
        ).encode()
        merged = tree_gather(
            self.store,
            self.rank,
            self.world_size,
            prefix=f"{K_PREFIX}/round/{round_idx}",
            payload=payload,
            combine=combine_json_merge,
            timeout=timeout,
            fanout=self.fanout,
            site="telemetry",
            gc_prefix=(
                f"{K_PREFIX}/round/{round_idx - 2}/" if round_idx >= 2 else None
            ),
            # per-rank snapshot maps grow O(world) toward the root: when
            # TPURX_TREE_PAYLOAD_CAP is set, sample them at every level
            # rather than shipping the full population through one node
            trim=trim_json_sampled,
        )
        if self.rank != 0:
            return None
        self.store.set(K_LATEST, merged)
        snapshots = {
            int(r): snap
            for r, snap in json.loads(merged).items()
            if not r.startswith("_")  # skip the trim bookkeeping marker
        }
        return aggregate_snapshots(snapshots)


def read_latest_snapshots(store) -> Dict[int, dict]:
    """Non-collective read (``smonsvc`` side): the merged per-rank snapshots
    rank 0 republished after its last tree round — one key, one RTT,
    regardless of world size (the flat poll-every-rank loop this replaces
    was itself an all-ranks-to-one gather)."""
    raw = store.try_get(K_LATEST)
    if raw is None:
        return {}
    return {
        int(r): snap
        for r, snap in json.loads(raw.decode()).items()
        if not r.startswith("_")
    }
