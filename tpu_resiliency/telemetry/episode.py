"""Fault-episode identity and per-phase MTTR decomposition.

An *episode* is one fault's life: first detection → decision → abort →
rendezvous → restore → resume.  The episode id is minted **at first
detection** with a store ADD (so every rank that detects the same fault
converges on one id via a compare-set claim keyed by the restart
iteration), propagated through the restart pipeline, rendezvous records,
policy journal rows and checkpoint restore, and stamped onto every flight
and profiling event the participating processes emit — the join key that
turns per-process dumps into one causal story.

Phase accounting is transition-based: :meth:`Episode.phase` ends the
current phase and starts the named one, so the decomposed phases sum to
the episode's wall time by construction (the bench lane's
``episode_phase_coverage_pct`` gate proves no uninstrumented gap).  At
:meth:`Episode.close` each phase lands in
``tpurx_episode_phase_ns{phase,fault_class}`` and the per-rank summary is
published to the store under ``episode/<id>/rank/<r>`` for ``smonsvc``'s
``GET /episodes``; episodes older than ``TPURX_EPISODE_KEEP`` are GC'd.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import env
from ..utils.logging import get_logger
from . import flight, histogram
from .clock import mono_ns

log = get_logger("telemetry.episode")

PHASES = (
    "detect", "decide", "evacuate", "abort", "rendezvous", "restore", "resume"
)
# phases a REACTIVE episode (fault fired first) walks; "evacuate" only
# appears when the policy's predict-and-evacuate loop preempted the fault
REACTIVE_PHASES = tuple(p for p in PHASES if p != "evacuate")

_PHASE_NS = histogram(
    "tpurx_episode_phase_ns",
    "Per-fault-episode phase wall time, decomposing MTTR by fault class",
    labels=("phase", "fault_class"),
)

EV_BEGIN = flight.declare_event("episode.begin", "episode", "fault_class")
EV_PHASE = flight.declare_event("episode.phase", "episode", "phase")
EV_CLOSE = flight.declare_event(
    "episode.close", "episode", "fault_class", "wall_ns"
)

SEQ_KEY = "episode/seq"
CURRENT_KEY = "episode/current"

_lock = threading.Lock()
_current: Optional["Episode"] = None
_recent: List["Episode"] = []   # closed episodes, in-process (bench lane)
_RECENT_KEEP = 64
_local_seq = itertools.count(1)


class Episode:
    """One fault episode as seen by this process."""

    def __init__(
        self,
        episode_id: str,
        fault_class: str = "unknown",
        store=None,
        rank: Optional[int] = None,
    ):
        self.id = episode_id
        self.fault_class = fault_class
        self.store = store
        self.rank = env.RANK.get() if rank is None else rank
        self.t0_ns = mono_ns()
        self.closed_ns: Optional[int] = None
        self._marks: List[tuple] = [("detect", self.t0_ns)]
        flight.set_current_episode(self.id)
        flight.record(EV_BEGIN, self.id, fault_class)
        flight.record(EV_PHASE, self.id, "detect")

    def phase(self, name: str) -> None:
        """End the running phase, start ``name`` (idempotent per phase)."""
        if self.closed_ns is not None or self._marks[-1][0] == name:
            return
        self._marks.append((name, mono_ns()))
        flight.record(EV_PHASE, self.id, name)

    def current_phase(self) -> str:
        return self._marks[-1][0]

    def set_fault_class(self, fault_class: str) -> None:
        if fault_class:
            self.fault_class = fault_class

    @property
    def phases_ns(self) -> Dict[str, int]:
        """Per-phase wall time; the running phase extends to now."""
        end = self.closed_ns if self.closed_ns is not None else mono_ns()
        out: Dict[str, int] = {}
        for (name, start), (_next_name, nxt) in zip(
            self._marks, self._marks[1:] + [("", end)]
        ):
            out[name] = out.get(name, 0) + (nxt - start)
        return out

    @property
    def wall_ns(self) -> int:
        end = self.closed_ns if self.closed_ns is not None else mono_ns()
        return end - self.t0_ns

    def coverage_pct(self) -> float:
        """How much of the episode's wall time the decomposed phases
        cover — <100 means an uninstrumented gap."""
        wall = self.wall_ns
        if wall <= 0:
            return 100.0
        return 100.0 * sum(self.phases_ns.values()) / wall

    def close(self) -> Dict[str, int]:
        """End the episode: observe phase histograms, publish the per-rank
        summary, clear the process's current-episode tag."""
        global _current
        if self.closed_ns is not None:
            return self.phases_ns
        self.closed_ns = mono_ns()
        phases = self.phases_ns
        for name, dur in phases.items():
            _PHASE_NS.labels(name, self.fault_class).observe(dur)
        flight.record(EV_CLOSE, self.id, self.fault_class, self.wall_ns)
        with _lock:
            if _current is self:
                _current = None
            _recent.append(self)
            del _recent[:-_RECENT_KEEP]
        if flight.current_episode_id() == self.id:
            flight.set_current_episode("")
        if self.store is not None:
            try:
                self.store.set(
                    f"episode/{self.id}/rank/{self.rank}",
                    json.dumps(self.summary()),
                )
                if self.rank == 0:
                    self.store.set(CURRENT_KEY, b"")
                    _gc(self.store, self.id)
            except Exception:  # noqa: BLE001 - publication is best-effort
                log.debug("episode summary publish failed", exc_info=True)
        log.info(
            "episode %s closed: fault_class=%s wall=%.1fms phases=%s",
            self.id, self.fault_class, self.wall_ns / 1e6,
            {k: round(v / 1e6, 1) for k, v in phases.items()},
        )
        return phases

    def summary(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "rank": self.rank,
            "fault_class": self.fault_class,
            "pid": os.getpid(),
            "wall_ns": self.wall_ns,
            "phases_ns": self.phases_ns,
            "coverage_pct": round(self.coverage_pct(), 2),
            # wall stamp keys the fleet-wide "when" for humans; durations
            # above all come from the monotonic marks
            "t_close": time.time(),  # tpurx: disable=TPURX016 -- summary label, not a duration operand
        }


def _eid_num(episode_id: str) -> Optional[int]:
    if episode_id.startswith("ep") and episode_id[2:].isdigit():
        return int(episode_id[2:])
    return None


def _gc(store, episode_id: str) -> None:
    """Drop summaries of episodes older than the retention window."""
    n = _eid_num(episode_id)
    if n is None:
        return
    old = n - max(1, env.EPISODE_KEEP.get())
    if old <= 0:
        return
    try:
        for key in store.list_keys(f"episode/ep{old}/"):
            store.delete(key)
    except Exception:  # noqa: BLE001 - GC is best-effort
        log.debug("episode GC failed", exc_info=True)


def begin(
    store=None,
    claim=None,
    fault_class: str = "unknown",
    rank: Optional[int] = None,
) -> Episode:
    """Mint (or join) the episode for the fault just detected.

    ``claim``, when given, is a callable ``proposed_id -> winning_id``
    that arbitrates one id per fault across ranks (the in-process wrapper
    passes a compare-set on the iteration-scoped store key).  Without a
    store the id falls back to a process-local sequence — phases and
    flight tagging still work, only cross-process joining is off.
    """
    global _current
    with _lock:
        if _current is not None and _current.closed_ns is None:
            _current.set_fault_class(fault_class)
            return _current
    if store is not None:
        try:
            eid = f"ep{store.add(SEQ_KEY, 1)}"
            if claim is not None:
                eid = claim(eid)
            store.set(CURRENT_KEY, eid)
        except Exception:  # noqa: BLE001 - identity must not block recovery
            log.debug("episode mint via store failed", exc_info=True)
            eid = f"ep-local-{os.getpid()}-{next(_local_seq)}"
            store = None
    else:
        eid = f"ep-local-{os.getpid()}-{next(_local_seq)}"
    ep = Episode(eid, fault_class=fault_class, store=store, rank=rank)
    with _lock:
        _current = ep
    return ep


def current() -> Optional[Episode]:
    with _lock:
        return _current if (_current and _current.closed_ns is None) else None


def recent() -> List[Episode]:
    with _lock:
        return list(_recent)


def adopt(store) -> str:
    """Tag this process's flight/profiling events with the job's live
    episode id (sidecar processes: ckpt worker, monitor, smonsvc)."""
    try:
        raw = store.try_get(CURRENT_KEY)
    except Exception:  # noqa: BLE001 - adoption is best-effort
        return flight.current_episode_id()
    eid = (raw or b"").decode() if isinstance(raw, bytes) else (raw or "")
    if current() is None:
        flight.set_current_episode(eid)
    return eid


def current_or_store_id(store=None) -> str:
    """The episode id to stamp into journal/ledger rows: the process's
    live episode, else the job-wide current key when a store is at hand."""
    ep = current()
    if ep is not None:
        return ep.id
    eid = flight.current_episode_id()
    if eid or store is None:
        return eid
    try:
        raw = store.try_get(CURRENT_KEY)
    except Exception:  # noqa: BLE001 - stamping is best-effort
        return ""
    return (raw or b"").decode() if isinstance(raw, bytes) else (raw or "")


# -- store-side reading (smonsvc GET /episodes) ------------------------------


def read_episodes(store, n: int = 10) -> List[Dict[str, Any]]:
    """Last-``n`` episode summaries from the store, newest first: phase
    breakdown (max across ranks per phase), implicated ranks and the
    attribution verdict when one was published."""
    try:
        raw = store.try_get(SEQ_KEY)
        latest = int(raw) if raw else 0
    except Exception:  # noqa: BLE001 - a broken store reads as no episodes
        return []
    out: List[Dict[str, Any]] = []
    eid_n = latest
    while eid_n > 0 and len(out) < n:
        eid = f"ep{eid_n}"
        eid_n -= 1
        try:
            keys = store.list_keys(f"episode/{eid}/")
        except Exception:  # noqa: BLE001
            break
        ranks: Dict[int, Dict[str, Any]] = {}
        verdict = None
        for key in keys:
            k = key.decode() if isinstance(key, bytes) else key
            raw = store.try_get(k)
            if not raw:
                continue
            try:
                payload = json.loads(raw)
            except ValueError:
                continue
            if k.endswith("/verdict"):
                verdict = payload
            elif "/rank/" in k:
                try:
                    ranks[int(k.rsplit("/", 1)[1])] = payload
                except (ValueError, IndexError):
                    continue
        if not ranks and verdict is None:
            continue
        phase_ns: Dict[str, int] = {}
        for summary in ranks.values():
            for name, dur in (summary.get("phases_ns") or {}).items():
                phase_ns[name] = max(phase_ns.get(name, 0), int(dur))
        fault_classes = sorted(
            {s.get("fault_class", "unknown") for s in ranks.values()}
        )
        out.append({
            "id": eid,
            "fault_class": (fault_classes or ["unknown"])[0],
            "ranks": {str(r): ranks[r] for r in sorted(ranks)},
            "phase_ns": phase_ns,
            "wall_ns": max(
                (int(s.get("wall_ns", 0)) for s in ranks.values()), default=0
            ),
            "implicated_ranks": (verdict or {}).get("culprit_ranks", []),
            "verdict": verdict,
        })
    return out
