"""Always-on fault-episode flight recorder: a black box for the hot seams.

Reference analog: the PyTorch/NCCL Flight Recorder consumed by NVRx's
``attribution/trace_analyzer/fr_attribution.py``, and the always-on
recorder argument of the observable-collectives line (PAPERS.md,
arxiv 2510.00991): a near-zero-cost ring of structured events whose dump
at fault time reconstructs what every participant was doing.

Design:

- **Preallocated ring, lock-free append.**  One slot store per event —
  ``ring[next(counter) & mask] = (mono_ns, name, episode, args)`` — no
  allocation beyond the slot tuple, no lock (the itertools counter is
  GIL-atomic), sub-µs per append (bench lane ``tm_flight_append_ns``).
- **``TPURX_FLIGHT=0`` no-op** — the module-level :func:`record` becomes
  a shared no-op, same discipline as the registry's ``TPURX_TELEMETRY=0``.
  Call sites must use attribute access (``flight.record(...)``), never
  ``from ... import record``, so :func:`configure` rebinds take effect.
- **Declared event names.**  Every event name is declared exactly once at
  module scope via :func:`declare_event` with a literal string and its
  positional field names — the same single-declaration discipline
  ``tests/test_repo_hygiene.py`` enforces for metric names.
- **Dumps are the product.**  :func:`dump` snapshots the ring to a JSONL
  file (records shaped like ``utils/profiling.py`` lines, so
  ``telemetry/trace.py`` merges both streams onto one timeline), stamps
  the per-host clock offset from ``telemetry/clock.py`` into the meta
  record, announces through the log funnel (the warning below travels the
  ``utils/log_funnel.py`` forwarder when installed), and feeds registered
  hooks — the in-process wrapper installs one that runs the attribution
  engine's ``trace_analyzer`` over the dump.

Dump triggers wired across the repo: monitor trip, abort-ladder entry,
``CollectiveTimeout``, unhandled wrapper exceptions, ``GET /flight`` on
the metrics exporter, and SIGUSR2.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import signal
import socket
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import env
from ..utils.logging import get_logger
from .clock import mono_ns, offset

log = get_logger("telemetry.flight")

# -- event-name registry -----------------------------------------------------

_EVENT_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
_EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {}


def declare_event(name: str, *fields: str) -> str:
    """Register a flight-event name with its positional field names.

    Names are dotted (``subsystem.event``); the part before the first dot
    becomes the trace category.  One declaration per name, literal string,
    at module scope — enforced by ``tests/test_repo_hygiene.py``.
    """
    if not _EVENT_NAME_RE.match(name):
        raise ValueError(f"invalid flight event name {name!r}")
    if name in _EVENT_FIELDS:
        raise ValueError(f"flight event {name!r} declared twice")
    _EVENT_FIELDS[name] = tuple(fields)
    return name


def event_names() -> List[str]:
    return sorted(_EVENT_FIELDS)


def event_fields(name: str) -> Tuple[str, ...]:
    return _EVENT_FIELDS[name]


EV_DUMP = declare_event("flight.dump", "reason")
# mirror of every utils/profiling.py record, so the ring alone tells the
# restart-pipeline story even when no profiling sink file is configured
EV_PROFILING = declare_event("profiling.event", "name", "cycle")

# -- current-episode cell ----------------------------------------------------
# telemetry/episode.py owns the lifecycle; the cell lives here so the hot
# append can tag every event with the live episode id in one list index.

_EPISODE_CELL: List[str] = [""]


def set_current_episode(episode_id: str) -> None:
    _EPISODE_CELL[0] = episode_id or ""


def current_episode_id() -> str:
    return _EPISODE_CELL[0]


# -- the ring ----------------------------------------------------------------


class FlightRecorder:
    """Preallocated, overwrite-oldest event ring."""

    __slots__ = ("_ring", "_mask", "_counter", "capacity")

    def __init__(self, capacity: int):
        cap = 1
        while cap < max(2, capacity):
            cap <<= 1
        self.capacity = cap
        self._ring: List[Optional[tuple]] = [None] * cap
        self._mask = cap - 1
        self._counter = itertools.count()

    def record(self, name: str, *args: Any) -> None:
        # HOT PATH: one counter bump, one tuple, one slot store.  Under
        # concurrent appends two threads may claim distinct slots out of
        # order — fine, the dump sorts by timestamp.
        self._ring[next(self._counter) & self._mask] = (
            mono_ns(), name, _EPISODE_CELL[0], args,
        )

    def __len__(self) -> int:
        return sum(1 for slot in self._ring if slot is not None)

    def snapshot(self) -> List[tuple]:
        """Occupied slots, oldest first (torn slots racing an in-flight
        append are simply whichever tuple won the store — never invalid)."""
        slots = [s for s in self._ring if s is not None]
        slots.sort(key=lambda s: s[0])
        return slots


class _NoopRecorder:
    capacity = 0

    def record(self, name: str, *args: Any) -> None:
        return None

    def __len__(self) -> int:
        return 0

    def snapshot(self) -> List[tuple]:
        return []


NOOP = _NoopRecorder()

_recorder: Any = NOOP
_dump_lock = threading.Lock()
_dump_seq = itertools.count()
_dump_paths: List[str] = []       # files this process wrote, oldest first
_last_dump_ns: Dict[str, int] = {}  # reason -> mono_ns of last dump
_DUMP_HOOKS: List[Callable[[List[dict]], None]] = []


def flight_enabled() -> bool:
    try:
        return bool(env.FLIGHT.get())
    except ValueError:
        return True


def configure(
    enabled: Optional[bool] = None, capacity: Optional[int] = None
) -> None:
    """(Re)build the process recorder and rebind :func:`record`."""
    global _recorder, record
    if enabled is None:
        enabled = flight_enabled()
    if capacity is None:
        capacity = env.FLIGHT_RING.get()
    _recorder = FlightRecorder(capacity) if enabled else NOOP
    record = _recorder.record


def get_flight() -> Any:
    return _recorder


configure()


def _host() -> str:
    return socket.gethostname().split(".")[0]


def _meta(reason: str) -> Dict[str, Any]:
    off = offset()
    meta: Dict[str, Any] = {
        "event": "_flight_meta",
        "mono_ns": mono_ns(),
        # wall stamp is deliberate: it names the dump for humans grepping
        # a fleet's dump dirs, never enters duration math
        "ts": time.time(),  # tpurx: disable=TPURX016 -- dump label, not a duration operand
        "host": _host(),
        "pid": os.getpid(),
        "rank": env.RANK.get(),
        "reason": reason,
        "episode": current_episode_id(),
        "events": len(_recorder),
        "capacity": getattr(_recorder, "capacity", 0),
    }
    if off is not None:
        meta["clock_offset_ns"] = off.offset_ns
        meta["clock_rtt_ns"] = off.rtt_ns
        meta["clock_ref"] = off.ref
    return meta


def _records(reason: str) -> List[Dict[str, Any]]:
    host = _host()
    pid = os.getpid()
    rank = env.RANK.get()
    out = [_meta(reason)]
    for t_ns, name, episode, args in _recorder.snapshot():
        rec: Dict[str, Any] = {
            "mono_ns": t_ns, "event": name, "host": host, "pid": pid,
            "rank": rank,
        }
        if episode:
            rec["episode"] = episode
        fields = _EVENT_FIELDS.get(name, ())
        for i, val in enumerate(args):
            rec[fields[i] if i < len(fields) else f"arg{i}"] = val
        out.append(rec)
    return out


def render_jsonl(reason: str = "request") -> str:
    """The ring as JSONL text (the ``GET /flight`` body)."""
    return "\n".join(json.dumps(r, default=repr) for r in _records(reason)) + "\n"


def add_dump_hook(hook: Callable[[List[dict]], None]) -> None:
    """Register a consumer fed every dump's parsed records (e.g. the
    attribution trace analyzer).  Hooks must never raise into the dump."""
    if hook not in _DUMP_HOOKS:
        _DUMP_HOOKS.append(hook)


def remove_dump_hook(hook: Callable[[List[dict]], None]) -> None:
    try:
        _DUMP_HOOKS.remove(hook)
    except ValueError:
        pass


def dump(
    reason: str, path: Optional[str] = None, min_interval_s: float = 2.0
) -> Optional[str]:
    """Write the ring to a JSONL black-box file; returns the path.

    Per-reason throttled (``min_interval_s``) so a trip→ladder→timeout
    cascade produces one dump per distinct trigger, not one per retry.
    Never raises: a dump failing must not worsen the fault being dumped.
    """
    if _recorder is NOOP:
        return None
    now = mono_ns()
    with _dump_lock:
        last = _last_dump_ns.get(reason)
        if (
            path is None and last is not None
            and now - last < min_interval_s * 1e9
        ):
            return None
        _last_dump_ns[reason] = now
    record(EV_DUMP, reason)
    try:
        records = _records(reason)
        if path is None:
            base = env.FLIGHT_DIR.get() or tempfile.gettempdir()
            os.makedirs(base, exist_ok=True)
            path = os.path.join(
                base,
                f"flight-{_host()}-{os.getpid()}"
                f"-{next(_dump_seq):04d}-{reason}.jsonl",
            )
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec, default=repr) + "\n")
        with _dump_lock:
            _dump_paths.append(path)
            keep = max(1, env.FLIGHT_DUMP_KEEP.get())
            stale, _dump_paths[:] = _dump_paths[:-keep], _dump_paths[-keep:]
        for old in stale:
            try:
                os.unlink(old)
            except OSError:
                pass
        # the funnel-forwarded announcement: one line through the root
        # logger so the node's RootLogServer archive names every dump
        log.warning(
            "flight dump (%s): %s (%d events, episode=%s)",
            reason, path, len(records) - 1, current_episode_id() or "-",
        )
        for hook in list(_DUMP_HOOKS):
            try:
                hook(records)
            except Exception:  # noqa: BLE001 - hooks never worsen a fault
                log.exception("flight dump hook failed")
        return path
    except Exception:  # noqa: BLE001 - dumping must never worsen a fault
        log.exception("flight dump (%s) failed", reason)
        return None


def last_dump_path() -> Optional[str]:
    with _dump_lock:
        return _dump_paths[-1] if _dump_paths else None


_signal_installed = False


def install_signal_handler() -> bool:
    """SIGUSR2 → dump.  Main-thread only (signal module constraint);
    returns whether the handler is installed."""
    global _signal_installed
    if _signal_installed:
        return True

    def _on_sigusr2(signum, frame):  # noqa: ARG001 - signal signature
        dump("sigusr2")

    try:
        signal.signal(signal.SIGUSR2, _on_sigusr2)
    except (ValueError, OSError):  # not the main thread / exotic platform
        return False
    _signal_installed = True
    return True
