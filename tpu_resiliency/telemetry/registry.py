"""Process-local metrics registry.

Design constraints (the reason this is not a prometheus_client dependency):

- **Near-zero hot-path overhead.**  ``Counter.inc`` is one lock acquire and
  one float add (~100-300 ns); ``Histogram.observe`` adds a bisect over a
  fixed bucket table.  Instrumentation sites in heartbeat/store/step paths
  run every few milliseconds, so anything allocating or formatting per event
  is out.
- **No-op fast path.**  With ``TPURX_TELEMETRY=0`` every constructor returns
  the shared :data:`NOOP` metric whose methods are empty — call sites keep a
  single unconditional ``metric.inc()`` and pay only a no-op method call.
  Metric *names* are still recorded (registration is one-time, not hot) so
  tooling can enumerate the catalog regardless of the switch.
- **Snapshot-friendly.**  ``snapshot()`` emits a plain-JSON structure that
  crosses the KV store for cross-rank aggregation (``aggregate.py``) and
  feeds the OpenMetrics renderer (``exporter.py``).

Values observed into histograms are **monotonic nanoseconds** by convention
(:data:`DEFAULT_NS_BUCKETS` spans 1 µs – 68 s in powers of four); byte-sized
histograms can pass their own bucket table.
"""

from __future__ import annotations

import bisect
import collections
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import env

ENV_TELEMETRY = env.TELEMETRY.name

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# 1 µs .. ~68 s in powers of 4 — covers a heartbeat send (~10 µs) and a full
# rendezvous round (~seconds) in one table.
DEFAULT_NS_BUCKETS: Tuple[float, ...] = tuple(
    1_000.0 * (4 ** i) for i in range(14)
)

# 4 KiB .. 16 GiB in powers of 8 — for byte-sized observations (drain chunks).
BYTE_BUCKETS: Tuple[float, ...] = tuple(4096.0 * (8 ** i) for i in range(8))


def telemetry_enabled() -> bool:
    """The global switch: ``TPURX_TELEMETRY=0`` disables collection."""
    return env.TELEMETRY.get()


def valid_metric_name(name: str) -> bool:
    return bool(_NAME_RE.match(name))


class _NoopTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_TIMER = _NoopTimer()


class _NoopMetric:
    """Shared do-nothing metric returned by disabled registries."""

    __slots__ = ()

    def labels(self, *values, **kv) -> "_NoopMetric":
        return self

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def time_ns(self):
        return _NOOP_TIMER

    def rate(self, window_s: float, now: Optional[float] = None) -> float:
        return 0.0


NOOP = _NoopMetric()


class RateWindow:
    """Sliding-window rate over a cumulative series.

    Each :meth:`observe` records ``(now, value)`` into a bounded ring;
    :meth:`rate` divides the delta against the oldest still-in-window
    sample by the elapsed time.  A value *decrease* means the underlying
    counter reset (process restart, scrape of a re-created registry): the
    history is re-baselined from the new value rather than reporting a
    negative rate.  ``Counter.rate`` wraps one of these; the job-level
    estimator feeds standalone instances from cross-rank snapshot sums,
    which reset whenever ranks restart.
    """

    __slots__ = ("_samples", "_lock")

    def __init__(self, maxlen: int = 256):
        self._samples: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def observe(self, value: float, now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._samples and value < self._samples[-1][1]:
                # counter reset: older samples describe a dead series
                self._samples.clear()
            self._samples.append((t, float(value)))

    def rate(
        self, window_s: float, value: float, now: Optional[float] = None
    ) -> float:
        """Record ``(now, value)`` and return events/s over ``window_s``.

        Returns 0.0 until two in-window samples exist (no baseline yet).
        """
        t = time.monotonic() if now is None else float(now)
        self.observe(value, now=t)
        horizon = t - float(window_s)
        with self._lock:
            base = None
            for st, sv in self._samples:
                if st >= horizon:
                    base = (st, sv)
                    break
            if base is None or base[0] >= t:
                return 0.0
            return max(0.0, (float(value) - base[1]) / (t - base[0]))


class _TimerCtx:
    """Context manager observing the enclosed duration in monotonic ns."""

    __slots__ = ("_metric", "_t0")

    def __init__(self, metric: "Histogram"):
        self._metric = metric

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        self._metric.observe(time.monotonic_ns() - self._t0)
        return False


class _Metric:
    """Base for the three concrete kinds.  A metric with ``label_names`` is a
    family: ``labels(v1, v2)`` (or ``labels(name=v)``) returns a child that
    shares the family entry in the registry."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}

    def labels(self, *values, **kv) -> "_Metric":
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(str(kv[n]) for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
            return child

    def _make_child(self) -> "_Metric":
        raise NotImplementedError

    def _sample_rows(self) -> List[Tuple[Dict[str, str], dict]]:
        """[(labels_dict, value_dict)] for this family (children or self)."""
        if self.label_names:
            with self._lock:
                items = list(self._children.items())
            return [
                (dict(zip(self.label_names, values)), child._value_dict())
                for values, child in items
            ]
        return [({}, self._value_dict())]

    def _value_dict(self) -> dict:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = "", label_names: Sequence[str] = ()):
        super().__init__(name, help, label_names)
        self._value = 0.0
        self._rate_window: Optional[RateWindow] = None

    def _make_child(self) -> "Counter":
        return Counter(self.name, self.help)

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def rate(self, window_s: float, now: Optional[float] = None) -> float:
        """Windowed rate view (events/s over the trailing ``window_s``).

        Sampling happens at call time — the caller's poll cadence builds the
        history, the hot ``inc`` path stays a lock + float add.  Returns 0.0
        until a second in-window call establishes a baseline.
        """
        if self._rate_window is None:
            with self._lock:
                if self._rate_window is None:
                    self._rate_window = RateWindow()
        return self._rate_window.rate(window_s, self.value, now=now)

    def _value_dict(self) -> dict:
        with self._lock:
            return {"value": self._value}


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = "", label_names: Sequence[str] = ()):
        super().__init__(name, help, label_names)
        self._value = 0.0

    def _make_child(self) -> "Gauge":
        return Gauge(self.name, self.help)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _value_dict(self) -> dict:
        with self._lock:
            return {"value": self._value}


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative-on-render, per-bucket in memory)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_NS_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = overflow (+Inf)
        self._sum = 0.0
        self._count = 0

    def _make_child(self) -> "Histogram":
        return Histogram(self.name, self.help, buckets=self.bounds)

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def time_ns(self) -> _TimerCtx:
        return _TimerCtx(self)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper bound of the bucket the
        q-th observation falls in; +Inf overflow reports the top bound)."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        target = max(1, int(q * total + 0.5))
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return self.bounds[min(i, len(self.bounds) - 1)]
        return self.bounds[-1]

    def _value_dict(self) -> dict:
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }


class Registry:
    """Thread-safe named-metric registry.

    Duplicate registration with identical (kind, label_names) returns the
    existing metric (modules are imported once, but tests re-import); any
    mismatch raises — two call sites silently sharing one name with
    different shapes is the bug this catches.
    """

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = telemetry_enabled() if enabled is None else bool(enabled)
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # name -> (kind, label_names); kept even when disabled so the
        # catalog stays enumerable
        self._declared: Dict[str, Tuple[str, Tuple[str, ...]]] = {}

    # -- registration ------------------------------------------------------

    def _register(self, cls, name: str, help: str, label_names, **kw):
        if not valid_metric_name(name):
            raise ValueError(f"invalid OpenMetrics metric name: {name!r}")
        label_names = tuple(label_names)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        with self._lock:
            declared = self._declared.get(name)
            if declared is not None and declared != (cls.kind, label_names):
                raise ValueError(
                    f"metric {name!r} already registered as {declared}, "
                    f"conflicting with ({cls.kind}, {label_names})"
                )
            self._declared[name] = (cls.kind, label_names)
            if not self.enabled:
                return NOOP
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, help, label_names=label_names, **kw)
                self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()):
        if not name.endswith("_total"):
            raise ValueError(f"counter {name!r} must end with '_total'")
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()):
        return self._register(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_NS_BUCKETS,
    ):
        return self._register(Histogram, name, help, labels, buckets=buckets)

    # -- introspection -----------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._declared)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def value_of(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        """Convenience for tests/bench: current value of a counter/gauge
        sample (0.0 when absent/disabled)."""
        metric = self.get(name)
        if metric is None:
            return 0.0
        for label_dict, value in metric._sample_rows():
            if labels is None or label_dict == {k: str(v) for k, v in labels.items()}:
                if "value" in value:
                    return value["value"]
                return value.get("sum", 0.0)
        return 0.0

    def collect(self) -> List[dict]:
        """[{name, kind, help, labels, samples: [(labels_dict, value_dict)]}]"""
        with self._lock:
            metrics = list(self._metrics.values())
        return [
            {
                "name": m.name,
                "kind": m.kind,
                "help": m.help,
                "labels": list(m.label_names),
                "samples": m._sample_rows(),
            }
            for m in metrics
        ]

    def snapshot(self) -> dict:
        """JSON-safe state for cross-rank aggregation."""
        out = {}
        for fam in self.collect():
            out[fam["name"]] = {
                "kind": fam["kind"],
                "labels": fam["labels"],
                "samples": [
                    {"labels": labels, **value} for labels, value in fam["samples"]
                ],
            }
        return out


_default_registry: Optional[Registry] = None
_default_lock = threading.Lock()


def get_registry() -> Registry:
    """The process-wide default registry (created on first use; the enable
    switch is read once, at creation)."""
    global _default_registry
    if _default_registry is None:
        with _default_lock:
            if _default_registry is None:
                _default_registry = Registry()
    return _default_registry


def counter(name: str, help: str = "", labels: Sequence[str] = ()):
    return get_registry().counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()):
    return get_registry().gauge(name, help, labels)


def histogram(
    name: str,
    help: str = "",
    labels: Sequence[str] = (),
    buckets: Sequence[float] = DEFAULT_NS_BUCKETS,
):
    return get_registry().histogram(name, help, labels, buckets=buckets)


