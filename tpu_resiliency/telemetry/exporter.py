"""OpenMetrics text exposition: HTTP scrape endpoint + textfile sink.

Two transports, both stdlib-only:

- :class:`MetricsHTTPServer` — a ``ThreadingHTTPServer`` on a background
  thread serving ``GET /metrics`` (plus ``/healthz``).  Port 0 binds an
  ephemeral port (``.port`` reports it) so every rank on a host can expose
  its own endpoint without coordination.
- :class:`TextfileSink` — periodic atomic writes of the exposition to a
  path template with the same ``%r`` (rank) / ``%h`` (hostname) expansion as
  ``utils/logging.py``, for node-exporter-textfile-style collection on
  hosts where an extra listening port is unwelcome.

``serve_from_env()`` wires both from ``TPURX_METRICS_PORT`` /
``TPURX_METRICS_TEXTFILE``.
"""

from __future__ import annotations

import math
import os
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..utils import env
from ..utils.logging import _resolve_rank
from .registry import Registry, get_registry

ENV_METRICS_PORT = env.METRICS_PORT.name
ENV_METRICS_TEXTFILE = env.METRICS_TEXTFILE.name

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in merged.items()
    )
    return "{" + inner + "}"


def render_openmetrics(registry: Optional[Registry] = None) -> str:
    """Serialize a registry in OpenMetrics text format (ends with ``# EOF``).

    Counter families drop the mandatory ``_total`` suffix in the family name
    (``# TYPE``) and keep it on the sample, per the spec.
    """
    reg = registry or get_registry()
    lines: List[str] = []
    for fam in reg.collect():
        name = fam["name"]
        kind = fam["kind"]
        family = name[: -len("_total")] if kind == "counter" else name
        lines.append(f"# TYPE {family} {kind}")
        if fam["help"]:
            lines.append(f"# HELP {family} {_escape_label_value(fam['help'])}")
        for labels, value in fam["samples"]:
            if kind == "histogram":
                cum = 0
                bounds = value["bounds"]
                counts = value["counts"]
                for bound, c in zip(bounds, counts[:-1]):
                    cum += c
                    lines.append(
                        f"{family}_bucket"
                        f"{_fmt_labels(labels, {'le': _fmt_value(bound)})} {cum}"
                    )
                cum += counts[-1]
                lines.append(
                    f"{family}_bucket{_fmt_labels(labels, {'le': '+Inf'})} {cum}"
                )
                lines.append(
                    f"{family}_sum{_fmt_labels(labels)} {_fmt_value(value['sum'])}"
                )
                lines.append(f"{family}_count{_fmt_labels(labels)} {value['count']}")
            else:
                sample = f"{family}_total" if kind == "counter" else family
                lines.append(
                    f"{sample}{_fmt_labels(labels)} {_fmt_value(value['value'])}"
                )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Background scrape endpoint for one process ("per-rank exporter")."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        host: str = "0.0.0.0",
        port: int = 0,
        extra_text_fn=None,
    ):
        self.registry = registry or get_registry()
        # appended after the registry's families, BEFORE '# EOF' (used by
        # smonsvc to splice in job-level aggregated series)
        self._extra_text_fn = extra_text_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
                pass

            def do_GET(self):  # noqa: N802 - stdlib name
                if self.path in ("/metrics", "/"):
                    body = outer.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/healthz":
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                    return
                if self.path == "/flight":
                    # the live flight-recorder ring as JSONL — the same
                    # bytes a black-box dump file would hold, on demand
                    from . import flight

                    body = flight.render_jsonl("http").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/x-ndjson")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(404)
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_port
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="tpurx-metrics-http"
        )

    def render(self) -> str:
        text = render_openmetrics(self.registry)
        if self._extra_text_fn is not None:
            try:
                extra = self._extra_text_fn()
            except Exception:  # noqa: BLE001 - extras are best-effort
                extra = ""
            if extra:
                # splice before the EOF marker to keep one valid exposition
                text = text[: -len("# EOF\n")] + extra.rstrip("\n") + "\n# EOF\n"
        return text

    def start(self) -> "MetricsHTTPServer":
        self._thread.start()
        return self

    def close(self) -> None:
        if self._thread.is_alive():
            # shutdown() blocks on serve_forever's exit handshake — calling
            # it on a never-started server would wait forever
            self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2)


def expand_sink_path(template: str) -> str:
    """``%r``/``%h`` expansion, identical to the log-file sink's."""
    return template.replace("%r", _resolve_rank(None)).replace(
        "%h", socket.gethostname()
    )


class TextfileSink:
    """Atomic exposition writes for textfile-collector scrapes."""

    def __init__(
        self,
        path_template: str,
        registry: Optional[Registry] = None,
        interval: float = 15.0,
    ):
        self.path_template = path_template
        self.registry = registry or get_registry()
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def path(self) -> str:
        return expand_sink_path(self.path_template)

    def write_once(self) -> str:
        path = self.path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(render_openmetrics(self.registry))
        os.replace(tmp, path)  # scrapers never see a half-written exposition
        return path

    def start(self) -> "TextfileSink":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="tpurx-metrics-textfile"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.write_once()
            except OSError:
                pass  # transient sink trouble must never hurt the trainer

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        try:
            self.write_once()  # final flush
        except OSError:
            pass


def serve_from_env(registry: Optional[Registry] = None):
    """Start whatever exporters the environment asks for.

    ``TPURX_METRICS_PORT=<n>`` starts the HTTP endpoint (0 = ephemeral);
    ``TPURX_METRICS_TEXTFILE=/path/metrics_%r.prom`` starts the textfile
    sink.  Returns the list of started exporters (possibly empty).
    """
    started = []
    if env.METRICS_PORT.is_set():
        try:
            base = env.METRICS_PORT.get()
            if base:
                # multi-worker hosts: each local rank claims base+local_rank
                base += env.LOCAL_RANK.get()
            started.append(MetricsHTTPServer(registry, port=base).start())
        except (OSError, ValueError):
            pass  # a taken port must not kill the workload
    template = env.METRICS_TEXTFILE.get()
    if template:
        started.append(TextfileSink(template, registry).start())
    return started


_env_exporters: Optional[list] = None
_env_lock = threading.Lock()


def serve_from_env_once(registry: Optional[Registry] = None) -> list:
    """Idempotent :func:`serve_from_env` — called from per-rank entry points
    (rank-monitor init, the in-process wrapper) so a worker that passes
    through several of them still binds one endpoint."""
    global _env_exporters
    with _env_lock:
        if _env_exporters is None:
            _env_exporters = serve_from_env(registry)
            # same per-rank entry points want the flight recorder's
            # on-demand dump trigger; best-effort (non-main threads skip)
            from . import flight

            flight.install_signal_handler()
        return _env_exporters
