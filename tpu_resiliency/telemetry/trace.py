"""ProfilingRecorder JSONL → Chrome-trace / Perfetto JSON.

``utils/profiling.py`` records the restart pipeline as flat JSONL events
(``rendezvous_started`` … ``inprocess_restart_completed``).  This module
pairs the start/end events into complete spans ("ph": "X") and emits the
Chrome trace-event format both ``chrome://tracing`` and Perfetto load
directly — one track (pid) per rank, category tracks (tid) per subsystem,
unpaired events as instants.

CLI::

    python -m tpu_resiliency.telemetry.trace profiling.jsonl -o cycle.trace.json

Multiple input files concatenate (e.g. one JSONL per rank collected off a
shared mount); each record's ``rank`` (fallback: ``pid``) selects its track.
Timestamps are the recorder's ``mono_ns`` normalized to the earliest event,
so spans from one host line up exactly; cross-host files only share a
relative timeline.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

# start event -> (end event, span name, category)
SPAN_PAIRS: Dict[str, Tuple[str, str, str]] = {
    "rendezvous_started": ("rendezvous_completed", "rendezvous", "fault_tolerance"),
    "worker_start_requested": ("worker_started", "worker_start", "fault_tolerance"),
    "worker_stop_requested": ("worker_stopped", "worker_stop", "fault_tolerance"),
    "checkpoint_save_started": (
        "checkpoint_save_finalized", "checkpoint_save", "checkpointing",
    ),
    "checkpoint_load_started": (
        "checkpoint_load_completed", "checkpoint_load", "checkpointing",
    ),
    "inprocess_restart_started": (
        "inprocess_restart_completed", "inprocess_restart", "inprocess",
    ),
    "health_check_started": ("health_check_completed", "health_check", "health"),
}
_END_TO_START = {end: start for start, (end, _, _) in SPAN_PAIRS.items()}

INSTANT_CATEGORIES = {
    "failure_detected": "fault_tolerance",
    "hang_detected": "fault_tolerance",
    "straggler_detected": "straggler",
    "inprocess_interrupted": "inprocess",
    "health_failure": "health",
    "node_exclude_requested": "health",
    "worker_started": "fault_tolerance",  # only when its start was never seen
}

_META_KEYS = ("ts", "mono_ns", "event", "pid")


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from a killed process
            if isinstance(rec, dict) and "event" in rec and "mono_ns" in rec:
                events.append(rec)
    return events


def _track(rec: Dict[str, Any]) -> int:
    rank = rec.get("rank")
    if rank is not None:
        return int(rank)
    return int(rec.get("pid", 0))


def _span_key(rec: Dict[str, Any], start_event: str) -> Tuple:
    # health checks of different names legitimately nest/overlap — keep them
    # on separate matching stacks; everything else matches LIFO per track
    if start_event == "health_check_started":
        return (start_event, rec.get("check", ""))
    return (start_event,)


def to_chrome_trace(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Pair start/end events into complete spans; returns the trace dict."""
    events = sorted(events, key=lambda r: r["mono_ns"])
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(r["mono_ns"] for r in events)
    out: List[Dict[str, Any]] = []
    tracks = set()
    # (track, span_key) -> stack of pending start records
    pending: Dict[Tuple, List[Dict[str, Any]]] = {}

    def args_of(rec: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in rec.items() if k not in _META_KEYS}

    for rec in events:
        event = rec["event"]
        track = _track(rec)
        tracks.add(track)
        ts_us = (rec["mono_ns"] - t0) / 1e3
        if event in SPAN_PAIRS:
            key = (track, _span_key(rec, event))
            pending.setdefault(key, []).append(rec)
            continue
        start_event = _END_TO_START.get(event)
        if start_event is not None:
            key = (track, _span_key(rec, start_event))
            stack = pending.get(key)
            if stack:
                start = stack.pop()
                _, name, cat = SPAN_PAIRS[start_event]
                out.append(
                    {
                        "name": name,
                        "cat": cat,
                        "ph": "X",
                        "ts": (start["mono_ns"] - t0) / 1e3,
                        "dur": (rec["mono_ns"] - start["mono_ns"]) / 1e3,
                        "pid": track,
                        "tid": 0,
                        "args": {**args_of(start), **args_of(rec)},
                    }
                )
                continue
            # end without a start (file truncated at the front): instant
        out.append(
            {
                "name": event,
                "cat": INSTANT_CATEGORIES.get(event, "events"),
                "ph": "i",
                "s": "t",
                "ts": ts_us,
                "pid": track,
                "tid": 0,
                "args": args_of(rec),
            }
        )
    # dangling starts (crash before the end event): zero-length instants so
    # the abandoned phase is still visible on the timeline
    for (track, key), stack in pending.items():
        for start in stack:
            _, name, cat = SPAN_PAIRS[key[0]]
            out.append(
                {
                    "name": f"{name} (unfinished)",
                    "cat": cat,
                    "ph": "i",
                    "s": "t",
                    "ts": (start["mono_ns"] - t0) / 1e3,
                    "pid": track,
                    "tid": 0,
                    "args": args_of(start),
                }
            )
    for track in sorted(tracks):
        out.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": track,
                "args": {"name": f"rank {track}"},
            }
        )
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("ph") != "M"))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def convert(paths: List[str], output: Optional[str] = None) -> Dict[str, Any]:
    events: List[Dict[str, Any]] = []
    for p in paths:
        events.extend(read_jsonl(p))
    trace = to_chrome_trace(events)
    if output:
        with open(output, "w") as f:
            json.dump(trace, f)
    return trace


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_resiliency.telemetry.trace",
        description="Convert ProfilingRecorder JSONL into Chrome-trace JSON "
        "(open in chrome://tracing or ui.perfetto.dev)",
    )
    parser.add_argument("inputs", nargs="+", help="JSONL file(s), one per rank")
    parser.add_argument(
        "-o", "--output",
        help="output path (default: stdout)",
    )
    args = parser.parse_args(argv)
    trace = convert(args.inputs, args.output)
    n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    if args.output:
        print(
            f"wrote {args.output}: {n_spans} spans, "
            f"{len(trace['traceEvents'])} events",
            file=sys.stderr,
        )
    else:
        json.dump(trace, sys.stdout)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
