"""Profiling/flight JSONL → Chrome-trace / Perfetto JSON, cross-host aligned.

``utils/profiling.py`` records the restart pipeline as flat JSONL events
(``rendezvous_started`` … ``inprocess_restart_completed``) and
``telemetry/flight.py`` dumps the flight-recorder ring in the same shape.
This module pairs the start/end events into complete spans ("ph": "X") and
emits the Chrome trace-event format both ``chrome://tracing`` and Perfetto
load directly — one track (pid) per rank, unpaired events as instants,
fault-episode phases as spans connected across ranks by flow arrows.

CLI::

    python -m tpu_resiliency.telemetry.trace profiling.jsonl -o cycle.trace.json

Multiple input files merge (e.g. one JSONL per rank collected off a shared
mount); each record's ``rank`` (fallback: ``pid``) selects its track.

Timestamps are the recorder's ``mono_ns``.  Monotonic clocks are per-host
domains, so each file's ``_flight_meta`` header (written by both recorders)
carries the producing process's estimated offset to the job's reference
clock (``telemetry/clock.py``); :func:`load_aligned` applies it per file so
multi-host dumps land on ONE aligned timeline.  When two or more hosts
contribute files with no offset, their clocks cannot be related and a
stderr warning names them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

META_EVENT = "_flight_meta"

# start event -> (end event, span name, category)
SPAN_PAIRS: Dict[str, Tuple[str, str, str]] = {
    "rendezvous_started": ("rendezvous_completed", "rendezvous", "fault_tolerance"),
    "worker_start_requested": ("worker_started", "worker_start", "fault_tolerance"),
    "worker_stop_requested": ("worker_stopped", "worker_stop", "fault_tolerance"),
    "checkpoint_save_started": (
        "checkpoint_save_finalized", "checkpoint_save", "checkpointing",
    ),
    "checkpoint_load_started": (
        "checkpoint_load_completed", "checkpoint_load", "checkpointing",
    ),
    "inprocess_restart_started": (
        "inprocess_restart_completed", "inprocess_restart", "inprocess",
    ),
    "health_check_started": ("health_check_completed", "health_check", "health"),
    # flight-recorder events (dotted namespace, see telemetry/flight.py)
    "monitor.section_begin": ("monitor.section_end", "section", "monitor"),
    "collective.dispatch": ("collective.settle", "collective", "collective"),
    "ckpt.drain_begin": ("ckpt.drain_end", "ckpt_drain", "checkpointing"),
    "ckpt.restore_begin": ("ckpt.restore_end", "ckpt_restore", "checkpointing"),
    # predict-and-evacuate: risk crossing → replacement's warm join is
    # the planned-handoff MTTR span (evac.ckpt_ahead / evac.promote
    # render as instants inside it)
    "evac.risk_cross": ("evac.join", "evacuation", "evac"),
}
_END_TO_START = {end: start for start, (end, _, _) in SPAN_PAIRS.items()}

INSTANT_CATEGORIES = {
    "failure_detected": "fault_tolerance",
    "hang_detected": "fault_tolerance",
    "straggler_detected": "straggler",
    "inprocess_interrupted": "inprocess",
    "health_failure": "health",
    "node_exclude_requested": "health",
    "worker_started": "fault_tolerance",  # only when its start was never seen
}

_META_KEYS = ("ts", "mono_ns", "event", "pid")

# fault-episode phase events become per-rank phase spans + cross-rank flows
_EP_BEGIN, _EP_PHASE, _EP_CLOSE = (
    "episode.begin", "episode.phase", "episode.close",
)


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from a killed process
            if isinstance(rec, dict) and "event" in rec and "mono_ns" in rec:
                events.append(rec)
    return events


def load_aligned(
    paths: List[str], warn: bool = True
) -> List[Dict[str, Any]]:
    """Read files and shift each into the reference clock domain.

    A file's offset is the last ``clock_offset_ns`` among its meta records
    (re-emitted after calibration, so last wins).  Files without one stay
    unshifted — correct when they ARE the reference domain (rank 0 serves
    the reference and never calibrates); when files from two or more hosts
    all lack offsets, their relative placement is meaningless and the
    warning below names them.
    """
    all_events: List[Dict[str, Any]] = []
    host_aligned: Dict[str, bool] = {}
    for path in paths:
        events = read_jsonl(path)
        offset: Optional[int] = None
        host = None
        for rec in events:
            if rec["event"] != META_EVENT:
                continue
            host = rec.get("host") or host
            if rec.get("clock_offset_ns") is not None:
                offset = int(rec["clock_offset_ns"])
        host = host or os.path.basename(path)
        host_aligned[host] = host_aligned.get(host, False) or offset is not None
        for rec in events:
            if rec["event"] == META_EVENT:
                continue
            if offset:
                rec = dict(rec, mono_ns=int(rec["mono_ns"]) + offset)
            all_events.append(rec)
    unaligned = sorted(h for h, ok in host_aligned.items() if not ok)
    if warn and len(host_aligned) >= 2 and len(unaligned) >= 2:
        print(
            "warning: no clock offset recorded for hosts "
            f"{', '.join(unaligned)}; their tracks share no reference "
            "clock and only line up by accident (run "
            "telemetry.clock.calibrate, or expect skew)",
            file=sys.stderr,
        )
    return all_events


def _track(rec: Dict[str, Any]) -> int:
    rank = rec.get("rank")
    if rank is not None:
        return int(rank)
    return int(rec.get("pid", 0))


def _span_key(rec: Dict[str, Any], start_event: str) -> Tuple:
    # health checks of different names legitimately nest/overlap — keep them
    # on separate matching stacks; everything else matches LIFO per track
    if start_event == "health_check_started":
        return (start_event, rec.get("check", ""))
    if start_event == "monitor.section_begin":
        return (start_event, rec.get("section", ""))
    if start_event == "collective.dispatch":
        return (start_event, rec.get("op", ""), rec.get("axis", ""))
    return (start_event,)


def _flow_id(episode: str) -> int:
    return zlib.crc32(episode.encode()) or 1


def _episode_flows(
    anchors: Dict[str, List[Tuple[float, int]]],
) -> List[Dict[str, Any]]:
    """One flow per episode: arrow from the first rank that saw the fault
    (the detection instant) to every other rank's episode activity."""
    out: List[Dict[str, Any]] = []
    for episode, sightings in anchors.items():
        sightings.sort()
        first_per_track: Dict[int, float] = {}
        for ts, track in sightings:
            first_per_track.setdefault(track, ts)
        if len(first_per_track) < 2:
            continue
        ordered = sorted(first_per_track.items(), key=lambda kv: kv[1])
        fid = _flow_id(episode)
        (t0_track, t0_ts) = ordered[0]
        out.append({
            "name": "episode", "cat": "episode", "ph": "s", "id": fid,
            "ts": t0_ts, "pid": t0_track, "tid": 0,
            "args": {"episode": episode},
        })
        for i, (track, ts) in enumerate(ordered[1:], start=1):
            ph = "f" if i == len(ordered) - 1 else "t"
            ev = {
                "name": "episode", "cat": "episode", "ph": ph, "id": fid,
                "ts": ts, "pid": track, "tid": 0,
                "args": {"episode": episode},
            }
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return out


def to_chrome_trace(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Pair start/end events into complete spans; returns the trace dict."""
    events = sorted(
        (r for r in events if r["event"] != META_EVENT),
        key=lambda r: r["mono_ns"],
    )
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(r["mono_ns"] for r in events)
    out: List[Dict[str, Any]] = []
    tracks = set()
    # (track, span_key) -> stack of pending start records
    pending: Dict[Tuple, List[Dict[str, Any]]] = {}
    # (track, episode) -> (phase name, start ts_us) of the running phase
    ep_phase: Dict[Tuple[int, str], Tuple[str, float]] = {}
    # episode -> [(ts_us, track)] of every episode event sighting
    ep_anchors: Dict[str, List[Tuple[float, int]]] = {}

    def args_of(rec: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in rec.items() if k not in _META_KEYS}

    def end_phase(track: int, episode: str, ts_us: float) -> None:
        running = ep_phase.pop((track, episode), None)
        if running is not None:
            name, start_us = running
            out.append({
                "name": name, "cat": "episode", "ph": "X",
                "ts": start_us, "dur": max(0.0, ts_us - start_us),
                "pid": track, "tid": 0, "args": {"episode": episode},
            })

    for rec in events:
        event = rec["event"]
        track = _track(rec)
        tracks.add(track)
        ts_us = (rec["mono_ns"] - t0) / 1e3
        if event in (_EP_BEGIN, _EP_PHASE, _EP_CLOSE):
            episode = str(rec.get("episode", ""))
            ep_anchors.setdefault(episode, []).append((ts_us, track))
            if event == _EP_PHASE:
                phase = str(rec.get("phase", ""))
                running = ep_phase.get((track, episode))
                if running is not None and running[0] == phase:
                    continue
                end_phase(track, episode, ts_us)
                ep_phase[(track, episode)] = (phase, ts_us)
                continue
            if event == _EP_CLOSE:
                end_phase(track, episode, ts_us)
            # begin/close also render as instants below
        if event in SPAN_PAIRS:
            key = (track, _span_key(rec, event))
            pending.setdefault(key, []).append(rec)
            continue
        start_event = _END_TO_START.get(event)
        if start_event is not None:
            key = (track, _span_key(rec, start_event))
            stack = pending.get(key)
            if stack:
                start = stack.pop()
                _, name, cat = SPAN_PAIRS[start_event]
                out.append(
                    {
                        "name": name,
                        "cat": cat,
                        "ph": "X",
                        "ts": (start["mono_ns"] - t0) / 1e3,
                        "dur": (rec["mono_ns"] - start["mono_ns"]) / 1e3,
                        "pid": track,
                        "tid": 0,
                        "args": {**args_of(start), **args_of(rec)},
                    }
                )
                continue
            # end without a start (file truncated at the front): instant
        out.append(
            {
                "name": event,
                "cat": INSTANT_CATEGORIES.get(
                    event,
                    event.split(".", 1)[0] if "." in event else "events",
                ),
                "ph": "i",
                "s": "t",
                "ts": ts_us,
                "pid": track,
                "tid": 0,
                "args": args_of(rec),
            }
        )
    # dangling starts (crash before the end event): zero-length instants so
    # the abandoned phase is still visible on the timeline
    for (track, key), stack in pending.items():
        for start in stack:
            _, name, cat = SPAN_PAIRS[key[0]]
            out.append(
                {
                    "name": f"{name} (unfinished)",
                    "cat": cat,
                    "ph": "i",
                    "s": "t",
                    "ts": (start["mono_ns"] - t0) / 1e3,
                    "pid": track,
                    "tid": 0,
                    "args": args_of(start),
                }
            )
    # a phase still running at the end of the stream extends to the last
    # event — visible, and marked unfinished
    end_us = (events[-1]["mono_ns"] - t0) / 1e3
    for (track, episode), (name, start_us) in list(ep_phase.items()):
        out.append({
            "name": f"{name} (unfinished)", "cat": "episode", "ph": "X",
            "ts": start_us, "dur": max(0.0, end_us - start_us),
            "pid": track, "tid": 0, "args": {"episode": episode},
        })
    out.extend(_episode_flows(ep_anchors))
    for track in sorted(tracks):
        out.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": track,
                "args": {"name": f"rank {track}"},
            }
        )
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("ph") != "M"))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def convert(paths: List[str], output: Optional[str] = None) -> Dict[str, Any]:
    trace = to_chrome_trace(load_aligned(paths))
    if output:
        with open(output, "w") as f:
            json.dump(trace, f)
    return trace


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_resiliency.telemetry.trace",
        description="Convert ProfilingRecorder/flight-recorder JSONL into "
        "Chrome-trace JSON (open in chrome://tracing or ui.perfetto.dev)",
    )
    parser.add_argument("inputs", nargs="+", help="JSONL file(s), one per rank")
    parser.add_argument(
        "-o", "--output",
        help="output path (default: stdout)",
    )
    args = parser.parse_args(argv)
    trace = convert(args.inputs, args.output)
    n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    n_flows = sum(
        1 for e in trace["traceEvents"] if e.get("ph") in ("s", "t", "f")
    )
    if args.output:
        print(
            f"wrote {args.output}: {n_spans} spans, {n_flows} flow events, "
            f"{len(trace['traceEvents'])} events",
            file=sys.stderr,
        )
    else:
        json.dump(trace, sys.stdout)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
