"""Device-side kernels for resiliency hot paths."""

from .quorum import QuorumMonitor, quorum_reduce

__all__ = ["QuorumMonitor", "quorum_reduce"]
