"""On-device ICI quorum heartbeat — the sub-millisecond hang-detection path.

North-star design (BASELINE.json): the reference's hang detection is a
host-side socket loop with seconds-scale latency (heartbeat timeout check
interval 5s — ``fault_tolerance/config.py:115-121``).  On TPU the pod's ICI
fabric itself can carry the liveness signal: every chip contributes a
heartbeat *age* (now - last_beat, wrap-safe int32 ms on a shared wall-clock
epoch), one all-reduce-max over the mesh returns the staleness of the oldest
heartbeat anywhere in the pod, and any chip observing ``max_age > budget``
knows some rank stalled — one collective (~µs over ICI at pod scale), no
host round-trips on the hot path.

Two layers:

- :func:`make_quorum_fn` — the jitted collective: per-device ages →
  pod-wide max age.  The local reduce body is a Pallas kernel on TPU feeding
  a ``lax.pmax`` over the mesh axis; a pure-jnp fallback covers CPU test
  meshes.  Identifying WHICH rank is stale happens on the rare stale path
  via a host gather — keeping the hot path to a single int32 all-reduce
  (TPUs lack native int64, and f32 lacks ms precision at epoch magnitude).
- :class:`QuorumMonitor` — host-side driver: publishes this process's stamp,
  runs the collective on a cadence, reports stale devices.  The host monitor
  path (RankMonitorServer) remains the source of truth: the kernel can only
  run while the program can still run collectives, so a wedged chip is
  detected by the *other* chips observing its stale stamp — and a wedged
  fabric falls through to the host path.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from ..utils.logging import get_logger

log = get_logger("quorum")


def _on_tpu() -> bool:
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


_WRAP = 2 ** 31
_I32_MAX = 2 ** 31 - 1


def now_stamp_ms() -> int:
    """Wall-clock ms folded into int32 — wall clock so every process shares
    the epoch (pod hosts are NTP-synced to ~ms, far inside any budget);
    int32 because f32 lacks ms precision at unix-epoch magnitude and TPUs
    have no native int64.  Wraps every ~24.8 days; age math is wrap-safe."""
    return int(time.time() * 1000.0) % _WRAP


def stamp_age_ms(now: int, then: int) -> int:
    return (now - then) % _WRAP


def make_local_max(use_pallas: bool) -> Callable:
    import jax
    import jax.numpy as jnp

    if not use_pallas:
        return jnp.max

    from jax.experimental import pallas as pl

    def kernel(ages_ref, out_ref):
        # scalar stores to VMEM are rejected; write the (1,1) tile
        out_ref[:] = jnp.max(ages_ref[:]).reshape(1, 1)

    def local_max(x):
        # pad to the int32 tile (8, 128)
        n = x.shape[0]
        pad = (-n) % (8 * 128)
        x2 = jnp.pad(x, (0, pad), constant_values=0).reshape(-1, 128)
        rows = x2.shape[0]
        row_pad = (-rows) % 8
        x2 = jnp.pad(x2, ((0, row_pad), (0, 0)), constant_values=0)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1, 1), x.dtype),
        )(x2)
        return out[0, 0]

    return local_max


# identify-mode packing: i32 = clamp(age_ms, 0, 2^15-1) << 16 | device_idx.
# A pmax over packed values sorts lexicographically by (age, device), so ONE
# collective — the same single int32 all-reduce as the age-only hot path —
# yields both the pod-wide max age AND which device holds it.  16 bits of
# device index covers 65k chips; 15 bits of age saturates at ~32.7s, far past
# any detection budget (saturated ages still compare correctly).
_AGE_CAP = (1 << 15) - 1


def pack_age_device(ages: "np.ndarray", device_idx: "np.ndarray") -> "np.ndarray":
    return (
        (np.minimum(ages, _AGE_CAP).astype(np.int32) << 16)
        | device_idx.astype(np.int32)
    )


def unpack_age_device(packed: int) -> tuple:
    return packed >> 16, packed & 0xFFFF


def make_quorum_fn(
    mesh,
    axis_name: Optional[str] = None,
    use_pallas: Optional[bool] = None,
    blocking: bool = True,
    identify: bool = False,
) -> Callable:
    """Build the jitted quorum collective over ``mesh``.

    Returns fn(stamps_ms: i32[n_local_devices]) -> max_age_ms (int): the
    staleness of the OLDEST heartbeat anywhere on the mesh.  The reduction
    runs over wrap-safe *ages* (now - stamp, mod 2^31), not raw stamps — a
    pmin over raw wrapped stamps would let a fresh post-wrap stamp mask a
    pre-wrap hung rank for ~24.8 days.

    With ``identify=True`` the ages are packed with each device's global
    index before the reduce (see :func:`pack_age_device` — the device path
    is the identical single int32 pmax) and the fn returns
    ``(max_age_ms, stale_device_idx)``: which chip's heartbeat is oldest,
    for free, so a trip can name the culprit without a second collective.

    Each process passes stamps for its OWN devices; the input global array is
    assembled with ``make_array_from_process_local_data`` so the call works on
    multi-host meshes.  All processes must call it together (collective)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = axis_name or mesh.axis_names[0]
    if use_pallas is None:
        use_pallas = _on_tpu()
    local_max = make_local_max(use_pallas)

    def _body(ages):
        return jax.lax.pmax(local_max(ages), axis)

    from ..utils.jax_compat import shard_map as shard_map_compat

    smapped = shard_map_compat(
        _body,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check=False,  # the pallas local-reduce's out vma is opaque to the checker
    )
    sharding = NamedSharding(mesh, P(axis))
    jitted = jax.jit(smapped)
    n_total = int(np.prod(mesh.devices.shape))
    n_local = len(mesh.local_devices) if hasattr(mesh, "local_devices") else n_total
    single_process = n_local == n_total
    if identify:
        # global flat position of each local device in mesh order
        flat = list(mesh.devices.flatten())
        local_devs = mesh.local_devices if hasattr(mesh, "local_devices") else flat
        local_idx = np.asarray([flat.index(d) for d in local_devs], dtype=np.int32)

    def _finish(packed: int):
        if not identify:
            return packed
        return unpack_age_device(packed)

    def run(local_stamps_ms):
        now = now_stamp_ms()
        local = np.asarray(local_stamps_ms, dtype=np.int64).reshape(n_local)
        ages = (now - local) % _WRAP
        # future == fresh (same rule as QuorumMonitor._current_stamp): a
        # stamp a few ms ahead of our pre-read `now` (NTP skew across
        # processes; a concurrent native beater) folds to ~2^31 — without
        # this clamp one such tick reads as a 24.8-day-stale heartbeat and
        # trips a spurious pod-wide restart (in identify mode it saturates
        # the 15-bit cap, same false trip).  A genuinely stale stamp past
        # the half-wrap horizon would have tripped eons earlier.
        ages = np.where(ages > _WRAP // 2, 0, ages).astype(np.int32)
        if identify:
            ages = pack_age_device(ages, local_idx)
        if single_process:
            # jit owns the tiny host->device transfer (one dispatch)
            global_ages = ages
        else:
            global_ages = jax.make_array_from_process_local_data(
                sharding, ages, (n_total,)
            )
        out = jitted(global_ages)
        # blocking: materialize now; non-blocking: hand back the device value
        # (int() on it later completes the dispatch) for pipelined ticks
        if blocking:
            return _finish(int(out))
        return out

    run.finish = _finish  # for pipelined callers materializing later
    return run


class QuorumMonitor:
    """Host driver for the on-device quorum tripwire.

    The workload calls :meth:`beat` every step (a host int write).  A daemon
    thread ticks the collective every ``interval`` seconds and calls
    ``on_stale(age_ms)`` when the pod-wide oldest stamp exceeds
    ``budget_ms``.  Ticks interleave with training steps on the device
    stream, so keep ``interval`` ≳ a step time.
    """

    def __init__(
        self,
        mesh,
        budget_ms: float = 1000.0,
        interval: float = 0.1,
        on_stale: Optional[Callable] = None,
        use_pallas: Optional[bool] = None,
        auto_beat_interval: Optional[float] = None,
        fetch_workers: int = 0,
        identify: bool = False,
        online_recalibrate_after: Optional[int] = None,
        online_min_budget_ms: float = 2.0,
        native_beat: bool = False,
    ):
        self.mesh = mesh
        self.budget_ms = budget_ms
        self.interval = interval
        self.auto_beat_interval = auto_beat_interval
        # >0 enables the overlapped loop: collectives dispatch every
        # ``interval`` and results are evaluated by a fetch thread pool, so
        # detection latency is budget + interval/2 + ONE readback even when
        # the result readback RTT dwarfs the interval (tunneled transports;
        # readbacks multiplex across threads, measured on the axon relay)
        self.fetch_workers = fetch_workers
        self.identify = identify
        self._last_seq = 0
        def _default_on_stale(age):
            from ..utils.profiling import ProfilingEvent, record_event

            log.error("pod heartbeat stale by %.1fms", age)
            record_event(ProfilingEvent.HANG_DETECTED, source="quorum", age_ms=age)

        self.on_stale = on_stale or _default_on_stale
        # tripwire callbacks may accept (age_ms, stale_device_idx); plain
        # age-only callbacks keep working
        try:
            import inspect

            n_params = len([
                p for p in inspect.signature(self.on_stale).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                or p.kind == p.VAR_POSITIONAL
            ])
        except (TypeError, ValueError):
            n_params = 1
        self._on_stale_wants_device = identify and n_params >= 2
        self.use_pallas = use_pallas
        self._fn = make_quorum_fn(mesh, use_pallas=use_pallas, identify=identify)
        self._fn_async = None
        self._pending = None  # (dispatch_t, device_value) in-flight slot
        # results DISPATCHED at or before this fence never fire on_stale —
        # they observed a hang era that a restart has since resolved
        self._fence_t = float("-inf")
        self._last_beat_ms = now_stamp_ms()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tpurx-quorum", daemon=True
        )
        self._beater_stop = threading.Event()
        self._beater: Optional[threading.Thread] = None
        self.last_max_age: Optional[int] = None
        self.last_stale_device: Optional[int] = None
        self.last_calibration_p99_ms: Optional[float] = None
        # Online recalibration: a pre-start calibrate() can only sample an
        # IDLE interpreter, and an idle-calibrated budget undershoots the
        # stamp lateness real training produces (false trips) — so after
        # ``online_recalibrate_after`` healthy ages observed by the RUNNING
        # loop (i.e. under the actual workload), the budget is recomputed
        # once from those in-vivo samples: safety*p99 + margin, floored at
        # ``online_min_budget_ms``.  Tripping ages are excluded (a real
        # hang must not inflate its own detection budget).
        self._recal_after = online_recalibrate_after
        self._recal_min_budget = online_min_budget_ms
        self._recal_ages: list = []
        self._recal_done = False
        # Native liveness beater (north-star lane): a C pthread stamping the
        # slot at machine cadence — its p99 jitter is scheduler noise (tens
        # of µs), not GIL scheduling (~1 ms), so calibrated budgets can go
        # sub-ms.  It proves PROCESS/DEVICE liveness only: a GIL-wedged
        # interpreter keeps a C thread stamping, so the Python beater (GIL
        # jitter is its feature) and the pending-call watchdog ring retain
        # GIL-wedge detection.  Falls back to the Python beater when the
        # toolchain can't build the helper.
        self._native_beat = native_beat
        self._native_slot = None
        self._native_handle = None
        self._native_lib = None

    def beat(self) -> None:
        self._last_beat_ms = now_stamp_ms()

    # -- liveness auto-beat (reference ProgressWatchdog auto-timestamps,
    # ``progress_watchdog.py:50-61``): a daemon thread stamping at
    # ``auto_beat_interval`` proves the interpreter schedules threads —
    # detects process death / GIL-holding wedges with a ms-scale budget,
    # independent of step cadence.  Manual ``beat()`` remains the
    # progress signal (budget tied to step time).
    def _beater_loop(self) -> None:
        while not self._beater_stop.is_set():
            self.beat()
            self._beater_stop.wait(self.auto_beat_interval)

    def _current_stamp(self) -> int:
        """Freshest liveness stamp: manual beat() or the native slot.

        Freshness compares wrap-safe AGES, not raw stamps — both sources
        fold into the int32 epoch (C side mirrors ``now_stamp_ms``), and a
        raw max() would both break at the 24.8-day wrap and let a stale
        native stamp shadow a fresh manual ``beat()``.

        A source can legitimately stamp a NEWER millisecond than our
        pre-read ``now`` (the C thread runs concurrently; NTP skew across
        processes): its age then folds to ~2^31 and a naive compare would
        discard the freshest stamp for a stale one — on a monitor whose
        manual beat() is seconds old, that single race tick trips a
        spurious restart.  Any age past the half-wrap horizon can only be
        a future stamp (a genuinely stale one would have tripped eons
        earlier), so clamp it to 0: future == fresh."""
        if self._native_slot is None:
            return self._last_beat_ms
        now = now_stamp_ms()
        a = self._last_beat_ms
        b = self._native_slot.value % _WRAP
        age_a = (now - a) % _WRAP
        age_b = (now - b) % _WRAP
        if age_a > _WRAP // 2:
            age_a = 0
        if age_b > _WRAP // 2:
            age_b = 0
        return a if age_a <= age_b else b

    def _start_native_beater(self) -> bool:
        import ctypes

        from ..utils.native import load_native

        if self._native_handle is not None:
            return True
        # the C thread writes into the slot until tpurx_beat_stop returns:
        # the slot must outlive a monitor dropped without stop() (the
        # registry pins it; __del__ is only best-effort)
        global _NATIVE_SLOT_KEEPALIVE
        if self._native_lib is None:
            self._native_lib = load_native(
                "libtpurx-beat.so", "beat_thread.c", extra_args=("-lpthread",),
                required_symbols=(
                    "tpurx_beat_start", "tpurx_beat_stop", "tpurx_beat_abi_v2",
                ),
            )
            if self._native_lib is not None:
                self._native_lib.tpurx_beat_start.argtypes = [
                    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ]
                self._native_lib.tpurx_beat_start.restype = ctypes.c_void_p
                self._native_lib.tpurx_beat_stop.argtypes = [ctypes.c_void_p]
        if self._native_lib is None:
            return False
        if self._native_slot is None:
            self._native_slot = ctypes.c_int64(now_stamp_ms())
        interval_us = int(max(0.00005, self.auto_beat_interval or 0.001) * 1e6)
        self._native_handle = self._native_lib.tpurx_beat_start(
            ctypes.byref(self._native_slot), interval_us
        )
        if self._native_handle is not None:
            _NATIVE_SLOT_KEEPALIVE[id(self)] = self._native_slot
        return self._native_handle is not None

    def _stop_native_beater(self) -> None:
        if self._native_handle is not None:
            self._native_lib.tpurx_beat_stop(self._native_handle)
            self._native_handle = None
            _NATIVE_SLOT_KEEPALIVE.pop(id(self), None)

    def __del__(self):  # best-effort: registry already prevents UAF
        try:
            self._stop_native_beater()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def _start_beater(self) -> None:
        if self.auto_beat_interval is None:
            return
        if self._native_beat and self._start_native_beater():
            return
        if self._beater is None or not self._beater.is_alive():
            self._beater_stop.clear()  # un-latch a previous stop_auto_beat
            self._beater = threading.Thread(
                target=self._beater_loop, name="tpurx-quorum-beat", daemon=True
            )
            self._beater.start()

    def stop_auto_beat(self) -> None:
        """Stop the liveness beater (tests/benchmarks simulate a wedged
        process this way — stamps freeze while the tick loop, playing the
        healthy peers' role, keeps reducing)."""
        self._beater_stop.set()
        if self._beater is not None:
            self._beater.join(timeout=2)
        # freeze semantics: the slot keeps its last stamp so ages grow from
        # the freeze instant, mirroring a wedged process
        self._stop_native_beater()

    def resume_auto_beat(self) -> None:
        """Re-arm the liveness beater (a rank recovered by the restart ring
        is alive again; its silence must stop reading as a pod hang).
        In-flight collectives dispatched during the hang era are fenced:
        their (stale-by-construction) results must not re-trip the ring."""
        self.beat()
        self._fence_t = time.monotonic()
        self._start_beater()

    def calibrate(self, n_ticks: int = 20, safety: float = 3.0,
                  margin_ms: float = 2.0, min_budget_ms: float = 5.0,
                  load_fn: Optional[Callable] = None) -> float:
        """Derive the detection budget from OBSERVED healthy tick ages
        (beat jitter + scheduling noise) instead of a safety factor over the
        beat period alone — ages already embed every real-world delay, so the
        budget is as tight as the platform allows without false positives.
        Runs ``n_ticks`` blocking ticks, sets and returns ``budget_ms``.

        ``load_fn`` (e.g. one training-step dispatch) runs before each
        calibration tick so the sampled ages embed the GIL/scheduler
        contention of REAL training — required before trusting a tight
        ``margin_ms``: a budget calibrated on an idle interpreter undershoots
        the stamp lateness a busy one produces and then false-trips.

        The floor physics (BASELINE north-star accounting): in XLA's
        execution model a collective observes stamps only at dispatch, so
        end-to-end detection = budget + dispatch cadence + one readback.
        The budget itself cannot go below the observed p99 healthy age
        times ``safety`` without false positives — and that p99 is the beat
        interval plus GIL-scheduling jitter of the Python beater thread,
        which is load-bearing: a C beater would keep stamping through a
        GIL-wedged interpreter and mask exactly the hangs this exists to
        catch.  ``min_budget_ms`` is an operator floor, not a physical one;
        set it to ~1 to let the calibration find the platform's true floor
        (the measured p99 is kept in ``last_calibration_p99_ms``)."""
        self._start_beater()
        ages = []
        for _ in range(max(3, n_ticks)):
            if load_fn is not None:
                load_fn()
            saved = self.budget_ms
            self.budget_ms = float("inf")  # no trips during calibration
            try:
                ages.append(self.tick())
            finally:
                self.budget_ms = saved
        ages_arr = np.asarray(sorted(ages), dtype=np.float64)
        p99 = float(ages_arr[min(len(ages_arr) - 1, int(0.99 * len(ages_arr)))])
        self.last_calibration_p99_ms = p99
        self.budget_ms = max(min_budget_ms, safety * p99 + margin_ms)
        return self.budget_ms

    def _observe_healthy_age(self, age: float) -> None:
        """Feed the online recalibration with an under-load healthy age."""
        if self._recal_after is None or self._recal_done or age > self.budget_ms:
            return
        self._recal_ages.append(float(age))
        if len(self._recal_ages) < self._recal_after:
            return
        ages = sorted(self._recal_ages)
        p99 = ages[min(len(ages) - 1, int(0.99 * len(ages)))]
        new_budget = max(self._recal_min_budget, 3.0 * p99 + 2.0)
        log.info(
            "quorum online recalibration: budget %.1fms -> %.1fms "
            "(p99 under load %.2fms over %d ticks)",
            self.budget_ms, new_budget, p99, len(ages),
        )
        self.last_calibration_p99_ms = p99
        self.budget_ms = new_budget
        self._recal_done = True
        self._recal_ages = []

    def _split(self, result):
        if self.identify:
            return result
        return result, None

    def _fire(self, age: int, dev: Optional[int]) -> None:
        if self._on_stale_wants_device:
            self.on_stale(age, dev)
        else:
            self.on_stale(age)

    def tick(self) -> int:
        """One collective; returns the pod-wide max heartbeat age (ms)."""
        n_local = (
            len(self.mesh.local_devices)
            if hasattr(self.mesh, "local_devices")
            else int(np.prod(self.mesh.devices.shape))
        )
        stamps = np.full(n_local, self._current_stamp(), dtype=np.int64)
        age, dev = self._split(self._fn(stamps))
        self.last_max_age = age
        self.last_stale_device = dev
        self._observe_healthy_age(age)
        if age > self.budget_ms:
            self._fire(age, dev)
        return age

    def tick_pipelined(self) -> Optional[int]:
        """Pipelined variant: dispatch this tick's collective without blocking
        and evaluate the PREVIOUS tick's result.  Hides the device round-trip
        behind the tick interval — on a dispatch-latency-bound link the
        effective cadence doubles, at the cost of results lagging one tick
        (bounded, and far under any budget).  Returns the previous age, or
        None on the first call."""
        if self._fn_async is None:
            self._fn_async = make_quorum_fn(
                self.mesh, use_pallas=self.use_pallas, blocking=False,
                identify=self.identify,
            )
        n_local = (
            len(self.mesh.local_devices)
            if hasattr(self.mesh, "local_devices")
            else int(np.prod(self.mesh.devices.shape))
        )
        stamps = np.full(n_local, self._current_stamp(), dtype=np.int64)
        pending = self._fn_async(stamps)
        previous, self._pending = self._pending, (time.monotonic(), pending)
        if previous is None:
            return None
        t_disp, value = previous
        # int() materializes the already-dispatched result
        age, dev = self._split(self._fn_async.finish(int(value)))
        self.last_max_age = age
        self.last_stale_device = dev
        self._observe_healthy_age(age)
        if age > self.budget_ms and t_disp > self._fence_t:
            self._fire(age, dev)
        return age

    def warmup(self) -> None:
        """Compile + run both collective variants so the monitor loop's
        first iteration doesn't spend ~0.5s tracing while hangs go
        unobserved."""
        saved = self.budget_ms
        self.budget_ms = float("inf")
        try:
            self.tick()
            self.tick_pipelined()
            self.tick_pipelined()
            # drain the in-flight dispatch: its host-side age includes the
            # compile time above and would trip a spurious on_stale as the
            # loop's first evaluated result
            if self._pending is not None:
                int(self._pending[1])
                self._pending = None
        finally:
            self.budget_ms = saved

    def start(self) -> "QuorumMonitor":
        self.beat()
        self._start_beater()
        if self._fn_async is None:
            self.warmup()
        self.beat()
        self._thread.start()
        return self

    def _loop(self) -> None:
        if self.fetch_workers > 0:
            self._loop_overlapped()
            return
        # pipelined ticks: the device round-trip hides behind the interval,
        # so the effective detection cadence is ~interval instead of
        # interval + round-trip (documented one-tick result lag)
        while not self._stop.is_set():
            try:
                self.tick_pipelined()
            except Exception as exc:  # noqa: BLE001
                log.warning("quorum tick failed: %s", exc)
                return
            self._stop.wait(self.interval)

    def _loop_overlapped(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        if self._fn_async is None:
            self._fn_async = make_quorum_fn(
                self.mesh, use_pallas=self.use_pallas, blocking=False,
                identify=self.identify,
            )
        n_local = (
            len(self.mesh.local_devices)
            if hasattr(self.mesh, "local_devices")
            else int(np.prod(self.mesh.devices.shape))
        )
        lock = threading.Lock()
        inflight = [0]

        def evaluate(seq, t_disp, pending):
            try:
                age, dev = self._split(self._fn_async.finish(int(pending)))
            except Exception as exc:  # noqa: BLE001
                log.warning("quorum fetch failed: %s", exc)
                return
            finally:
                with lock:
                    inflight[0] -= 1
            # on_stale stays serialized and at-most-once per dispatch seq
            # (monotonic), matching the single-threaded tick loop's contract
            # — restart machinery wired to it need not be re-entrant
            fire = False
            with lock:
                if seq > self._last_seq:
                    self._last_seq = seq
                    self.last_max_age = age
                    self.last_stale_device = dev
                    self._observe_healthy_age(age)
                    fire = age > self.budget_ms and t_disp > self._fence_t
                if fire:
                    self._fire(age, dev)

        # interval == 0 is the DENSE RE-DISPATCHED CHAIN: the next collective
        # dispatches the moment a slot frees, so the cadence term of the
        # detection floor (budget + cadence + readback) collapses from a
        # polling interval to the dispatch cost itself (~0.1-0.5 ms).  The
        # in-flight cap keeps the chain bounded; evaluation stays on the
        # fetch pool.
        seq = 0
        with ThreadPoolExecutor(
            max_workers=self.fetch_workers, thread_name_prefix="tpurx-quorum-fetch"
        ) as pool:
            while not self._stop.is_set():
                with lock:
                    free = inflight[0] < self.fetch_workers
                if free:
                    try:
                        stamps = np.full(n_local, self._current_stamp(), dtype=np.int64)
                        pending = self._fn_async(stamps)
                    except Exception as exc:  # noqa: BLE001
                        log.warning("quorum dispatch failed: %s", exc)
                        return
                    seq += 1
                    with lock:
                        inflight[0] += 1
                    pool.submit(evaluate, seq, time.monotonic(), pending)
                    if self.interval > 0:
                        self._stop.wait(self.interval)
                else:
                    # all slots busy: yield briefly instead of spinning
                    self._stop.wait(self.interval or 0.0002)

    def stop(self) -> None:
        self._stop.set()
        self.stop_auto_beat()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


def quorum_reduce(mesh, stamps_ms) -> int:
    """One-shot quorum collective: max heartbeat age (ms) across the mesh
    (builds + caches the fn per mesh)."""
    key = id(mesh)
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = make_quorum_fn(mesh)
        _FN_CACHE[key] = fn
    return fn(stamps_ms)


_FN_CACHE: dict = {}
# ctypes slots written by live native beater threads: pinned until the
# matching tpurx_beat_stop returns (see _start_native_beater)
_NATIVE_SLOT_KEEPALIVE: dict = {}
