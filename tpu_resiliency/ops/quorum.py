"""On-device ICI quorum heartbeat — the sub-millisecond hang-detection path.

North-star design (BASELINE.json): the reference's hang detection is a
host-side socket loop with seconds-scale latency (heartbeat timeout check
interval 5s — ``fault_tolerance/config.py:115-121``).  On TPU the pod's ICI
fabric itself can carry the liveness signal: every chip contributes a
heartbeat *age* (now - last_beat, wrap-safe on a shared wall-clock epoch),
one all-reduce-max over the mesh returns the staleness of the oldest
heartbeat anywhere in the pod, and any chip observing ``max_age > budget``
knows some rank stalled — one collective (~µs over ICI at pod scale), no
host round-trips on the hot path.

Stamp contract (v3 — the ns-scale rebuild; see ``docs/detection.md``):

- **Host domain**: stamps are ``CLOCK_REALTIME`` nanoseconds folded into
  ``[0, 2^63)`` (:func:`now_stamp_ns`) — wall clock so every process and the
  native C beater share the epoch (pod hosts are NTP-synced to ~ms, far
  inside any budget).  Age math is wrap-safe mod 2^63
  (:func:`stamp_age_ns`), and any age past the half-wrap horizon can only
  be a FUTURE stamp (NTP skew, a concurrently-stamping C thread), so it
  clamps to 0: future == fresh.
- **Device domain**: TPUs lack native int64 and f32 lacks ns precision at
  epoch magnitude, so the collective reduces int32 *ages* quantized to the
  device quantum ``2^15 ns = 32.768 µs`` (:data:`DEV_QUANTUM_NS`).  Ages
  saturate rather than wrap on device: the host computes the wrap-safe ns
  age, shifts, and clips — the device only ever compares saturating
  non-negative int32 units.
- **Intervals and jitter** are measured on ``CLOCK_MONOTONIC`` (native
  side) — an NTP step must never appear as beat jitter or a negative age.

Layers:

- :func:`make_quorum_fn` — the jitted collective: per-device ages →
  pod-wide max age.  The local reduce body is a Pallas kernel on TPU feeding
  a ``lax.pmax`` over the mesh axis; a pure-jnp fallback covers CPU test
  meshes.  Identifying WHICH rank is stale rides the same single int32
  all-reduce via age/device packing (:func:`pack_age_device`).
- :class:`FusedStepQuorum` — the ICI lane: the same packed reduce fused
  into the *training step's* dispatch, so pod-wide oldest-stamp detection
  is one allreduce riding the interconnect at step cadence — detection cost
  independent of rank count, host tripwire as backstop.
- :class:`NativeBeater` — pinned C pthread (ABI v3) stamping the slot at
  machine cadence with a generation word futex-woken on every beat.
- :class:`StampTripwire` — event-driven staleness watcher:
  ``futex(FUTEX_WAIT)`` on the beat generation word (``threading.Event``
  fallback), so staleness is observed at wake latency, not poll-interval
  granularity.  The wait loop contains no polling sleep.
- :class:`QuorumMonitor` — host-side driver: publishes this process's
  stamp, runs the collective on a cadence, reports stale devices.  The host
  monitor path (RankMonitorServer) remains the source of truth: the kernel
  can only run while the program can still run collectives, so a wedged
  chip is detected by the *other* chips observing its stale stamp — and a
  wedged fabric falls through to the host path.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from ..telemetry import counter, gauge, histogram
from ..utils import env
from ..utils.logging import get_logger

log = get_logger("quorum")


def _on_tpu() -> bool:
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


# -- stamp contract (host ns domain / device quantum) -----------------------

_WRAP_BITS = 63
_WRAP_NS = 1 << _WRAP_BITS          # host epoch fold (int64-safe)
_HALF_NS = 1 << (_WRAP_BITS - 1)    # future==fresh horizon
_MASK_NS = np.uint64(_WRAP_NS - 1)

DEV_SHIFT = 15                      # device quantum: 2^15 ns = 32.768 µs
DEV_QUANTUM_NS = 1 << DEV_SHIFT
_I32_MAX = 2 ** 31 - 1

# identify-mode packing: i32 = clamp(age_units, 0, 2^15-1) << 16 | dev_idx.
# A pmax over packed values sorts lexicographically by (age, device), so ONE
# collective — the same single int32 all-reduce as the age-only hot path —
# yields both the pod-wide max age AND which device holds it.  16 bits of
# device index covers 65k chips; 15 bits of age in device-quantum units
# saturates at (2^15-1) * 2^15 ns ≈ 1.073 s — identify-mode budgets must sit
# below AGE_CAP_MS (any sane detection budget does; saturated ages still
# compare correctly, they lose magnitude, not ordering).
_AGE_CAP = (1 << 15) - 1            # identify-mode age cap, in quantum units
_AGE_CAP_NS = _AGE_CAP << DEV_SHIFT
AGE_CAP_MS = _AGE_CAP_NS / 1e6      # ≈ 1073.7 ms


def now_stamp_ns() -> int:
    """Wall-clock ns folded into ``[0, 2^63)`` — wall clock so every process
    (and the native beater, ABI v3 parity) shares the epoch.  The fold is an
    identity until year ~2262; the age math stays wrap-safe regardless."""
    return time.time_ns() % _WRAP_NS


def stamp_age_ns(now: int, then: int) -> int:
    """Wrap-safe ns age of ``then`` as seen at ``now`` (both folded)."""
    return (now - then) % _WRAP_NS


def clamp_future_ns(age_ns: int) -> int:
    """future == fresh: an age past the half-wrap horizon can only be a
    stamp from the future (NTP skew across processes, a concurrently
    stamping native thread) — a genuinely stale stamp would have tripped
    eons earlier.  Without this clamp one such tick reads as an eras-stale
    heartbeat and trips a spurious pod-wide restart."""
    return 0 if age_ns > _HALF_NS else age_ns


def wall_time_s() -> float:
    """Sanctioned wall-clock seconds for double-slot stamp contracts (the
    progress-watchdog shm slot, monitor shared state).  Every liveness stamp
    in the repo derives from this module's clock helpers — the hygiene suite
    bans raw ``time.time()``-derived stamps elsewhere so the epoch/clock
    contract has exactly one home."""
    return time.time_ns() / 1e9


def ages_ns_from_stamps(now_ns: int, stamps_ns: "np.ndarray") -> "np.ndarray":
    """Vector wrap-safe ages (uint64 ns) with the future==fresh clamp.

    The mod-2^63 subtraction runs in uint64 with a mask — numpy int64 can
    hold neither the 2^63 modulus nor the intermediate difference."""
    local = np.asarray(stamps_ns).astype(np.uint64)
    age = (np.uint64(now_ns) - local) & _MASK_NS
    return np.where(age > np.uint64(_HALF_NS), np.uint64(0), age)


def age_units(age_ns) -> "np.ndarray":
    """ns age → saturating int32 device units (quantum ``2^15 ns``)."""
    units = np.asarray(age_ns).astype(np.uint64) >> np.uint64(DEV_SHIFT)
    return np.minimum(units, np.uint64(_I32_MAX)).astype(np.int32)


def units_to_ns(units: int) -> int:
    return int(units) << DEV_SHIFT


# -- telemetry (single declaration site for the detection plane) ------------

_DETECT_NS = histogram(
    "tpurx_quorum_detect_ns",
    "Staleness age observed at trip time (ns), per detection lane "
    "(collective / futex / fused)",
    labels=("lane",),
)
_BEAT_JITTER_P99_US = gauge(
    "tpurx_beat_jitter_p99_us",
    "Native beater stamp-interval lateness p99 (µs) — CLOCK_MONOTONIC-"
    "sourced, so an NTP step can never appear as beat jitter",
)
_BEAT_SCHED = gauge(
    "tpurx_beat_sched_flags",
    "Native beater scheduling state: bit0 = affinity-pinned, "
    "bit1 = SCHED_FIFO granted",
)
_TRIPWIRE_WAITS = counter(
    "tpurx_quorum_futex_waits_total",
    "Stamp-tripwire wait outcomes (fresh = woken by a beat, stale = "
    "budget elapsed with no beat, error = futex unavailable)",
    labels=("outcome",),
)


def make_local_max(use_pallas: bool) -> Callable:
    import jax
    import jax.numpy as jnp

    if not use_pallas:
        return jnp.max

    from jax.experimental import pallas as pl

    def kernel(ages_ref, out_ref):
        # scalar stores to VMEM are rejected; write the (1,1) tile
        out_ref[:] = jnp.max(ages_ref[:]).reshape(1, 1)

    def local_max(x):
        # pad to the int32 tile (8, 128)
        n = x.shape[0]
        pad = (-n) % (8 * 128)
        x2 = jnp.pad(x, (0, pad), constant_values=0).reshape(-1, 128)
        rows = x2.shape[0]
        row_pad = (-rows) % 8
        x2 = jnp.pad(x2, ((0, row_pad), (0, 0)), constant_values=0)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1, 1), x.dtype),
        )(x2)
        return out[0, 0]

    return local_max


def pack_age_device(age_units_arr: "np.ndarray", device_idx: "np.ndarray") -> "np.ndarray":
    """Pack (age in device-quantum units, device index) into one int32 whose
    pmax sorts lexicographically by (age, device)."""
    return (
        (np.minimum(np.asarray(age_units_arr, dtype=np.int64), _AGE_CAP)
         .astype(np.int32) << 16)
        | np.asarray(device_idx).astype(np.int32)
    )


def unpack_age_device(packed: int) -> tuple:
    """packed int32 → (age in quantum units, device index)."""
    return packed >> 16, packed & 0xFFFF


def make_quorum_fn(
    mesh,
    axis_name: Optional[str] = None,
    use_pallas: Optional[bool] = None,
    blocking: bool = True,
    identify: bool = False,
) -> Callable:
    """Build the jitted quorum collective over ``mesh``.

    Returns fn(stamps_ns: i64[n_local_devices]) -> max_age_ns (int): the
    staleness of the OLDEST heartbeat anywhere on the mesh, quantized to the
    device quantum (``2^15 ns``).  The reduction runs over wrap-safe *ages*
    (now - stamp, mod 2^63, future==fresh clamped, then quantized to
    saturating int32 units), not raw stamps — a pmin over raw wrapped
    stamps would let a fresh post-wrap stamp mask a pre-wrap hung rank.

    With ``identify=True`` the ages are packed with each device's global
    index before the reduce (see :func:`pack_age_device` — the device path
    is the identical single int32 pmax) and the fn returns
    ``(max_age_ns, stale_device_idx)``: which chip's heartbeat is oldest,
    for free, so a trip can name the culprit without a second collective.
    Identify-mode ages saturate at :data:`AGE_CAP_MS` (~1.07 s) — budgets
    must sit below it (they do: sub-ms is the point of this lane).

    Each process passes stamps for its OWN devices; the input global array is
    assembled with ``make_array_from_process_local_data`` so the call works on
    multi-host meshes.  All processes must call it together (collective)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = axis_name or mesh.axis_names[0]
    if use_pallas is None:
        use_pallas = _on_tpu()
    local_max = make_local_max(use_pallas)

    def _body(ages):
        return jax.lax.pmax(local_max(ages), axis)

    from ..utils.jax_compat import shard_map as shard_map_compat

    smapped = shard_map_compat(
        _body,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check=False,  # the pallas local-reduce's out vma is opaque to the checker
    )
    sharding = NamedSharding(mesh, P(axis))
    jitted = jax.jit(smapped)
    n_total = int(np.prod(mesh.devices.shape))
    n_local = len(mesh.local_devices) if hasattr(mesh, "local_devices") else n_total
    single_process = n_local == n_total
    if identify:
        # global flat position of each local device in mesh order
        flat = list(mesh.devices.flatten())
        local_devs = mesh.local_devices if hasattr(mesh, "local_devices") else flat
        local_idx = np.asarray([flat.index(d) for d in local_devs], dtype=np.int32)

    def _finish(packed: int):
        if not identify:
            return units_to_ns(packed)
        units, dev = unpack_age_device(packed)
        return units_to_ns(units), dev

    def run(local_stamps_ns):
        now = now_stamp_ns()
        ages = age_units(ages_ns_from_stamps(now, local_stamps_ns).reshape(n_local))
        if identify:
            ages = pack_age_device(ages, local_idx)
        if single_process:
            # jit owns the tiny host->device transfer (one dispatch)
            global_ages = ages
        else:
            global_ages = jax.make_array_from_process_local_data(
                sharding, ages, (n_total,)
            )
        out = jitted(global_ages)
        # blocking: materialize now; non-blocking: hand back the device value
        # (int() on it later completes the dispatch) for pipelined ticks
        if blocking:
            return _finish(int(out))
        return out

    run.finish = _finish  # for pipelined callers materializing later
    return run


# -- native beater (ABI v3): pinned C pthread + futex-woken generation ------

ENV_PIN_CPU = env.BEAT_PIN_CPU.name
ENV_RT_PRIO = env.BEAT_RT_PRIO.name

_BEAT_SYMBOLS = (
    "tpurx_beat_start", "tpurx_beat_stop", "tpurx_beat_abi_v3",
    "tpurx_beat_wait_stale", "tpurx_beat_kick", "tpurx_beat_jitter",
    "tpurx_beat_flags", "tpurx_beat_now_ns", "tpurx_beat_wrap_bits",
    "tpurx_beat_freeze",
)

# ctypes slots/generation words written by live native beater threads (and
# touchable by queued futex waiters): pinned until the matching
# tpurx_beat_stop returns — a beater dropped without stop() must never let
# the C thread write freed memory (__del__ is only best-effort)
_NATIVE_SLOT_KEEPALIVE: dict = {}


def _default_pin_cpu() -> int:
    """Default pin target: the highest-numbered CPU in our affinity mask
    (conventionally the least-contended by rank-pinned workloads); -1
    disables pinning (single-CPU hosts: pinning to the only core is a
    no-op that still costs an RT-throttle risk, skip it)."""
    try:
        cpus = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return -1
    if len(cpus) <= 1:
        return -1
    return cpus[-1]


def load_beat_lib():
    """Load (building if needed) the ABI-v3 beat helper; None without a
    toolchain.  The required-symbol set forces a rebuild over any stale v2
    ``.so`` — v2 stamped int32 milliseconds and lacks the generation word,
    so mixing it with ns-domain readers would silently break age math."""
    import ctypes

    from ..utils.native import load_native

    lib = load_native(
        "libtpurx-beat.so", "beat_thread.c",
        extra_args=("-lpthread", "-D_GNU_SOURCE"),
        required_symbols=_BEAT_SYMBOLS,
    )
    if lib is not None:
        lib.tpurx_beat_start.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
        lib.tpurx_beat_start.restype = ctypes.c_void_p
        lib.tpurx_beat_stop.argtypes = [ctypes.c_void_p]
        lib.tpurx_beat_freeze.argtypes = [ctypes.c_void_p]
        lib.tpurx_beat_flags.argtypes = [ctypes.c_void_p]
        lib.tpurx_beat_flags.restype = ctypes.c_int
        lib.tpurx_beat_jitter.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.tpurx_beat_jitter.restype = ctypes.c_int
        lib.tpurx_beat_wait_stale.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32, ctypes.c_int64,
        ]
        lib.tpurx_beat_wait_stale.restype = ctypes.c_int
        lib.tpurx_beat_kick.argtypes = [ctypes.POINTER(ctypes.c_uint32)]
        lib.tpurx_beat_now_ns.restype = ctypes.c_int64
        lib.tpurx_beat_wrap_bits.restype = ctypes.c_int
    return lib


class NativeBeater:
    """Pinned native liveness beater: a C pthread stamping ns wall-clock
    into ``slot`` at a fixed CLOCK_MONOTONIC cadence, bumping ``gen`` and
    futex-waking waiters on every beat.

    Why native: the Python auto-beat thread's stamp jitter is GIL-scheduling
    noise (p99 ~1 ms contended) and calibrated budgets must sit above
    safety*p99 — a hard multi-ms floor.  The C thread never touches the GIL
    and is pinned (sched affinity + best-effort SCHED_FIFO, graceful
    fallback), so its p99 is tens of µs, unlocking sub-ms budgets for the
    PROCESS/DEVICE-liveness hang class.  It deliberately does NOT prove
    interpreter schedulability: a GIL-wedged interpreter keeps a C thread
    stamping — the Python beater and pending-call watchdog own that class.

    ``slot``/``gen`` are allocated once per instance and survive
    start/stop cycles, so :class:`StampTripwire` references stay valid
    across a freeze (stop) / resume — stop() freezes the stamp at its last
    value, mirroring a wedged process."""

    JITTER_RING = 256

    def __init__(self, interval_s: float = 0.001,
                 pin_cpu: Optional[int] = None,
                 rt_prio: Optional[int] = None):
        import ctypes

        self.interval_s = max(0.00005, interval_s)
        if pin_cpu is None:
            pin_cpu = env.BEAT_PIN_CPU.get(default=_default_pin_cpu())
        if rt_prio is None:
            rt_prio = env.BEAT_RT_PRIO.get()
        self.pin_cpu = pin_cpu
        self.rt_prio = rt_prio
        self.slot = ctypes.c_int64(now_stamp_ns())
        self.gen = ctypes.c_uint32(0)
        self.flags = 0
        self._lib = None
        self._handle = None
        self._final_jitter: Optional[np.ndarray] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> bool:
        import ctypes

        if self._handle is not None:
            return True
        if self._lib is None:
            self._lib = load_beat_lib()
        if self._lib is None:
            return False
        self.slot.value = now_stamp_ns()
        self._handle = self._lib.tpurx_beat_start(
            ctypes.byref(self.slot), ctypes.byref(self.gen),
            int(self.interval_s * 1e6), self.pin_cpu, self.rt_prio,
        )
        if self._handle is None:
            return False
        _NATIVE_SLOT_KEEPALIVE[id(self)] = (self.slot, self.gen)
        self.flags = int(self._lib.tpurx_beat_flags(self._handle))
        _BEAT_SCHED.set(self.flags)
        self._final_jitter = None
        return True

    def freeze(self) -> None:
        """Stop stamping WITHOUT joining the C thread: the stamp freezes
        within one beat interval, exactly as on a real wedge — benchmarks
        use this so freeze->detect latency excludes the caller's join time.
        :meth:`stop` must still follow to join and free."""
        if self._handle is not None:
            self._lib.tpurx_beat_freeze(self._handle)

    def stop(self) -> None:
        """Stop stamping (joins the C thread).  The slot keeps its last
        stamp and the gen word freezes — ages grow from the freeze instant,
        and futex waiters time out exactly as they would on a wedge."""
        if self._handle is None:
            return
        self._final_jitter = self.jitter_ns()
        self._lib.tpurx_beat_stop(self._handle)
        self._handle = None
        _NATIVE_SLOT_KEEPALIVE.pop(id(self), None)

    def __del__(self):  # best-effort: keepalive registry prevents UAF
        try:
            self.stop()
        # tpurx: disable=TPURX009 -- __del__ at interpreter teardown: any raise prints unraisable-noise to stderr
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    @property
    def alive(self) -> bool:
        return self._handle is not None

    # -- stamp / generation ------------------------------------------------

    @property
    def stamp_ns(self) -> int:
        return self.slot.value % _WRAP_NS

    @property
    def generation(self) -> int:
        return self.gen.value

    def age_ns(self) -> int:
        return clamp_future_ns(stamp_age_ns(now_stamp_ns(), self.stamp_ns))

    def wait_stale(self, expected_gen: int, timeout_ns: int) -> int:
        """futex(FUTEX_WAIT) on the generation word: 0 = a beat arrived
        (or the word already moved), 1 = ``timeout_ns`` elapsed with no
        beat, <0 = -errno (no futex on this platform).  Releases the GIL
        for the wait (ctypes foreign call)."""
        import ctypes

        if self._lib is None:
            self._lib = load_beat_lib()
        if self._lib is None:
            return -95  # EOPNOTSUPP: caller falls back to Event mode
        return int(self._lib.tpurx_beat_wait_stale(
            ctypes.byref(self.gen), ctypes.c_uint32(expected_gen),
            ctypes.c_int64(timeout_ns),
        ))

    def kick(self) -> None:
        """Bump gen + wake futex waiters without a stamp (tripwire stop)."""
        import ctypes

        if self._lib is not None:
            self._lib.tpurx_beat_kick(ctypes.byref(self.gen))

    # -- jitter accounting (CLOCK_MONOTONIC, native-measured) --------------

    def jitter_ns(self) -> np.ndarray:
        """Most recent per-beat wake lateness samples (ns, monotonic clock;
        ≤ :data:`JITTER_RING`).  After stop(), the last live snapshot."""
        import ctypes

        if self._handle is None:
            if self._final_jitter is not None:
                return self._final_jitter
            return np.empty(0, dtype=np.int64)
        buf = (ctypes.c_int64 * self.JITTER_RING)()
        n = int(self._lib.tpurx_beat_jitter(self._handle, buf, self.JITTER_RING))
        return np.asarray(buf[: max(0, n)], dtype=np.int64)

    def jitter_p99_us(self) -> Optional[float]:
        samples = self.jitter_ns()
        if samples.size == 0:
            return None
        p99 = float(np.percentile(samples, 99)) / 1e3
        _BEAT_JITTER_P99_US.set(p99)
        return p99


class StampTripwire:
    """Event-driven staleness watcher on the liveness beat.

    Replaces the polling read of the stamp slot: the watcher thread parks in
    ``futex(FUTEX_WAIT)`` on the beater's generation word (native mode) or
    in ``threading.Event.wait`` (fallback), with the detection budget as the
    wait timeout.  A beat wakes it (re-arm); a timeout IS the detection —
    staleness is observed at wake latency, not poll-interval granularity.
    The wait loop contains **no polling sleep** (asserted by test).

    What it proves depends on the beat source: wired to a
    :class:`NativeBeater` it detects process/device-liveness loss; wired to
    the Python beater's event (or :class:`ProgressWatchdog` pings) it
    detects GIL-liveness loss.  Either way the budget is read through
    ``budget_ms_fn`` every wait, so calibration updates and
    protected-section suspensions (budget=inf) apply to the *next* wait
    without restarting the thread.
    """

    REARM_MS = 200.0  # chunked re-arm wait while suppressed or post-trip

    def __init__(
        self,
        on_stale: Callable[[float], None],
        budget_ms: float = 50.0,
        budget_ms_fn: Optional[Callable[[], float]] = None,
        beater: Optional[NativeBeater] = None,
        event: Optional[threading.Event] = None,
        age_ns_fn: Optional[Callable[[], int]] = None,
        name: str = "tpurx-stamp-tripwire",
    ):
        if (beater is None) == (event is None):
            raise ValueError("exactly one of beater= / event= is required")
        self.on_stale = on_stale
        self._budget_fn = budget_ms_fn or (lambda: budget_ms)
        self.beater = beater
        self.event = event
        if age_ns_fn is None:
            if beater is None:
                raise ValueError("event mode requires age_ns_fn")
            age_ns_fn = beater.age_ns
        self._age_ns_fn = age_ns_fn
        self._stop = False
        self.trip_count = 0
        self.last_trip_age_ms: Optional[float] = None
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)

    def start(self) -> "StampTripwire":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop = True
        # wake the parked waiter so stop() returns at wake latency too
        if self.beater is not None:
            self.beater.kick()
        else:
            self.event.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2)

    def _fire(self, age_ns: int) -> None:
        age_ms = age_ns / 1e6
        self.trip_count += 1
        self.last_trip_age_ms = age_ms
        _TRIPWIRE_WAITS.labels("stale").inc()
        _DETECT_NS.labels("futex").observe(age_ns)
        try:
            self.on_stale(age_ms)
        except Exception:  # noqa: BLE001 - the watcher must survive
            log.exception("stamp tripwire on_stale failed")

    def _loop(self) -> None:
        if self.beater is not None:
            self._loop_futex()
        else:
            self._loop_event()

    def _loop_futex(self) -> None:
        rearm_ns = int(self.REARM_MS * 1e6)
        while not self._stop:
            budget_ms = self._budget_fn()
            finite = math.isfinite(budget_ms)
            g = self.beater.generation
            rc = self.beater.wait_stale(
                g, int(budget_ms * 1e6) if finite else rearm_ns
            )
            if self._stop:
                return
            if rc == 0:
                _TRIPWIRE_WAITS.labels("fresh").inc()
                continue
            if rc < 0:
                # no futex on this platform: nothing to park on — bail out
                # (callers pair with the Event-mode fallback)
                _TRIPWIRE_WAITS.labels("error").inc()
                log.warning("futex wait unavailable (errno %d); tripwire exiting", -rc)
                return
            if not finite:
                continue  # suppressed (protected section): re-check budget
            age_ns = self._age_ns_fn()
            if age_ns / 1e6 <= budget_ms:
                # a manual beat() refreshed the stamp without bumping gen
                _TRIPWIRE_WAITS.labels("fresh").inc()
                continue
            self._fire(age_ns)
            # re-arm: park until the beat stream resumes (still event-driven)
            while not self._stop and self.beater.generation == g:
                self.beater.wait_stale(g, rearm_ns)

    def _loop_event(self) -> None:
        rearm_s = self.REARM_MS / 1e3
        while not self._stop:
            budget_ms = self._budget_fn()
            finite = math.isfinite(budget_ms)
            beat = self.event.wait(budget_ms / 1e3 if finite else rearm_s)
            if self._stop:
                return
            if beat:
                self.event.clear()
                _TRIPWIRE_WAITS.labels("fresh").inc()
                continue
            if not finite:
                continue
            age_ns = self._age_ns_fn()
            if age_ns / 1e6 <= budget_ms:
                _TRIPWIRE_WAITS.labels("fresh").inc()
                continue
            self._fire(age_ns)
            # re-arm: park until the beat stream resumes
            while not self._stop and not self.event.wait(rearm_s):
                pass
            self.event.clear()


class FusedStepQuorum:
    """The ICI lane: pod-wide oldest-stamp detection fused into the training
    step — one allreduce riding the step's own dispatch, so detection cost
    is a single collective independent of rank count and needs no separate
    tick thread.  The host tripwire (:class:`QuorumMonitor` /
    :class:`StampTripwire`) remains the backstop for a wedged fabric.

    ``fuse(step_fn)`` returns a jitted step that additionally reduces the
    packed per-device ages (the identical int32 pmax packing as
    :func:`make_quorum_fn` identify mode, expressed as a ``jnp.max`` over a
    mesh-sharded array so GSPMD inserts the all-reduce) and returns the
    packed pod max alongside the step outputs.  The wrapper materializes
    the PREVIOUS step's packed result each call (one-step result lag,
    bounded by step time — the collective itself ran with the step), so the
    hot path never blocks on a readback.

    Budgets must sit below :data:`AGE_CAP_MS` (~1.07 s): the packed age
    saturates there (it loses magnitude, not ordering)."""

    def __init__(
        self,
        mesh,
        axis_name: Optional[str] = None,
        budget_ms: float = 1000.0,
        on_stale: Optional[Callable[[float, int], None]] = None,
        identify: bool = True,
    ):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.axis = axis_name or mesh.axis_names[0]
        self.budget_ms = budget_ms
        if identify and math.isfinite(budget_ms) and budget_ms > AGE_CAP_MS:
            # packed ages saturate at the cap: a finite budget above it
            # could never trip — clamp so "stale beyond representable"
            # still fires (inf stays inf: lane-disabled sentinel)
            log.warning(
                "fused-quorum budget %.0fms exceeds the packed age cap; "
                "clamped to %.0fms", budget_ms, AGE_CAP_MS,
            )
            self.budget_ms = AGE_CAP_MS
        self.on_stale = on_stale
        self.identify = identify
        self.n_total = int(np.prod(mesh.devices.shape))
        self.n_local = (
            len(mesh.local_devices) if hasattr(mesh, "local_devices")
            else self.n_total
        )
        self._single_process = self.n_local == self.n_total
        self._sharding = NamedSharding(mesh, P(self.axis))
        flat = list(mesh.devices.flatten())
        local_devs = mesh.local_devices if hasattr(mesh, "local_devices") else flat
        self._local_idx = np.asarray(
            [flat.index(d) for d in local_devs], dtype=np.int32
        )
        self._jax = jax
        self._last_beat_ns = now_stamp_ns()
        self._pending = None
        self._readback = None  # lazy ResilientCollective (parallel layer)
        self.last_max_age_ms: Optional[float] = None
        self.last_stale_device: Optional[int] = None
        self.trip_count = 0

    def beat(self) -> None:
        self._last_beat_ns = now_stamp_ns()

    # -- host side ---------------------------------------------------------

    def local_ages(self) -> np.ndarray:
        ages_ns = ages_ns_from_stamps(
            now_stamp_ns(),
            np.full(self.n_local, self._last_beat_ns, dtype=np.int64),
        )
        units = age_units(ages_ns)
        if self.identify:
            return pack_age_device(units, self._local_idx)
        return units

    def device_ages(self):
        ages = self.local_ages()
        if self._single_process:
            return self._jax.device_put(ages, self._sharding)
        return self._jax.make_array_from_process_local_data(
            self._sharding, ages, (self.n_total,)
        )

    # -- fused step --------------------------------------------------------

    def fuse(self, step_fn: Callable, donate_argnums: tuple = ()) -> Callable:
        """Wrap ``step_fn`` with the fused quorum reduce.  The returned
        callable has ``step_fn``'s signature; quorum age injection, the
        one-step-lagged check, and trip firing are internal.
        ``donate_argnums`` refer to ``step_fn``'s own positions."""
        import jax.numpy as jnp

        def fused(quorum_ages, *args, **kwargs):
            out = step_fn(*args, **kwargs)
            # jnp.max over the axis-sharded ages with a replicated output:
            # GSPMD inserts the single all-reduce-max (the packed values
            # sort lexicographically by (age, device) — identify for free)
            return out, jnp.max(quorum_ages)

        jfused = self._jax.jit(
            fused, donate_argnums=tuple(i + 1 for i in donate_argnums)
        )

        def run(*args, **kwargs):
            out, packed = jfused(self.device_ages(), *args, **kwargs)
            previous, self._pending = self._pending, packed
            if previous is not None:
                # materialize LAST step's already-dispatched reduce (async
                # dispatch means this is usually a completed value) — the
                # host readback is THE blockable point of the fused lane,
                # so it rides the resilient-collective deadline lane: a
                # wedged fabric trips CollectiveTimeout (folded into the
                # staleness path below) instead of wedging the step thread
                self._materialize_check(previous)
            return out

        run.check_now = self.check_now
        run.quorum = self
        return run

    def _materialize_check(self, packed_arr) -> float:
        rc = self._readback
        if rc is None:
            # lazy: parallel.collectives imports this module (stamp/tripwire
            # machinery), so the wrapper must be built at call time
            from ..parallel.collectives import ResilientCollective
            from ..parallel.degrade import DegradePolicy

            budget = (
                max(self.budget_ms * 4.0, 50.0)
                if math.isfinite(self.budget_ms) else 0.0
            )
            rc = self._readback = ResilientCollective(
                "fused_quorum_readback",
                lambda p: int(p),
                axis=self.axis,
                deadline_ms=budget,  # 0 (budget inf) = inline fast path
                # retry/relayout cannot help a readback: the value either
                # materializes or the fabric is wedged — fail fast into the
                # staleness trip below
                policy=DegradePolicy(rungs=(), retries=0),
            )
        from ..parallel.deadline import CollectiveTimeout

        try:
            value = rc(packed_arr)
        except CollectiveTimeout:
            # the readback itself wedged: that IS the staleness signal —
            # report the saturated age (magnitude lost, ordering correct)
            self.trip_count += 1
            self.last_max_age_ms = AGE_CAP_MS
            self.last_stale_device = None
            _DETECT_NS.labels("fused").observe(int(_AGE_CAP_NS))
            if self.on_stale is not None:
                try:
                    self.on_stale(AGE_CAP_MS, None)
                except Exception:  # noqa: BLE001
                    log.exception("fused-quorum on_stale failed")
            else:
                log.error(
                    "fused quorum: readback wedged past %.0fms deadline "
                    "(axis %s)", rc.budget_ms(), self.axis,
                )
            return AGE_CAP_MS
        return self._check(value)

    def check_now(self) -> Optional[float]:
        """Materialize and check the in-flight packed result (end-of-loop
        drain; also lets tests assert synchronously).  Returns age_ms."""
        if self._pending is None:
            return None
        pending, self._pending = self._pending, None
        return self._materialize_check(pending)

    def _check(self, packed: int) -> float:
        if self.identify:
            units, dev = unpack_age_device(packed)
        else:
            units, dev = packed, None
        age_ns = units_to_ns(units)
        age_ms = age_ns / 1e6
        self.last_max_age_ms = age_ms
        self.last_stale_device = dev
        if age_ms > self.budget_ms:
            self.trip_count += 1
            _DETECT_NS.labels("fused").observe(age_ns)
            if self.on_stale is not None:
                try:
                    self.on_stale(age_ms, dev)
                except Exception:  # noqa: BLE001
                    log.exception("fused-quorum on_stale failed")
            else:
                log.error(
                    "fused quorum: pod heartbeat stale by %.3fms (device %s)",
                    age_ms, dev,
                )
        return age_ms


class QuorumMonitor:
    """Host driver for the on-device quorum tripwire.

    The workload calls :meth:`beat` every step (a host int write).  A daemon
    thread ticks the collective every ``interval`` seconds and calls
    ``on_stale(age_ms)`` when the pod-wide oldest stamp exceeds
    ``budget_ms``.  Ticks interleave with training steps on the device
    stream, so keep ``interval`` ≳ a step time.  With
    ``futex_tripwire=True`` a :class:`StampTripwire` additionally watches
    the LOCAL beat stream event-driven (futex on the native beater's gen
    word; Event fallback on the Python beater), so a local stamp freeze is
    observed at wake latency without waiting for a collective round.
    """

    def __init__(
        self,
        mesh,
        budget_ms: float = 1000.0,
        interval: float = 0.1,
        on_stale: Optional[Callable] = None,
        use_pallas: Optional[bool] = None,
        auto_beat_interval: Optional[float] = None,
        fetch_workers: int = 0,
        identify: bool = False,
        online_recalibrate_after: Optional[int] = None,
        online_min_budget_ms: float = 2.0,
        native_beat: bool = False,
        futex_tripwire: bool = False,
    ):
        self.mesh = mesh
        self.budget_ms = budget_ms
        self.interval = interval
        self.auto_beat_interval = auto_beat_interval
        # >0 enables the overlapped loop: collectives dispatch every
        # ``interval`` and results are evaluated by a fetch thread pool, so
        # detection latency is budget + interval/2 + ONE readback even when
        # the result readback RTT dwarfs the interval (tunneled transports;
        # readbacks multiplex across threads, measured on the axon relay)
        self.fetch_workers = fetch_workers
        self.identify = identify
        self._last_seq = 0
        def _default_on_stale(age):
            from ..utils.profiling import ProfilingEvent, record_event

            log.error("pod heartbeat stale by %.1fms", age)
            record_event(ProfilingEvent.HANG_DETECTED, source="quorum", age_ms=age)

        self.on_stale = on_stale or _default_on_stale
        # tripwire callbacks may accept (age_ms, stale_device_idx); plain
        # age-only callbacks keep working
        try:
            import inspect

            n_params = len([
                p for p in inspect.signature(self.on_stale).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                or p.kind == p.VAR_POSITIONAL
            ])
        except (TypeError, ValueError):
            n_params = 1
        self._on_stale_wants_device = identify and n_params >= 2
        self.use_pallas = use_pallas
        self._fn = make_quorum_fn(mesh, use_pallas=use_pallas, identify=identify)
        self._fn_async = None
        self._pending = None  # (dispatch_t, device_value) in-flight slot
        # results DISPATCHED at or before this fence never fire on_stale —
        # they observed a hang era that a restart has since resolved
        self._fence_t = float("-inf")
        self._last_beat_ns = now_stamp_ns()
        self.beat_event = threading.Event()  # event-mode tripwire feed
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tpurx-quorum", daemon=True
        )
        self._beater_stop = threading.Event()
        self._beater: Optional[threading.Thread] = None
        self.last_max_age: Optional[float] = None       # ms
        self.last_max_age_ns: Optional[int] = None
        self.last_stale_device: Optional[int] = None
        self.last_calibration_p99_ms: Optional[float] = None
        # Online recalibration: a pre-start calibrate() can only sample an
        # IDLE interpreter, and an idle-calibrated budget undershoots the
        # stamp lateness real training produces (false trips) — so after
        # ``online_recalibrate_after`` healthy ages observed by the RUNNING
        # loop (i.e. under the actual workload), the budget is recomputed
        # once from those in-vivo samples: safety*p99 + margin, floored at
        # ``online_min_budget_ms``.  Tripping ages are excluded (a real
        # hang must not inflate its own detection budget).
        self._recal_after = online_recalibrate_after
        self._recal_min_budget = online_min_budget_ms
        self._recal_ages: list = []
        self._recal_done = False
        # Native liveness beater (north-star lane): a pinned C pthread
        # stamping the slot at machine cadence — its p99 jitter is tens of
        # µs (scheduler noise, CLOCK_MONOTONIC-measured), not GIL
        # scheduling (~1 ms), so calibrated budgets can go sub-ms.  It
        # proves PROCESS/DEVICE liveness only: a GIL-wedged interpreter
        # keeps a C thread stamping, so the Python beater (GIL jitter is
        # its feature) and the pending-call watchdog ring retain GIL-wedge
        # detection.  Falls back to the Python beater when the toolchain
        # can't build the helper.
        self._native_beat = native_beat
        self._native_beater: Optional[NativeBeater] = None
        self._native_slot = None  # the beater's ctypes slot (tests poke it)
        self._futex_tripwire = futex_tripwire
        self._tripwire: Optional[StampTripwire] = None

    def beat(self) -> None:
        self._last_beat_ns = now_stamp_ns()
        self.beat_event.set()

    # -- liveness auto-beat (reference ProgressWatchdog auto-timestamps,
    # ``progress_watchdog.py:50-61``): a daemon thread stamping at
    # ``auto_beat_interval`` proves the interpreter schedules threads —
    # detects process death / GIL-holding wedges with a ms-scale budget,
    # independent of step cadence.  Manual ``beat()`` remains the
    # progress signal (budget tied to step time).
    def _beater_loop(self) -> None:
        while not self._beater_stop.is_set():
            self.beat()
            self._beater_stop.wait(self.auto_beat_interval)

    def _current_stamp(self) -> int:
        """Freshest liveness stamp (ns): manual beat() or the native slot.

        Freshness compares wrap-safe AGES, not raw stamps — both sources
        fold into the 2^63 ns epoch (the ABI-v3 C side mirrors
        ``now_stamp_ns``), and a raw max() would both break at the wrap and
        let a stale native stamp shadow a fresh manual ``beat()``.

        A source can legitimately stamp NEWER than our pre-read ``now``
        (the C thread runs concurrently; NTP skew across processes): its
        age then folds to ~2^63 and a naive compare would discard the
        freshest stamp for a stale one — on a monitor whose manual beat()
        is seconds old, that single race tick trips a spurious restart.
        Any age past the half-wrap horizon can only be a future stamp (a
        genuinely stale one would have tripped eons earlier), so clamp it
        to 0: future == fresh."""
        if self._native_slot is None:
            return self._last_beat_ns
        now = now_stamp_ns()
        a = self._last_beat_ns
        b = self._native_slot.value % _WRAP_NS
        age_a = clamp_future_ns((now - a) % _WRAP_NS)
        age_b = clamp_future_ns((now - b) % _WRAP_NS)
        return a if age_a <= age_b else b

    def _start_native_beater(self) -> bool:
        if self._native_beater is not None and self._native_beater.alive:
            return True
        if self._native_beater is None:
            self._native_beater = NativeBeater(
                interval_s=self.auto_beat_interval or 0.001
            )
        ok = self._native_beater.start()
        if ok:
            self._native_slot = self._native_beater.slot
        return ok

    def _stop_native_beater(self) -> None:
        if self._native_beater is not None:
            # freeze semantics: the slot keeps its last stamp so ages grow
            # from the freeze instant, mirroring a wedged process; the
            # jitter snapshot lands in the gauge before the thread joins
            self._native_beater.jitter_p99_us()
            self._native_beater.stop()

    def __del__(self):  # best-effort: registry already prevents UAF
        try:
            self._stop_native_beater()
        # tpurx: disable=TPURX009 -- __del__ at interpreter teardown: any raise prints unraisable-noise to stderr
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def _start_beater(self) -> None:
        if self.auto_beat_interval is None:
            return
        if self._native_beat and self._start_native_beater():
            self._start_tripwire()
            return
        if self._beater is None or not self._beater.is_alive():
            self._beater_stop.clear()  # un-latch a previous stop_auto_beat
            self._beater = threading.Thread(
                target=self._beater_loop, name="tpurx-quorum-beat", daemon=True
            )
            self._beater.start()
        self._start_tripwire()

    def _start_tripwire(self) -> None:
        if not self._futex_tripwire or self._tripwire is not None:
            return
        local_dev = None
        if self.identify:
            # local staleness: name our own first device as the culprit
            flat = list(self.mesh.devices.flatten())
            local = (
                self.mesh.local_devices if hasattr(self.mesh, "local_devices")
                else flat
            )
            local_dev = flat.index(local[0]) if local else None

        def on_local_stale(age_ms):
            self._fire(age_ms, local_dev, lane=None)  # lane recorded by tripwire

        kwargs = dict(
            on_stale=on_local_stale,
            budget_ms_fn=lambda: self.budget_ms,
            # age from the freshest of manual beat() and the native slot —
            # a manual beat between gen wakes must suppress a false trip
            age_ns_fn=lambda: clamp_future_ns(
                stamp_age_ns(now_stamp_ns(), self._current_stamp())
            ),
        )
        if self._native_beater is not None and self._native_beater.alive:
            self._tripwire = StampTripwire(beater=self._native_beater, **kwargs)
        else:
            self._tripwire = StampTripwire(event=self.beat_event, **kwargs)
        self._tripwire.start()

    def stop_auto_beat(self) -> None:
        """Stop the liveness beater (tests/benchmarks simulate a wedged
        process this way — stamps freeze while the tick loop, playing the
        healthy peers' role, keeps reducing)."""
        self._beater_stop.set()
        if self._beater is not None:
            self._beater.join(timeout=2)
        # freeze semantics: the slot keeps its last stamp so ages grow from
        # the freeze instant, mirroring a wedged process
        self._stop_native_beater()

    def resume_auto_beat(self) -> None:
        """Re-arm the liveness beater (a rank recovered by the restart ring
        is alive again; its silence must stop reading as a pod hang).
        In-flight collectives dispatched during the hang era are fenced:
        their (stale-by-construction) results must not re-trip the ring."""
        self.beat()
        self._fence_t = time.monotonic()
        self._start_beater()

    def calibrate(self, n_ticks: int = 20, safety: float = 3.0,
                  margin_ms: float = 2.0, min_budget_ms: float = 5.0,
                  load_fn: Optional[Callable] = None) -> float:
        """Derive the detection budget from OBSERVED healthy tick ages
        (beat jitter + scheduling noise) instead of a safety factor over the
        beat period alone — ages already embed every real-world delay, so the
        budget is as tight as the platform allows without false positives.
        Runs ``n_ticks`` blocking ticks, sets and returns ``budget_ms``.

        ``load_fn`` (e.g. one training-step dispatch) runs before each
        calibration tick so the sampled ages embed the GIL/scheduler
        contention of REAL training — required before trusting a tight
        ``margin_ms``: a budget calibrated on an idle interpreter undershoots
        the stamp lateness a busy one produces and then false-trips.

        The floor physics (BASELINE north-star accounting): in XLA's
        execution model a collective observes stamps only at dispatch, so
        end-to-end detection = budget + dispatch cadence + one readback.
        The budget itself cannot go below the observed p99 healthy age
        times ``safety`` without false positives — and that p99 is the beat
        interval plus the beater's stamp jitter: GIL-scheduling noise for
        the Python beater (~1 ms contended, its GIL-liveness feature), tens
        of µs for the pinned native beater.  ``min_budget_ms`` is an
        operator floor, not a physical one; set it to ~0.1 to let the
        calibration find the platform's true floor (the measured p99 is
        kept in ``last_calibration_p99_ms``)."""
        self._start_beater()
        ages = []
        for _ in range(max(3, n_ticks)):
            if load_fn is not None:
                load_fn()
            saved = self.budget_ms
            self.budget_ms = float("inf")  # no trips during calibration
            try:
                ages.append(self.tick())
            finally:
                self.budget_ms = saved
        ages_arr = np.asarray(sorted(ages), dtype=np.float64)
        p99 = float(ages_arr[min(len(ages_arr) - 1, int(0.99 * len(ages_arr)))])
        self.last_calibration_p99_ms = p99
        self.budget_ms = max(min_budget_ms, safety * p99 + margin_ms)
        return self.budget_ms

    def _observe_healthy_age(self, age: float) -> None:
        """Feed the online recalibration with an under-load healthy age."""
        if self._recal_after is None or self._recal_done or age > self.budget_ms:
            return
        self._recal_ages.append(float(age))
        if len(self._recal_ages) < self._recal_after:
            return
        ages = sorted(self._recal_ages)
        p99 = ages[min(len(ages) - 1, int(0.99 * len(ages)))]
        new_budget = max(self._recal_min_budget, 3.0 * p99 + 2.0)
        log.info(
            "quorum online recalibration: budget %.1fms -> %.1fms "
            "(p99 under load %.2fms over %d ticks)",
            self.budget_ms, new_budget, p99, len(ages),
        )
        self.last_calibration_p99_ms = p99
        self.budget_ms = new_budget
        self._recal_done = True
        self._recal_ages = []

    def _split(self, result):
        if self.identify:
            return result
        return result, None

    def _fire(self, age_ms: float, dev: Optional[int], lane: str = "collective") -> None:
        if lane is not None:
            _DETECT_NS.labels(lane).observe(int(age_ms * 1e6))
        if self._on_stale_wants_device:
            self.on_stale(age_ms, dev)
        else:
            self.on_stale(age_ms)

    def _record(self, age_ns: int, dev: Optional[int]) -> float:
        age_ms = age_ns / 1e6
        self.last_max_age = age_ms
        self.last_max_age_ns = age_ns
        self.last_stale_device = dev
        return age_ms

    def tick(self) -> float:
        """One collective; returns the pod-wide max heartbeat age (ms,
        quantized to the device quantum)."""
        n_local = (
            len(self.mesh.local_devices)
            if hasattr(self.mesh, "local_devices")
            else int(np.prod(self.mesh.devices.shape))
        )
        stamps = np.full(n_local, self._current_stamp(), dtype=np.int64)
        age_ns, dev = self._split(self._fn(stamps))
        age = self._record(age_ns, dev)
        self._observe_healthy_age(age)
        if age > self.budget_ms:
            self._fire(age, dev)
        return age

    def tick_pipelined(self) -> Optional[float]:
        """Pipelined variant: dispatch this tick's collective without blocking
        and evaluate the PREVIOUS tick's result.  Hides the device round-trip
        behind the tick interval — on a dispatch-latency-bound link the
        effective cadence doubles, at the cost of results lagging one tick
        (bounded, and far under any budget).  Returns the previous age (ms),
        or None on the first call."""
        if self._fn_async is None:
            self._fn_async = make_quorum_fn(
                self.mesh, use_pallas=self.use_pallas, blocking=False,
                identify=self.identify,
            )
        n_local = (
            len(self.mesh.local_devices)
            if hasattr(self.mesh, "local_devices")
            else int(np.prod(self.mesh.devices.shape))
        )
        stamps = np.full(n_local, self._current_stamp(), dtype=np.int64)
        pending = self._fn_async(stamps)
        previous, self._pending = self._pending, (time.monotonic(), pending)
        if previous is None:
            return None
        t_disp, value = previous
        # int() materializes the already-dispatched result
        age_ns, dev = self._split(self._fn_async.finish(int(value)))
        age = self._record(age_ns, dev)
        self._observe_healthy_age(age)
        if age > self.budget_ms and t_disp > self._fence_t:
            self._fire(age, dev)
        return age

    def warmup(self) -> None:
        """Compile + run both collective variants so the monitor loop's
        first iteration doesn't spend ~0.5s tracing while hangs go
        unobserved."""
        saved = self.budget_ms
        self.budget_ms = float("inf")
        try:
            self.tick()
            self.tick_pipelined()
            self.tick_pipelined()
            # drain the in-flight dispatch: its host-side age includes the
            # compile time above and would trip a spurious on_stale as the
            # loop's first evaluated result
            if self._pending is not None:
                int(self._pending[1])
                self._pending = None
        finally:
            self.budget_ms = saved

    def start(self) -> "QuorumMonitor":
        self.beat()
        self._start_beater()
        if self._fn_async is None:
            self.warmup()
        self.beat()
        self._thread.start()
        return self

    def _loop(self) -> None:
        if self.fetch_workers > 0:
            self._loop_overlapped()
            return
        # pipelined ticks: the device round-trip hides behind the interval,
        # so the effective detection cadence is ~interval instead of
        # interval + round-trip (documented one-tick result lag)
        while not self._stop.is_set():
            try:
                self.tick_pipelined()
            except Exception as exc:  # noqa: BLE001
                log.warning("quorum tick failed: %s", exc)
                return
            self._stop.wait(self.interval)

    def _loop_overlapped(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        if self._fn_async is None:
            self._fn_async = make_quorum_fn(
                self.mesh, use_pallas=self.use_pallas, blocking=False,
                identify=self.identify,
            )
        n_local = (
            len(self.mesh.local_devices)
            if hasattr(self.mesh, "local_devices")
            else int(np.prod(self.mesh.devices.shape))
        )
        lock = threading.Lock()
        inflight = [0]

        def evaluate(seq, t_disp, pending):
            try:
                age_ns, dev = self._split(self._fn_async.finish(int(pending)))
            except Exception as exc:  # noqa: BLE001
                log.warning("quorum fetch failed: %s", exc)
                return
            finally:
                with lock:
                    inflight[0] -= 1
            # on_stale stays serialized and at-most-once per dispatch seq
            # (monotonic), matching the single-threaded tick loop's contract
            # — restart machinery wired to it need not be re-entrant
            fire = False
            age = age_ns / 1e6
            with lock:
                if seq > self._last_seq:
                    self._last_seq = seq
                    self._record(age_ns, dev)
                    self._observe_healthy_age(age)
                    fire = age > self.budget_ms and t_disp > self._fence_t
                if fire:
                    self._fire(age, dev)

        # interval == 0 is the DENSE RE-DISPATCHED CHAIN: the next collective
        # dispatches the moment a slot frees, so the cadence term of the
        # detection floor (budget + cadence + readback) collapses from a
        # polling interval to the dispatch cost itself (~0.1-0.5 ms).  The
        # in-flight cap keeps the chain bounded; evaluation stays on the
        # fetch pool.
        seq = 0
        with ThreadPoolExecutor(
            max_workers=self.fetch_workers, thread_name_prefix="tpurx-quorum-fetch"
        ) as pool:
            while not self._stop.is_set():
                with lock:
                    free = inflight[0] < self.fetch_workers
                if free:
                    try:
                        stamps = np.full(n_local, self._current_stamp(), dtype=np.int64)
                        pending = self._fn_async(stamps)
                    except Exception as exc:  # noqa: BLE001
                        log.warning("quorum dispatch failed: %s", exc)
                        return
                    seq += 1
                    with lock:
                        inflight[0] += 1
                    pool.submit(evaluate, seq, time.monotonic(), pending)
                    if self.interval > 0:
                        self._stop.wait(self.interval)
                else:
                    # all slots busy: yield briefly instead of spinning
                    self._stop.wait(self.interval or 0.0002)

    def stop(self) -> None:
        self._stop.set()
        if self._tripwire is not None:
            self._tripwire.stop()
            self._tripwire = None
        self.stop_auto_beat()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


def quorum_reduce(mesh, stamps_ns) -> int:
    """One-shot quorum collective: max heartbeat age (ns) across the mesh
    (builds + caches the fn per mesh)."""
    key = id(mesh)
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = make_quorum_fn(mesh)
        _FN_CACHE[key] = fn
    return fn(stamps_ns)


_FN_CACHE: dict = {}
