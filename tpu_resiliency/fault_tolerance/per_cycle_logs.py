"""Per-cycle worker log capture.

Capability parity with ``fault_tolerance/per_cycle_logs.py`` (1618 LoC,
``PipeBasedLogsSpecs``): worker stdout/stderr flow through kernel pipes into
launcher-side reader threads that write rank-prefixed lines into one log file
per restart cycle.  Pipes (not files handed to the child) mean no lines are
lost or truncated when a worker is SIGKILLed mid-write, and the launcher can
tee to its own stdout.

Design here is deliberately simpler than the reference (no gRPC streaming —
the log funnel lives in ``tpu_resiliency.integrations.log_funnel`` later):
one :class:`CycleLogRouter` per launcher owning a file per cycle, one reader
thread per worker stream.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, IO, Optional, Tuple

from ..utils import env
from ..utils.logging import get_logger

log = get_logger("per_cycle_logs")


class CycleLogRouter:
    """Routes worker output pipes into per-cycle log files."""

    def __init__(
        self,
        log_dir: Optional[str],
        tee_to_stdout: bool = True,
        max_bytes_per_cycle: int = 512 << 20,
    ):
        self.log_dir = log_dir
        self.tee = tee_to_stdout
        # a worker stuck in a print loop must not fill the host disk; when a
        # cycle file hits the cap, writing stops with a truncation marker
        # (the funnel/stdout tee keeps flowing)
        self.max_bytes = max_bytes_per_cycle
        self._written = 0
        self._truncated = False
        self._cycle = 0
        self._file: Optional[IO[str]] = None
        self._file_lock = threading.Lock()
        self._readers: Dict[Tuple[int, str], threading.Thread] = {}
        self._funnel = None
        funnel = env.LOG_FUNNEL.get()
        if funnel:
            # stream worker lines into the cluster log funnel as well
            try:
                from ..utils.log_funnel import LogForwarder

                host, _, port = funnel.rpartition(":")
                fwd = LogForwarder(host, int(port))
                fwd.setFormatter(logging.Formatter("%(message)s"))
                self._funnel = fwd
            except Exception:  # noqa: BLE001 - funnel is best-effort
                log.exception("could not attach log funnel %s", funnel)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)

    def start_cycle(self, cycle: int) -> None:
        with self._file_lock:
            # forget the previous cycle's readers so join_readers() only ever
            # waits on the current cycle; a reader stuck on a leaked write-fd
            # (grandchild outliving SIGKILL) must not tax every later restart.
            # Done under the same lock the readers' identity check takes, so
            # no stale line can slip into the new cycle's file.
            stale = [k for k, r in self._readers.items() if r.is_alive()]
            if stale:
                log.warning(
                    "dropping %d still-draining reader(s) from prior cycles: %s",
                    len(stale), stale,
                )
            self._readers = {}
            if self._file:
                self._file.close()
                self._file = None
            self._cycle = cycle
            self._written = 0
            self._truncated = False
            if self.log_dir:
                path = os.path.join(self.log_dir, f"cycle_{cycle}.log")
                self._file = open(path, "a", buffering=1)

    def make_worker_pipe(self, rank: int, stream_name: str) -> int:
        """Create a pipe; returns the write fd to hand to the worker as
        stdout/stderr.  A reader thread drains the read end until EOF."""
        r_fd, w_fd = os.pipe()
        reader = threading.Thread(
            target=self._drain,
            args=(r_fd, rank, stream_name),
            name=f"tpurx-log-r{rank}-{stream_name}",
            daemon=True,
        )
        self._readers[(rank, stream_name)] = reader
        reader.start()
        return w_fd

    def _drain(self, r_fd: int, rank: int, stream_name: str) -> None:
        prefix = f"[r{rank}]"
        me = threading.current_thread()
        with os.fdopen(r_fd, "r", errors="replace") as rf:
            for line in rf:
                line = line.rstrip("\n")
                out = f"{prefix} {line}"
                with self._file_lock:
                    # checked under the lock that start_cycle holds while
                    # swapping files: a reader replaced by a new cycle (leaked
                    # write-fd in a grandchild kept its pipe open) must not
                    # write stale output into the new cycle's log — the
                    # attribution gate reads it; closing the fd EPIPEs the
                    # holdout
                    if self._readers.get((rank, stream_name)) is not me:
                        break
                    if self._file and not self._truncated:
                        self._file.write(out + "\n")
                        self._written += len(out) + 1
                        if self._written >= self.max_bytes:
                            self._file.write(
                                f"[per_cycle_logs] TRUNCATED at "
                                f"{self.max_bytes} bytes for cycle "
                                f"{self._cycle}\n"
                            )
                            self._truncated = True
                if self._funnel is not None:
                    record = logging.LogRecord(
                        "worker", logging.INFO, "", 0, out, None, None
                    )
                    self._funnel.emit(record)
                if self.tee:
                    print(out, flush=True)

    def join_readers(self, timeout: float = 2.0) -> bool:
        """Wait until every reader thread has drained its pipe to EOF.

        Called after the workers are stopped (their pipe write ends closed)
        so the per-cycle file provably contains the final output — e.g. the
        traceback the attribution gate is about to read — instead of relying
        on a fixed sleep.  Returns False if some reader is still running at
        the deadline (worker fd leaked to a grandchild that is still alive).
        """
        deadline = time.monotonic() + timeout
        for reader in list(self._readers.values()):
            reader.join(timeout=max(0.0, deadline - time.monotonic()))
        return not any(r.is_alive() for r in self._readers.values())

    def close(self) -> None:
        with self._file_lock:
            if self._file:
                self._file.close()
                self._file = None
        if self._funnel is not None:
            self._funnel.close()
            self._funnel = None
