"""Launcher-managed attribution service lifecycle.

Reference parity: ``fault_tolerance/attribution_manager.py:47-140`` — the
launcher spawns and monitors the attribution service, resolves its endpoint,
and health-checks it before the restart gate consults it.  Round-2 VERDICT
missing #2: previously the gate ran a local rule engine inline and attrsvc
had to be hand-started and hand-pointed-to.

Modes (``FaultToleranceConfig.attribution_service_mode``):

- ``"inline"`` (default): no service; the gate runs the in-process
  ``LogAnalyzer`` as before.
- ``"spawn"``: the store-hosting launcher spawns ``services.attrsvc`` on a
  free port, publishes ``attrsvc/endpoint`` in the KV store, monitors the
  child, and restarts it (bounded) when it dies.  Every node's gate
  resolves the endpoint from the store — one service per job, shared
  verdict cache and coalescing.
- ``"external"``: the operator runs attrsvc; the launcher takes
  ``attribution_service_url`` (or the store key) and only health-checks.

The gate NEVER blocks recovery on the service: an unreachable or unhealthy
endpoint falls back to the inline analyzer, exactly the reference's
defensive posture.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Optional

from ..utils.logging import get_logger

log = get_logger("attribution_manager")

ENDPOINT_KEY = "attrsvc/endpoint"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class AttributionManager:
    """Owns the attrsvc child process + endpoint resolution + health."""

    def __init__(
        self,
        mode: str = "inline",
        store=None,
        url: Optional[str] = None,
        bind_host: str = "127.0.0.1",
        advertise_host: Optional[str] = None,
        max_service_restarts: int = 3,
        health_timeout: float = 2.0,
    ):
        self.mode = mode
        self.store = store
        self.url = url
        self.bind_host = bind_host
        self.advertise_host = advertise_host or bind_host
        self.max_service_restarts = max_service_restarts
        self.health_timeout = health_timeout
        self._proc: Optional[subprocess.Popen] = None
        self._restarts = 0
        self._port: Optional[int] = None
        # spawn mode wants a live service; tick() keeps retrying (bounded)
        # even after a failed initial spawn — a lost free-port race must not
        # permanently disable the service
        self._want_service = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Spawn (mode="spawn") and/or publish the endpoint."""
        if self.mode == "spawn":
            self._want_service = True
            self._spawn()
        elif self.mode == "external" and self.url and self.store is not None:
            self.store.set(ENDPOINT_KEY, self.url)

    def _spawn(self) -> None:
        self._port = _free_port()
        env = dict(os.environ)
        env.setdefault("PYTHONPATH", os.pathsep.join(sys.path))
        self._proc = subprocess.Popen(
            [
                sys.executable, "-m", "tpu_resiliency.services.attrsvc",
                "--host", self.bind_host, "--port", str(self._port),
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        self.url = f"http://{self.advertise_host}:{self._port}"
        # wait until it serves /health, then publish the endpoint — peers
        # must never resolve an endpoint that was not yet accepting
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if self.healthy():
                if self.store is not None:
                    self.store.set(ENDPOINT_KEY, self.url)
                log.info("attribution service up at %s", self.url)
                return
            if self._proc.poll() is not None:
                break
            time.sleep(0.1)
        log.error("attribution service failed to come up at %s", self.url)
        self.stop()
        self.url = None  # never leave the gate health-checking a dead URL

    def tick(self) -> None:
        """Called from the launcher's monitor loop: (re)start a dead or
        never-started service (bounded) — a failed initial spawn retries
        here instead of latching the service off."""
        if self.mode != "spawn" or not self._want_service:
            return
        if self._proc is not None and self._proc.poll() is None:
            return
        if self._restarts >= self.max_service_restarts:
            log.error(
                "attribution service down after %d restarts; giving up "
                "(gate falls back to the inline analyzer)", self._restarts,
            )
            self._proc = None
            self._want_service = False
            return
        self._restarts += 1
        rc = self._proc.returncode if self._proc is not None else "unstarted"
        log.warning(
            "attribution service down (rc=%s) — restarting (%d/%d)",
            rc, self._restarts, self.max_service_restarts,
        )
        self._spawn()

    def stop(self) -> None:
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None

    # -- endpoint resolution + health --------------------------------------

    def resolve(self) -> Optional[str]:
        """This node's view of the service endpoint (spawn-local URL, the
        configured external URL, or the store-published one)."""
        if self.url:
            return self.url
        if self.store is not None:
            raw = self.store.try_get(ENDPOINT_KEY)
            if raw:
                return raw.decode()
        return None

    def healthy(self, url: Optional[str] = None) -> bool:
        url = url or self.resolve()
        if not url:
            return False
        try:
            with urllib.request.urlopen(
                url + "/health", timeout=self.health_timeout
            ) as resp:
                return resp.status == 200
        except (urllib.error.URLError, OSError, ValueError):
            return False

    # -- gate --------------------------------------------------------------

    def analyze_log(self, path: str, tail_bytes: int = 65536) -> Optional[dict]:
        """POST the cycle log tail to /analyze; None when the service can't
        answer (caller falls back to the inline analyzer)."""
        url = self.resolve()
        if not url or not self.healthy(url):
            return None
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                text = f.read().decode(errors="replace")
            req = urllib.request.Request(
                url + "/analyze",
                data=json.dumps({"text": text}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as exc:
            log.warning("attribution service analyze failed: %s", exc)
            return None
