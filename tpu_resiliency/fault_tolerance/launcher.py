"""Elastic per-host launcher (``torchrun``-replacement analog).

Capability parity with ``fault_tolerance/launcher.py:300-3612``
(``LocalElasticAgent`` + ``launch_agent`` + CLI): one launcher process per TPU
host that

- forks per-rank :class:`RankMonitorServer` watchdog processes *before* any
  threads exist,
- hosts (or connects to) the KV store and performs barrier rendezvous,
- spawns one worker process per local chip/slot with the rank/cycle env,
- monitors workers + peer restarts + workload-control requests in a hot loop,
- on failure: profiling events, progress-tracker gate, restart budget, new
  rendezvous round, worker stop (SIGTERM → grace → SIGKILL), respawn,
- per-cycle log capture via pipes.

TPU-native deltas from the reference: no GPU-memory-reclaim polling (HBM is
freed when the worker process dies — the stop path's waitpid is the
equivalent gate); NUMA binding via numactl when ``numa_binding`` is set.

CLI:  python -m tpu_resiliency.fault_tolerance.launcher \
        --nnodes 1:2 --nproc-per-node 4 --rdzv-endpoint 127.0.0.1:29500 \
        [--host-store] [--ft-cfg path.yaml] [--max-restarts 3] \
        script.py [script args...]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional

from ..store import StoreClient, StoreError, StoreServer
from ..utils import env
from ..utils.ipc import IpcConnector
from ..utils.logging import get_logger, setup_logger
from ..utils.profiling import ProfilingEvent, get_recorder, record_event
from .config import FaultToleranceConfig
from .data import WorkloadAction
from .per_cycle_logs import CycleLogRouter
from .progress_tracker import TrainingProgressTracker
from .rank_monitor_server import RankMonitorServer
from .rendezvous import (
    K_ACTIVE_ROUND,
    K_SHUTDOWN,
    NodeDesc,
    NodeRole,
    RendezvousClosedError,
    RendezvousHost,
    RendezvousJoiner,
    RendezvousResult,
    UnhealthyNodeError,
    is_next_round_open,
    k_restart_req,
    k_result,
    k_shutdown_ack,
    request_restart,
)

log = get_logger("launcher")


@dataclasses.dataclass
class WorkerSpec:
    cmd: List[str]
    nproc_per_node: int
    monitor_interval: float = 0.1
    extra_env: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _Worker:
    local_rank: int
    global_rank: int
    proc: subprocess.Popen


class HostRoundLoop:
    """Store-host side thread: opens/closes rounds for the whole job.

    Loop: close the currently-open round, then wait for either a restart
    request or shutdown; on restart request open the next round."""

    def __init__(self, host: RendezvousHost, round_timeout: float):
        self.host = host
        self.round_timeout = round_timeout
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpurx-rdzv-host", daemon=True
        )

    def start(self) -> None:
        self.host.bootstrap()
        self.host.open_round()
        self._thread.start()

    def _run(self) -> None:
        store = self.host.store
        while not self._stop.is_set():
            try:
                n = self.host.close_round_when_ready(timeout=self.round_timeout)
            except Exception as exc:  # noqa: BLE001
                log.error("rendezvous host failed to close round: %s", exc)
                store.set(K_SHUTDOWN, f"rendezvous failed: {exc}")
                return
            # wait for restart request or shutdown
            while not self._stop.is_set():
                if store.try_get(K_SHUTDOWN) is not None:
                    return
                if store.check([k_restart_req(n)]):
                    self.host.open_round()
                    break
                time.sleep(0.1)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class ElasticAgent:
    def __init__(
        self,
        cfg: FaultToleranceConfig,
        spec: WorkerSpec,
        store_addr: str,
        store_port: int,
        host_store: bool = False,
        node_id: Optional[str] = None,
        max_restarts: Optional[int] = None,
        slice_key: str = "",
    ):
        self.cfg = cfg
        self.spec = spec
        self.store_addr = store_addr
        self.store_port = store_port
        self.host_store = host_store
        self.max_restarts = (
            max_restarts if max_restarts is not None else cfg.max_rank_restarts
        )
        self.node_id = node_id or f"{os.uname().nodename}-{uuid.uuid4().hex[:8]}"
        self.slice_key = slice_key or cfg.node_group_key or ""
        self.remaining_restarts = self.max_restarts
        self._store_server: Optional[StoreServer] = None
        self._host_loop: Optional[HostRoundLoop] = None
        self.store: Optional[StoreClient] = None
        self.workers: List[_Worker] = []
        self.monitors: List = []  # (proc, ctrl_conn, socket_path)
        self.log_router = CycleLogRouter(cfg.per_cycle_log_dir)
        self.progress = TrainingProgressTracker(
            cfg.progress_iteration_file if cfg.enable_progress_tracking else None,
            cfg.max_no_progress_cycles,
        )
        self.cycle_info = None
        self.attr_manager = None  # built in _setup_store (needs the store)
        if host_store and cfg.cycle_info_dir:
            from .cycle_info import CycleInfoReporter

            self.cycle_info = CycleInfoReporter(cfg.cycle_info_dir)
        run_dir = f"/tmp/tpurx-{os.getpid()}"
        os.makedirs(run_dir, exist_ok=True)
        self._run_dir = run_dir
        self.ipc = IpcConnector(os.path.join(run_dir, "launcher.sock"))
        self._pending_exclude = False
        self._pending_shutdown: Optional[str] = None
        self._pending_restart: Optional[str] = None
        # restart interrupted by a store outage after workers were stopped:
        # reason + cached gate verdict (see _complete_restart)
        self._restart_in_flight: Optional[str] = None
        self._restart_in_flight_allowed: Optional[bool] = None
        self._result: Optional[RendezvousResult] = None
        self._last_store_ok = 0.0

    # -- setup -------------------------------------------------------------

    def setup_rank_monitors_early(self) -> None:
        """Fork monitor processes before any threads exist (reference
        constraint, ``launcher.py:703-759``)."""
        if self.cfg.monitor_health_check_interval > 0:
            # fail fast on a bad spec HERE — inside the monitor it could only
            # be logged, and a typo would silently disable the health loop
            from ..health import build_passive_checks

            build_passive_checks(self.cfg.monitor_health_checks)
        for lr in range(self.spec.nproc_per_node):
            sock = os.path.join(self._run_dir, f"monitor_{lr}.sock")
            # the node-scope health loop runs in exactly one monitor per host
            proc, ctrl = RankMonitorServer.run_in_subprocess(
                self.cfg, sock, host_health_loop=(lr == 0)
            )
            self.monitors.append((proc, ctrl, sock))

    def _setup_store(self) -> None:
        if self.host_store:
            if env.NATIVE_STORE.get():
                from ..store.native import NativeStoreServer

                self._store_server = NativeStoreServer(
                    host="0.0.0.0", port=self.store_port
                ).start()
                log.info("hosting native C++ store on port %s", self._store_server.port)
            else:
                self._store_server = StoreServer(
                    host="0.0.0.0", port=self.store_port
                ).start_in_thread()
            self.store_port = self._store_server.port
        self.store = StoreClient(
            self.store_addr, self.store_port, timeout=self.cfg.rdzv_round_timeout
        )
        if self.host_store:
            host = RendezvousHost(
                self.store.clone(),
                min_nodes=self.cfg.min_nodes,
                max_nodes=self.cfg.max_nodes,
                require_equal_slots=self.cfg.require_equal_slots,
            )
            self._host_loop = HostRoundLoop(host, self.cfg.rdzv_round_timeout)
            self._host_loop.start()
        # attribution service lifecycle (reference attribution_manager.py):
        # the store-hosting launcher spawns ONE attrsvc per job and
        # publishes its endpoint; every node resolves it from the store
        from .attribution_manager import AttributionManager

        mode = self.cfg.attribution_service_mode
        if mode == "spawn" and not self.host_store:
            mode = "inline"  # only the host node spawns; others resolve
        self.attr_manager = AttributionManager(
            mode=mode,
            store=self.store,
            url=self.cfg.attribution_service_url,
        )
        self.attr_manager.start()

    def _on_ipc(self, msg: Dict) -> None:
        if msg.get("kind") != "workload_control":
            return
        action = WorkloadAction(msg["action"])
        log.warning("workload control request: %s (%s)", action.value, msg.get("reason"))
        if action == WorkloadAction.ExcludeThisNode:
            self._pending_exclude = True
        elif action == WorkloadAction.ShutdownWorkload:
            self._pending_shutdown = msg.get("reason", "workload requested shutdown")
        elif action == WorkloadAction.RestartWorkload:
            self._pending_restart = msg.get("reason", "workload requested restart")

    # -- worker lifecycle --------------------------------------------------

    def _start_workers(self, result: RendezvousResult) -> None:
        cycle = result.cycle
        if cycle > 0:
            # hard-killed workers may have leaked staged-checkpoint shm;
            # reclaim before the respawn needs the space
            from ..utils.shm_janitor import sweep as shm_sweep

            try:
                shm_sweep(min_age_s=60.0)
            except Exception:  # noqa: BLE001 - never block a restart on cleanup
                log.exception("shm sweep failed")
        self.log_router.start_cycle(cycle)
        for _, ctrl, _ in self.monitors:
            ctrl.send({"cmd": "cycle", "cycle": cycle})
        record_event(ProfilingEvent.WORKER_START_REQUESTED, cycle=cycle)
        self.workers = []
        for lr in range(self.spec.nproc_per_node):
            grank = result.rank_offset + lr
            env = dict(os.environ)
            env.update(self.spec.extra_env)
            env.update(
                {
                    "TPURX_RANK": str(grank),
                    "TPURX_LOCAL_RANK": str(lr),
                    "TPURX_WORLD_SIZE": str(result.global_world_size),
                    "TPURX_GROUP_RANK": str(result.group_rank),
                    "TPURX_NNODES": str(result.group_world_size),
                    "TPURX_CYCLE": str(cycle),
                    "TPURX_STORE_ADDR": self.store_addr,
                    "TPURX_STORE_PORT": str(self.store_port),
                    "TPURX_RANK_MONITOR_SOCKET": self.monitors[lr][2],
                    "TPURX_LAUNCHER_IPC_SOCKET": self.ipc.socket_path,
                }
            )
            out_fd = self.log_router.make_worker_pipe(grank, "out")
            err_fd = self.log_router.make_worker_pipe(grank, "err")
            proc = subprocess.Popen(
                self._numa_wrap(self.spec.cmd, lr),
                env=env,
                stdout=out_fd,
                stderr=err_fd,
                start_new_session=True,  # own PGID so we can signal the tree
            )
            os.close(out_fd)
            os.close(err_fd)
            self.workers.append(_Worker(lr, grank, proc))
        record_event(ProfilingEvent.WORKER_STARTED, cycle=cycle)
        if self.cycle_info is not None:
            self.cycle_info.start_cycle(
                cycle, result.round_num, result.participants, [],
                result.global_world_size,
            )
        log.info(
            "cycle %s: started %s workers (global ranks %s..%s)",
            cycle, len(self.workers), result.rank_offset,
            result.rank_offset + self.spec.nproc_per_node - 1,
        )

    def _numa_wrap(self, cmd: List[str], local_rank: int) -> List[str]:
        """NUMA binding (reference ``launcher.py:239-291``): TPU hosts are
        NUMA machines; binding each worker's CPU+memory to the node nearest
        its chips avoids cross-socket HBM staging traffic.  Uses numactl when
        present; silently a no-op otherwise."""
        if not self.cfg.numa_binding:
            return cmd
        import shutil as _shutil

        numactl = _shutil.which("numactl")
        nodes = self._numa_node_count()
        if not numactl or nodes <= 1:
            return cmd
        node = local_rank * nodes // max(1, self.spec.nproc_per_node)
        return [numactl, f"--cpunodebind={node}", f"--membind={node}"] + cmd

    @staticmethod
    def _numa_node_count() -> int:
        try:
            return len([
                d for d in os.listdir("/sys/devices/system/node")
                if d.startswith("node") and d[4:].isdigit()
            ])
        except OSError:
            return 1

    def _stop_workers(self) -> None:
        if not self.workers:
            return
        record_event(ProfilingEvent.WORKER_STOP_REQUESTED)
        stop_sig = getattr(
            signal, self.cfg.worker_stop_signal, signal.SIGTERM
        )
        for w in self.workers:
            if w.proc.poll() is None:
                try:
                    os.killpg(w.proc.pid, stop_sig)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + self.cfg.workers_stop_timeout
        for w in self.workers:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                pass
        for w in self.workers:
            # Always sweep the process group: a dead leader can leave live
            # children (data loaders, probes) that would hold devices/ports.
            try:
                os.killpg(w.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            if w.proc.poll() is None:
                w.proc.wait()  # tpurx: disable=TPURX005 -- process group was just SIGKILLed; exit is kernel-guaranteed
        record_event(ProfilingEvent.WORKER_STOPPED)
        self.workers = []

    def _workers_status(self) -> str:
        """'running' | 'succeeded' | 'failed' | 'none'

        'none' = no workers exist (already stopped for an in-flight restart
        or not yet started) — callers must not read an empty list as
        success (``all()`` over ``[]`` is True) or as failure.

        ``restart_policy="min-healthy"`` tolerates worker exits as long as
        at least ``min_healthy_workers`` local workers remain healthy
        (running or exited 0) — for jobs with non-collective sidecar
        workers whose loss should not burn a restart cycle."""
        codes = [w.proc.poll() for w in self.workers]
        if not codes:
            return "none"
        failed = sum(1 for c in codes if c is not None and c != 0)
        if self.cfg.restart_policy == "min-healthy" and self.cfg.min_healthy_workers >= 0:
            healthy = len(codes) - failed
            if healthy < self.cfg.min_healthy_workers:
                return "failed"
            if all(c is not None for c in codes):
                return "succeeded"  # enough zero-exits; losses tolerated
            return "running"
        if failed:
            return "failed"
        if all(c == 0 for c in codes):
            return "succeeded"
        return "running"

    # -- main loop ---------------------------------------------------------

    def run(self) -> int:
        self._setup_store()
        self.ipc.start_receiving(self._on_ipc)
        joiner = RendezvousJoiner(
            self.store.clone(),
            NodeDesc(
                node_id=self.node_id,
                hostname=os.uname().nodename,
                slots=self.spec.nproc_per_node,
                slice_key=self.slice_key,
            ),
            pre_join_health_check=self._pre_join_health_check,
        )
        try:
            return self._run_loop(joiner)
        finally:
            self._stop_workers()
            self._teardown()

    def _pre_join_health_check(self) -> None:
        # Device health gate before joining a round (reference pre_join_hook).
        # Full TPU checks live in tpu_resiliency.health; the launcher-level
        # gate is injectable for tests via env.
        from .health_gate import pre_rendezvous_health_check
        from .rendezvous import K_CYCLE

        cycle = int(self.store.try_get(K_CYCLE) or b"1") - 1
        pre_rendezvous_health_check(self.cfg, self.node_id, current_cycle=cycle)

    def _run_loop(self, joiner: RendezvousJoiner) -> int:
        store_down_since: Optional[float] = None
        while True:
            try:
                result = joiner.join(timeout=self.cfg.rdzv_round_timeout)
                store_down_since = None
            except RendezvousClosedError as exc:
                log.info("rendezvous closed: %s", exc)
                self._ack_shutdown()
                return 0 if "success" in str(exc) else 1
            except UnhealthyNodeError as exc:
                log.error("node unhealthy, leaving the job: %s", exc)
                self._ack_shutdown()
                return 1
            except StoreError as exc:
                # Store host unreachable.  Either the job finished without us
                # (host tore the store down) or the control plane is
                # restarting and --journal will re-host the state.  Keep the
                # fleet: retry joining for a bounded rejoin window before
                # concluding the job is gone.
                now = time.monotonic()
                if store_down_since is None:
                    store_down_since = now
                waited = now - store_down_since
                if waited < self.cfg.store_rejoin_window:
                    log.warning(
                        "store unreachable during rendezvous (%.0fs/%.0fs "
                        "rejoin window): %s",
                        waited, self.cfg.store_rejoin_window, exc,
                    )
                    time.sleep(min(5.0, max(1.0, waited / 4)))
                    continue
                log.warning(
                    "store unreachable past the %.0fs rejoin window, giving "
                    "up: %s", self.cfg.store_rejoin_window, exc,
                )
                return 1
            if result.role != NodeRole.PARTICIPANT:
                continue
            self._result = result
            self._start_workers(result)
            outcome = self._monitor_until_event(result)
            if outcome == "succeeded":
                log.info("workers finished successfully")
                try:
                    self.store.set(K_SHUTDOWN, "success")
                except StoreError:
                    pass  # store host already gone — job is over either way
                self._ack_shutdown()
                return 0
            if outcome == "shutdown":
                self._ack_shutdown()
                return 1
            if outcome == "excluded":
                joiner.desc.excluded = True
                self._stop_workers()
                request_restart(self.store, "node excluded")
                # rejoin so the host can reassign without us; join() raises
                # RendezvousClosedError for excluded nodes
                continue
            # outcome == restart (local failure or peer restart)
            self._stop_workers()
            continue

    def _monitor_until_event(self, result: RendezvousResult) -> str:
        """Hot loop (reference ``launcher.py:629-697``). Returns outcome."""
        store_down_since: Optional[float] = None
        # tpurx: disable=TPURX007 -- outage ride-out, not a retry: the window resets when the store recovers and the verdict depends on live worker status
        while True:
            try:
                outcome = self._monitor_tick(result)
                return outcome
            except StoreError as exc:
                # Store host unreachable mid-training.  If our workers are
                # done, the job most likely succeeded and the host tore down
                # first.  Otherwise ride out a control-plane restart
                # (--journal re-hosts the state): workers keep training on
                # ICI and don't need the store until the next event, so keep
                # them alive for the rejoin window before giving up.
                status = self._workers_status()
                if status == "succeeded":
                    return "succeeded"
                now = time.monotonic()
                if store_down_since is None or self._last_store_ok > store_down_since:
                    store_down_since = now  # fresh outage, fresh window
                waited = now - store_down_since
                if waited < self.cfg.store_rejoin_window:
                    log.warning(
                        "store unreachable in monitor loop (workers: %s; "
                        "%.0fs/%.0fs rejoin window): %s",
                        status, waited, self.cfg.store_rejoin_window, exc,
                    )
                    time.sleep(min(5.0, max(1.0, waited / 4)))
                    continue
                log.warning(
                    "store unreachable past the %.0fs rejoin window "
                    "(workers: %s) — shutting down: %s",
                    self.cfg.store_rejoin_window, status, exc,
                )
                self._stop_workers()
                return "shutdown"

    def _poll_monitor_events(self) -> None:
        """Drain health events the rank-monitor watchdogs push over their
        control pipes.  Polled at the top of every monitor tick so a node
        health failure turns into exclusion BEFORE a possibly-coincident
        worker failure turns into a plain restart (restarting on a sick node
        just fails again)."""
        for _, ctrl, _ in self.monitors:
            try:
                while ctrl.poll(0):
                    evt = ctrl.recv()
                    if not isinstance(evt, dict):
                        continue
                    if evt.get("event") == "health_failure":
                        log.error(
                            "monitor reported node health failure (%s): %s — "
                            "excluding this node",
                            evt.get("check"), evt.get("message"),
                        )
                        record_event(
                            ProfilingEvent.NODE_EXCLUDE_REQUESTED,
                            node=self.node_id,
                            check=evt.get("check"),
                            message=evt.get("message"),
                        )
                        self._pending_exclude = True
            except (EOFError, OSError):
                continue

    def _monitor_tick(self, result: RendezvousResult) -> str:
        while True:
            time.sleep(self.spec.monitor_interval)
            self._poll_monitor_events()
            if self.attr_manager is not None:
                self.attr_manager.tick()  # respawn a dead attrsvc (bounded)
            if self._pending_shutdown:
                log.warning("shutting down workload: %s", self._pending_shutdown)
                self.store.set(K_SHUTDOWN, self._pending_shutdown)
                self._stop_workers()
                return "shutdown"
            if self._pending_exclude:
                self._pending_exclude = False
                return "excluded"
            if self._restart_in_flight is not None:
                # A store outage interrupted a restart AFTER the workers were
                # already stopped and the cycle accounted: resume it instead
                # of letting the dead workers reclassify as a fresh failure
                # (which would charge end_cycle and the restart budget a
                # second time for the same fault).
                return self._complete_restart()
            if self._pending_restart:
                # Quorum tripwire (or other in-workload detector) named a
                # hang: restart the cycle NOW instead of waiting for the
                # rank-heartbeat timeout ring to kill the hung worker.
                reason = self._pending_restart
                self._pending_restart = None
                log.error("in-workload restart request: %s", reason)
                record_event(
                    ProfilingEvent.FAILURE_DETECTED,
                    cycle=result.cycle, reason=reason, source="workload_control",
                )
                if self.cycle_info is not None:
                    self.cycle_info.end_cycle("workload_restart_request", [])
                self._stop_workers()
                if not self.log_router.join_readers(timeout=2.0):
                    log.warning("per-cycle log readers still draining at deadline")
                self._restart_in_flight = reason
                return self._complete_restart()
            shutdown = self.store.try_get(K_SHUTDOWN)
            self._last_store_ok = time.monotonic()
            if shutdown == b"success":
                # Peers finished; let local workers drain instead of killing
                # them mid-final-step, then report success.
                deadline = time.monotonic() + self.cfg.workers_stop_timeout
                for w in self.workers:
                    try:
                        w.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        break
                self._stop_workers()
                return "succeeded"
            if shutdown is not None:
                log.info("shutdown flag observed: %s", shutdown.decode())
                self._stop_workers()
                return "shutdown"
            status = self._workers_status()
            if status == "succeeded":
                return "succeeded"
            if status == "failed":
                failed = [
                    (w.global_rank, w.proc.poll())
                    for w in self.workers
                    if w.proc.poll() not in (None, 0)
                ]
                log.error("worker failure detected: ranks %s", failed)
                record_event(
                    ProfilingEvent.FAILURE_DETECTED,
                    cycle=result.cycle,
                    failed=[[r, c] for r, c in failed],
                )
                if self.cycle_info is not None:
                    self.cycle_info.end_cycle(
                        "worker_failure", [r for r, _ in failed]
                    )
                # Stop workers FIRST so the per-cycle pipe readers drain the
                # dying ranks' final output (tracebacks) before the
                # attribution gate reads the cycle log.
                self._stop_workers()
                if not self.log_router.join_readers(timeout=2.0):
                    log.warning("per-cycle log readers still draining at deadline")
                self._restart_in_flight = f"worker failure on {self.node_id}"
                return self._complete_restart()
            if is_next_round_open(self.store, result.round_num):
                log.info("peer-initiated restart: new round open")
                return "restart"

    def _complete_restart(self) -> str:
        """Finish an in-flight restart (workers already stopped, cycle
        already accounted).  Idempotent across StoreError retries: the gate
        verdict is computed once and cached so a store outage between the
        gate and ``request_restart`` can't charge the restart budget twice."""
        if self._restart_in_flight_allowed is None:
            self._restart_in_flight_allowed = self._restart_allowed()
        if not self._restart_in_flight_allowed:
            self.store.set(K_SHUTDOWN, "restart budget exhausted")
            self._restart_in_flight = None
            self._restart_in_flight_allowed = None
            return "shutdown"
        request_restart(self.store, self._restart_in_flight)
        self._restart_in_flight = None
        self._restart_in_flight_allowed = None
        return "restart"

    def _restart_allowed(self) -> bool:
        self.progress.analyze_previous_cycle()
        if self.progress.should_terminate_early():
            log.error(
                "terminating early: no progress for %s cycles",
                self.progress.no_progress_cycles,
            )
            return False
        if not self._attribution_gate_allows():
            return False
        if self.max_restarts > 0:
            if self.remaining_restarts <= 0:
                log.error("restart budget exhausted (%s)", self.max_restarts)
                return False
            self.remaining_restarts -= 1
        return True

    def _attribution_gate_allows(self) -> bool:
        """Consult the log analyzer before burning a restart on a failure
        that cannot succeed (OOM, NaN, bad data) — reference
        ``attribution_manager.py`` gate."""
        if not self.cfg.enable_attribution_gate or not self.cfg.per_cycle_log_dir:
            return True
        cycle = self._result.cycle if self._result else 0
        path = os.path.join(self.cfg.per_cycle_log_dir, f"cycle_{cycle}.log")
        if not os.path.exists(path):
            return True
        category, should_resume, confidence, summary = None, True, 0.0, ""
        # managed service first (shared cache + coalescing + LLM backend);
        # unhealthy/unreachable falls back to the inline analyzer — the
        # gate must never block recovery on the service
        svc = None
        if self.attr_manager is not None:
            svc = self.attr_manager.analyze_log(path)
        if svc is not None:
            category = svc.get("category")
            should_resume = bool(svc.get("should_resume", True))
            confidence = float(svc.get("confidence", 0.0))
            summary = svc.get("summary", "")
        else:
            try:
                from ..attribution import LogAnalyzer

                verdict = LogAnalyzer().analyze_file(path)
            except Exception:  # noqa: BLE001 - never block recovery
                log.exception("attribution gate failed; allowing restart")
                return True
            category = (
                verdict.category.value
                if hasattr(verdict.category, "value") else verdict.category
            )
            should_resume = verdict.should_resume
            confidence = verdict.confidence
            summary = verdict.summary
        log.info(
            "attribution%s: category=%s resume=%s confidence=%.2f (%s)",
            " (service)" if svc is not None else "", category,
            should_resume, confidence, summary,
        )
        if not should_resume and confidence >= 0.8:
            log.error(
                "attribution gate: %s is not survivable by restart — stopping",
                category,
            )
            return False
        return True

    def _ack_shutdown(self) -> None:
        """Record that this node has observed the shutdown flag (best-effort —
        the store host may already be gone).  Only acks when the flag actually
        exists: an excluded node exiting on a closed rendezvous must not leave
        a premature ack that would later satisfy the host's wait spuriously."""
        try:
            if self.store.try_get(K_SHUTDOWN) is not None:
                self.store.set(k_shutdown_ack(self.node_id), "1")
        except (StoreError, OSError):
            pass

    def _await_shutdown_acks(self, timeout: float = 3.0) -> None:
        """Store-hosting agent: wait until every participant of the latest
        closed round has acked the shutdown flag (or the deadline passes)
        before the store disappears.  Replaces the old fixed grace sleep — a
        loaded host no longer races its peers' final ``try_get(K_SHUTDOWN)``.

        Runs on a dedicated short-timeout connection: this is reachable from
        the SIGTERM handler, where reusing ``self.store`` could re-enter its
        lock mid-frame of an interrupted request and desync the wire protocol.
        """
        try:
            store = StoreClient(
                self.store.host, self.store.port, timeout=2.0, connect_timeout=2.0
            )
        except (StoreError, OSError):
            return
        try:
            if store.try_get(K_SHUTDOWN) is None:
                # tearing down without a published flag (SIGTERM on the host,
                # unhealthy exit): publish one so peers can observe and ack
                # instead of stalling the full deadline for acks that can
                # never arrive
                store.set(K_SHUTDOWN, "host terminated")
            peers = [
                n for n in self._latest_participants(store) if n != self.node_id
            ]
            keys = [k_shutdown_ack(n) for n in peers]
            deadline = time.monotonic() + timeout
            while peers and time.monotonic() < deadline:
                if store.check(keys):
                    break
                time.sleep(0.05)
            else:
                if peers:
                    log.warning(
                        "peers did not all ack shutdown within %.1fs: %s",
                        timeout, peers,
                    )
            # Standby spares and mid-join nodes are not in the ack set; they
            # poll the store on a ~0.25 s cadence.  Hold the store one poll
            # interval past the participant acks so they observe the flag and
            # exit cleanly instead of hitting a dead store.
            time.sleep(0.5)
        except (StoreError, OSError):
            return
        finally:
            store.close()

    def _latest_participants(self, store) -> List[str]:
        """Participants of the latest closed rendezvous round, read from the
        store — ``self._result`` can be stale (e.g. this host was excluded
        after its last participant round while the fleet moved on)."""
        try:
            raw_n = store.try_get(K_ACTIVE_ROUND)
            if raw_n is not None:
                for rnd in (int(raw_n), int(raw_n) - 1):
                    if rnd < 0:
                        continue
                    raw = store.try_get(k_result(rnd))
                    if raw:
                        return list(json.loads(raw)["participants"])
        except (StoreError, OSError, ValueError, KeyError):
            pass
        return list(self._result.participants) if self._result else []

    def _teardown(self) -> None:
        self.ipc.stop_receiving()
        if self.attr_manager is not None:
            self.attr_manager.stop()
        for proc, ctrl, _ in self.monitors:
            try:
                ctrl.send({"cmd": "shutdown"})
            except (BrokenPipeError, OSError):
                pass
        for proc, _, _ in self.monitors:
            proc.join(timeout=3)
            if proc.is_alive():
                proc.terminate()
        if self._host_loop:
            self._host_loop.stop()
        self.log_router.close()
        if self._store_server:
            # peers must observe the shutdown flag before the store disappears
            # (they tolerate store loss after that); wait for their explicit
            # acks rather than sleeping a fixed grace period
            self._await_shutdown_acks(timeout=3.0)
            self._store_server.stop()


# -- CLI ---------------------------------------------------------------------

def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="tpurx-launch", description="TPU-resilient elastic launcher"
    )
    p.add_argument("--nnodes", default="1:1", help="MIN:MAX nodes (or a single N)")
    p.add_argument("--nproc-per-node", type=int, default=1)
    p.add_argument("--rdzv-endpoint", default="127.0.0.1:29400")
    p.add_argument(
        "--host-store",
        action="store_true",
        help="host the KV store + rendezvous rounds in this launcher",
    )
    p.add_argument("--node-id", default=None)
    p.add_argument("--slice-key", default="", help="TPU slice / ICI domain id")
    p.add_argument("--max-restarts", type=int, default=None)
    p.add_argument("--ft-cfg", default=None, help="YAML config path")
    p.add_argument(
        "--ft-param", action="append", default=[], metavar="KEY=VALUE",
        help="FaultToleranceConfig override (repeatable), e.g. "
             "--ft-param rank_heartbeat_timeout=30 --ft-param max_nodes=8",
    )
    p.add_argument("--monitor-interval", type=float, default=0.1)
    p.add_argument("--log-dir", default=None)
    # operator surface (each also reachable via --ft-param; these are the
    # high-traffic knobs the reference exposes as dedicated flags)
    p.add_argument(
        "--worker-stop-signal", default=None, metavar="SIG",
        help="graceful signal before the KILL sweep (default SIGTERM)",
    )
    p.add_argument(
        "--term-signal", default=None, metavar="SIG",
        help="signal the rank monitor uses to kill a hung rank (default SIGKILL)",
    )
    p.add_argument(
        "--workers-stop-timeout", type=float, default=None,
        help="seconds to wait after the stop signal before SIGKILL",
    )
    p.add_argument(
        "--restart-policy", choices=["any-failed", "min-healthy"], default=None,
        help="when a worker exit fails the cycle (default any-failed)",
    )
    p.add_argument(
        "--min-healthy-workers", type=int, default=None,
        help="min-healthy policy: local workers that must stay healthy",
    )
    p.add_argument(
        "--allow-heterogeneous", action="store_true",
        help="accept nodes with differing worker counts (mixed slot fleets)",
    )
    p.add_argument("cmd", nargs=argparse.REMAINDER, help="worker command")
    args = p.parse_args(argv)
    if not args.cmd:
        p.error("worker command required")
    if args.cmd and args.cmd[0] == "--":
        args.cmd = args.cmd[1:]
    return args


def build_agent(args: argparse.Namespace) -> ElasticAgent:
    cfg = (
        FaultToleranceConfig.from_yaml(args.ft_cfg)
        if args.ft_cfg
        else FaultToleranceConfig()
    )
    if args.ft_param:
        from .config import _coerce
        import dataclasses as _dc

        types = {f.name: f.type for f in _dc.fields(FaultToleranceConfig)}
        overrides = {}
        for item in args.ft_param:
            key, sep, value = item.partition("=")
            if not sep or key not in types:
                raise SystemExit(f"bad --ft-param {item!r} (unknown key or missing '=')")
            overrides[key] = _coerce(value, types[key])
        cfg = cfg.merged_with(overrides, allow_none=True)
    cfg = cfg.merged_with_env()
    if ":" in args.nnodes:
        mn, mx = args.nnodes.split(":")
        cfg = cfg.merged_with({"min_nodes": int(mn), "max_nodes": int(mx)})
    else:
        n = int(args.nnodes)
        cfg = cfg.merged_with({"min_nodes": n, "max_nodes": n})
    if args.log_dir:
        cfg = cfg.merged_with({"per_cycle_log_dir": args.log_dir})
    flag_overrides = {}
    if args.worker_stop_signal:
        if not hasattr(signal, args.worker_stop_signal):
            raise SystemExit(f"unknown signal {args.worker_stop_signal!r}")
        flag_overrides["worker_stop_signal"] = args.worker_stop_signal
    if args.term_signal:
        if not hasattr(signal, args.term_signal):
            raise SystemExit(f"unknown signal {args.term_signal!r}")
        flag_overrides["term_signal"] = args.term_signal
    if args.workers_stop_timeout is not None:
        flag_overrides["workers_stop_timeout"] = args.workers_stop_timeout
    if args.restart_policy is not None:
        flag_overrides["restart_policy"] = args.restart_policy
    if args.min_healthy_workers is not None:
        flag_overrides["min_healthy_workers"] = args.min_healthy_workers
    if args.allow_heterogeneous:
        flag_overrides["require_equal_slots"] = False
    if flag_overrides:
        cfg = cfg.merged_with(flag_overrides)
    host, port = args.rdzv_endpoint.rsplit(":", 1)
    cmd = args.cmd
    if cmd[0].endswith(".py"):
        cmd = [sys.executable] + cmd
    spec = WorkerSpec(
        cmd=cmd,
        nproc_per_node=args.nproc_per_node,
        monitor_interval=args.monitor_interval,
    )
    return ElasticAgent(
        cfg,
        spec,
        store_addr=host,
        store_port=int(port),
        host_store=args.host_store,
        node_id=args.node_id,
        max_restarts=args.max_restarts,
        slice_key=args.slice_key,
    )


def main(argv: Optional[List[str]] = None) -> None:
    setup_logger()
    args = parse_args(argv)
    agent = build_agent(args)
    if agent.cfg.profiling_file:
        get_recorder()._path = agent.cfg.profiling_file
    agent.setup_rank_monitors_early()

    # SIGTERM/SIGINT must sweep the worker process groups before the launcher
    # dies — orphaned workers would keep holding TPU chips and ports
    # (reference stops worker groups on agent shutdown, ``launcher.py:922``).
    def _terminate(signum, frame):
        log.warning("launcher received %s; stopping workers", signal.Signals(signum).name)
        try:
            agent._stop_workers()
            agent._teardown()
        finally:
            os._exit(128 + signum)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)

    rc = agent.run()
    sys.exit(rc)


if __name__ == "__main__":
    main()
