"""Per-rank watchdog process: heartbeat + section hang detection.

Capability parity with ``fault_tolerance/rank_monitor_server.py:122-704``
(``RankMonitorServer``): a separate OS process per worker rank (forked by the
launcher *before* any threads exist), hosting an asyncio unix-socket server
the rank's :class:`RankMonitorClient` connects to.  It tracks heartbeats and
open timed sections and, on timeout, kills the rank (SIGCONT first in case it
is stopped, then the configured signal) so the launcher's monitor loop sees a
failed worker and triggers the restart cycle.

TPU-native notes: the watchdog is pure host-side (it must survive XLA/device
hangs, so it never touches JAX).  The fast on-device quorum detection in
``tpu_resiliency.ops.quorum`` complements — not replaces — this process: the
kernel gives sub-ms detection *inside* healthy steps, this process is the
source of truth when the device or the Python loop is gone.

Control: the launcher communicates over a ``multiprocessing.Pipe`` (cycle
updates, shutdown) instead of a second unix socket — same capability, simpler
ownership.
"""

from __future__ import annotations

import asyncio
import dataclasses
import multiprocessing as mp
import os
import signal
import time
from typing import Any, Callable, Dict, Optional

from ..telemetry import counter, histogram
from ..utils.ipc import _U32
from ..utils.logging import get_logger, setup_logger
from ..utils.profiling import ProfilingEvent, record_event
from .config import FaultToleranceConfig
from .data import (
    HeartbeatTimeouts,
    MsgType,
    SectionTimeouts,
    heartbeat_timeouts_from_dict,
    heartbeat_timeouts_to_dict,
    section_timeouts_from_dict,
    section_timeouts_to_dict,
)

import json

log = get_logger("rank_monitor")

_HB_RECEIVED = counter(
    "tpurx_heartbeat_received_total", "Heartbeats received by the rank monitor"
)
_HB_GAP_NS = histogram(
    "tpurx_heartbeat_gap_ns",
    "Observed gap between consecutive heartbeats of the monitored rank",
)
_HANGS = counter(
    "tpurx_hang_detected_total",
    "Hangs the rank monitor terminated a worker for",
    labels=("kind",),
)


@dataclasses.dataclass
class _RankState:
    pid: Optional[int] = None
    rank: Optional[int] = None
    connected_at: Optional[float] = None
    last_hb: Optional[float] = None
    open_sections: Dict[str, float] = dataclasses.field(default_factory=dict)
    last_section_activity: Optional[float] = None
    seen_section_msgs: bool = False
    # id of the connection that INITed this state: a lingering old worker's
    # late EOF must not clobber the state of the new cycle's worker
    owner_conn: Optional[int] = None
    # straggler op-ring shm name: readable post-mortem while the rank hangs
    op_ring_shm: Optional[str] = None

    def reset(self) -> None:
        self.pid = None
        self.rank = None
        self.connected_at = None
        self.last_hb = None
        self.open_sections.clear()
        self.last_section_activity = None
        self.seen_section_msgs = False
        self.owner_conn = None
        self.op_ring_shm = None


class RankMonitorServer:
    def __init__(
        self,
        cfg: FaultToleranceConfig,
        socket_path: str,
        ctrl_conn=None,
        kill_fn: Optional[Callable[[int, str], None]] = None,
        host_health_loop: bool = True,
    ):
        self.cfg = cfg
        self.socket_path = socket_path
        self.ctrl_conn = ctrl_conn
        # the health loop is NODE-scope: on multi-worker hosts only one of
        # the per-rank monitors should run it (duplicated dmesg/daemon/sysfs
        # sweeps and duplicate failure events otherwise)
        self.host_health_loop = host_health_loop
        self._kill_fn = kill_fn or self._default_kill
        self.hb_timeouts = HeartbeatTimeouts(
            initial=cfg.initial_rank_heartbeat_timeout,
            subsequent=cfg.rank_heartbeat_timeout,
        )
        self.section_timeouts = SectionTimeouts(
            section=dict(cfg.rank_section_timeouts),
            out_of_section=cfg.rank_out_of_section_timeout,
        )
        self.state = _RankState()
        self.cycle = 0
        self._hang_detected = False
        self._server: Optional[asyncio.AbstractServer] = None

    # -- kill action -------------------------------------------------------

    @staticmethod
    def _default_kill(pid: int, sig_name: str) -> None:
        """Kill the whole worker process group (the launcher starts workers as
        session leaders), falling back to the single pid — a hung worker's
        children (data loaders, probes) must not survive into the next cycle."""
        sig = getattr(signal, sig_name, signal.SIGKILL)
        for send in (os.killpg, os.kill):
            try:
                send(pid, signal.SIGCONT)
                send(pid, sig)
                return
            except (ProcessLookupError, PermissionError, OSError):
                continue

    def _shutdown_rank(self, reason: str) -> None:
        pid = self.state.pid
        _HANGS.labels("section" if "section" in reason else "heartbeat").inc()
        log.error(
            "hang detected (cycle=%s rank=%s pid=%s): %s — terminating rank",
            self.cycle, self.state.rank, pid, reason,
        )
        post_mortem_ops = self._read_op_rings_post_mortem()
        record_event(
            ProfilingEvent.HANG_DETECTED,
            rank=self.state.rank, reason=reason, cycle=self.cycle,
            **({"post_mortem_ops": post_mortem_ops} if post_mortem_ops else {}),
        )
        self._hang_detected = True
        if pid:
            self._kill_fn(pid, self.cfg.term_signal)
        self.state.reset()

    def _read_op_rings_post_mortem(self) -> Optional[list]:
        """BEFORE killing a hung rank, attach its straggler op-ring arena
        (shared memory survives the wedge) and capture the top ops by total
        time — which op the rank was spending time in when it stalled is
        exactly the CUPTI-buffers post-mortem the reference gets from its
        persistent kernel buffers."""
        if not self.state.op_ring_shm:
            return None
        try:
            from ..straggler.collector import OpRingArena

            arena = OpRingArena.attach(self.state.op_ring_shm)
            try:
                stats = arena.stats()
            finally:
                arena.close()
            top = sorted(stats.values(), key=lambda s: -s.total)[:5]
            summary = [
                {"op": s.name, "total_s": round(s.total, 4),
                 "median_s": round(s.median, 6), "count": s.count}
                for s in top
            ]
            if summary:
                log.error("post-mortem op stats (top by total): %s", summary)
            return summary or None
        except Exception as exc:  # noqa: BLE001 - never block the kill path
            log.warning("post-mortem ring read failed: %s", exc)
            return None

    # -- timeout checks (reference `_periodic_rank_check` :545) ------------

    def _check_timeouts(self, now: Optional[float] = None) -> Optional[str]:
        st = self.state
        if st.connected_at is None:
            return None
        now = time.monotonic() if now is None else now
        # heartbeat path
        if st.last_hb is None:
            t = self.hb_timeouts.initial
            if t is not None and now - st.connected_at > t:
                return f"no initial heartbeat within {t:.1f}s"
        else:
            t = self.hb_timeouts.subsequent
            if t is not None and now - st.last_hb > t:
                return f"heartbeat gap exceeded {t:.1f}s"
        # section path
        for name, opened in st.open_sections.items():
            t = self.section_timeouts.section.get(name)
            if t is not None and now - opened > t:
                return f"section {name!r} open for more than {t:.1f}s"
        if st.seen_section_msgs and not st.open_sections:
            t = self.section_timeouts.out_of_section
            ref = st.last_section_activity or st.connected_at
            if t is not None and now - ref > t:
                return f"out-of-section gap exceeded {t:.1f}s"
        return None

    async def _periodic_check(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.workload_check_interval)
            reason = self._check_timeouts()
            if reason is not None:
                self._shutdown_rank(reason)

    async def _periodic_health(self) -> None:
        """Monitor-hosted node health loop (reference hosts GPU/NIC check
        loops inside the watchdog, ``rank_monitor_server.py:122``).  Runs
        only PASSIVE checks — the watchdog must never initialize the TPU
        runtime beside its worker — and reports failures to the launcher
        over the control pipe, which excludes the node mid-cycle instead of
        waiting for the pre-join gate."""
        from ..health import build_passive_checks

        try:
            chain = build_passive_checks(
                self.cfg.monitor_health_checks,
                kernel_log_source=self.cfg.monitor_health_kernel_log,
                storage_path=(
                    self.cfg.storage_health_check_path
                    if self.cfg.enable_storage_health_check
                    else None
                ),
            )
        except ValueError:
            # a bad check spec must not take the whole watchdog down with it
            # (hang detection matters more than the health loop); the spec is
            # also validated launcher-side so this is double-walled
            log.exception("invalid monitor_health_checks; health loop disabled")
            return
        log.info(
            "monitor health loop enabled: every %.1fs, checks=%s",
            self.cfg.monitor_health_check_interval, self.cfg.monitor_health_checks,
        )
        loop = asyncio.get_running_loop()
        was_healthy = True
        while True:
            await asyncio.sleep(self.cfg.monitor_health_check_interval)
            # run_in_executor: a wedged probe (hung mount, stuck dmesg) must
            # not stall heartbeat timeout checks on the event loop
            result = await loop.run_in_executor(None, chain.run)
            if result.healthy:
                was_healthy = True
                continue
            if not was_healthy:
                continue  # edge-trigger: one report per failure episode
            was_healthy = False
            log.error(
                "node health failure (check=%s): %s", result.name, result.message
            )
            record_event(
                ProfilingEvent.HEALTH_FAILURE,
                check=result.name, message=result.message, cycle=self.cycle,
            )
            if self.ctrl_conn is not None:
                try:
                    self.ctrl_conn.send(
                        {
                            "event": "health_failure",
                            "check": result.name,
                            "message": result.message,
                            "cycle": self.cycle,
                        }
                    )
                except (OSError, BrokenPipeError):
                    pass

    # -- message handling --------------------------------------------------

    def _handle_msg(
        self, msg: Dict[str, Any], conn_id: Optional[int] = None
    ) -> Optional[Dict[str, Any]]:
        try:
            mtype = MsgType(msg["type"])
        except (ValueError, KeyError):
            # Unknown/garbled message (e.g. version skew): report, keep conn.
            return {"type": MsgType.ERROR.value, "error": f"unknown msg {msg.get('type')!r}"}
        st = self.state
        now = time.monotonic()
        if mtype == MsgType.INIT:
            st.reset()
            st.pid = msg.get("pid")
            st.rank = msg.get("rank")
            st.connected_at = now
            st.owner_conn = conn_id
            st.op_ring_shm = msg.get("op_ring_shm")
            # restore persisted calculated timeouts if client carries them
            if msg.get("hb_timeouts"):
                restored = heartbeat_timeouts_from_dict(msg["hb_timeouts"])
                if restored.were_calculated:
                    self.hb_timeouts = restored
            if msg.get("section_timeouts"):
                restored_s = section_timeouts_from_dict(msg["section_timeouts"])
                if restored_s.calculated_sections or restored_s.calculated_out_of_section:
                    self.section_timeouts = restored_s
            log.info("rank %s (pid %s) connected to monitor", st.rank, st.pid)
            return {
                "type": MsgType.OK.value,
                "hb_timeouts": heartbeat_timeouts_to_dict(self.hb_timeouts),
                "section_timeouts": section_timeouts_to_dict(self.section_timeouts),
                "cycle": self.cycle,
            }
        if mtype in (MsgType.HEARTBEAT, MsgType.SECTION_START, MsgType.SECTION_END):
            if st.owner_conn is not None and conn_id != st.owner_conn:
                # a lingering previous worker must not refresh the new
                # worker's liveness state (it would mask a real hang)
                return {
                    "type": MsgType.ERROR.value,
                    "error": "stale connection: another worker owns this monitor",
                }
            if mtype == MsgType.HEARTBEAT:
                if st.last_hb is not None:
                    _HB_GAP_NS.observe((now - st.last_hb) * 1e9)
                st.last_hb = now
                _HB_RECEIVED.inc()
            elif mtype == MsgType.SECTION_START:
                st.seen_section_msgs = True
                st.open_sections[msg["name"]] = now
            else:
                st.seen_section_msgs = True
                st.open_sections.pop(msg["name"], None)
                st.last_section_activity = now
            return {"type": MsgType.OK.value}
        if mtype == MsgType.UPDATE_TIMEOUTS:
            if st.owner_conn is not None and conn_id != st.owner_conn:
                # a lingering previous worker must not rewrite the learned
                # timeouts under the current worker
                return {
                    "type": MsgType.ERROR.value,
                    "error": "stale connection: another worker owns this monitor",
                }
            if msg.get("hb_timeouts"):
                self.hb_timeouts = heartbeat_timeouts_from_dict(msg["hb_timeouts"])
            if msg.get("section_timeouts"):
                self.section_timeouts = section_timeouts_from_dict(msg["section_timeouts"])
            log.info(
                "timeouts updated: hb=%s sections=%s",
                self.hb_timeouts, self.section_timeouts,
            )
            return {"type": MsgType.OK.value}
        return {"type": MsgType.ERROR.value, "error": f"unknown msg {mtype}"}

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn_id = id(writer)
        try:
            while True:
                header = await reader.readexactly(4)
                (ln,) = _U32.unpack(header)
                raw = await reader.readexactly(ln)
                msg = json.loads(raw.decode())
                reply = self._handle_msg(msg, conn_id=conn_id)
                if reply is not None and not msg.get("noack"):
                    out = json.dumps(reply).encode()
                    writer.write(_U32.pack(len(out)) + out)
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            # Only the connection that INITed the current state may reset it:
            # a lingering previous worker's late EOF must not disable hang
            # detection for the new cycle's worker.
            if (
                self.state.connected_at is not None
                and self.state.owner_conn == conn_id
            ):
                log.info("rank %s disconnected from monitor", self.state.rank)
                self.state.reset()
        finally:
            writer.close()

    async def _poll_ctrl(self) -> None:
        """Launcher control pipe: {'cmd': 'cycle', 'cycle': N} / {'cmd': 'shutdown'}."""
        if self.ctrl_conn is None:
            return
        loop = asyncio.get_running_loop()
        while True:
            has_data = await loop.run_in_executor(None, self.ctrl_conn.poll, 0.25)
            if not has_data:
                continue
            try:
                msg = self.ctrl_conn.recv()
            except (EOFError, OSError):
                msg = {"cmd": "shutdown"}
            if msg.get("cmd") == "cycle":
                self.cycle = int(msg["cycle"])
            elif msg.get("cmd") == "shutdown":
                raise asyncio.CancelledError

    # -- lifecycle ---------------------------------------------------------

    async def run_async(self, started_evt=None) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        os.makedirs(os.path.dirname(self.socket_path) or ".", exist_ok=True)
        self._server = await asyncio.start_unix_server(self._handle_conn, self.socket_path)
        if started_evt is not None:
            started_evt.set()
        tasks = [asyncio.create_task(self._periodic_check())]
        if self.cfg.monitor_health_check_interval > 0 and self.host_health_loop:
            tasks.append(asyncio.create_task(self._periodic_health()))
        if self.ctrl_conn is not None:
            tasks.append(asyncio.create_task(self._poll_ctrl()))
        try:
            async with self._server:
                await asyncio.gather(*tasks)
        except asyncio.CancelledError:
            pass
        finally:
            for t in tasks:
                t.cancel()

    @classmethod
    def _proc_main(cls, cfg, socket_path, ctrl_conn, started_evt,
                   host_health_loop=True) -> None:
        setup_logger()
        server = cls(cfg, socket_path, ctrl_conn, host_health_loop=host_health_loop)
        try:
            asyncio.run(server.run_async(started_evt))
        except KeyboardInterrupt:
            pass

    @classmethod
    def run_in_subprocess(
        cls, cfg: FaultToleranceConfig, socket_path: str, mp_ctx=None,
        host_health_loop: bool = True,
    ) -> tuple[mp.Process, Any]:
        """Start the monitor process; returns (process, control_conn).

        Uses **spawn** by default: the axon sitecustomize imports jax into
        every interpreter, so any parent is multithreaded by the time this
        runs and a fork risks the documented fork-under-JAX deadlock on real
        TPU hosts.  All arguments are picklable by construction (dataclass
        cfg, path string, context-matched pipe/event).
        """
        ctx = mp_ctx or mp.get_context("spawn")
        parent_conn, child_conn = ctx.Pipe()
        started_evt = ctx.Event()
        proc = ctx.Process(
            target=cls._proc_main,
            args=(cfg, socket_path, child_conn, started_evt, host_health_loop),
            name=f"tpurx-rank-monitor:{os.path.basename(socket_path)}",
            daemon=True,
        )
        proc.start()
        # spawn boots a fresh interpreter and the sitecustomize imports jax
        # into it — budget the handshake like MonitorProcess does (60s), not
        # the fork-era 15s
        if not started_evt.wait(timeout=60):
            proc.terminate()
            raise RuntimeError("rank monitor server failed to start")
        return proc, parent_conn
