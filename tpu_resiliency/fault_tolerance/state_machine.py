"""Restart-protocol state machine for layered (nested) restart observability.

Capability parity with ``fault_tolerance/rank_monitor_state_machine.py:35-131``:
tracks which phase of the in-process restart protocol a rank is in, so the
launcher-ring monitor knows an in-process restart is underway and must NOT
kill the rank for missing heartbeats mid-recovery.
"""

from __future__ import annotations

import enum
from typing import Dict, Set

from ..utils.logging import get_logger

log = get_logger("restart_state_machine")


class RestarterState(str, enum.Enum):
    UNINITIALIZED = "uninitialized"
    INITIALIZED = "initialized"
    HANDLING_START = "handling_start"   # fault observed, restart beginning
    PROCESSING = "processing"           # abort/finalize/barrier in progress
    COMPLETED = "completed"             # restart finished, fn re-entered
    FINALIZED = "finalized"             # wrapper exited cleanly
    ABORTED = "aborted"                 # wrapper gave up (RestartAbort)


_TRANSITIONS: Dict[RestarterState, Set[RestarterState]] = {
    RestarterState.UNINITIALIZED: {RestarterState.INITIALIZED},
    RestarterState.INITIALIZED: {
        RestarterState.HANDLING_START,
        RestarterState.FINALIZED,
        RestarterState.ABORTED,
    },
    RestarterState.HANDLING_START: {RestarterState.PROCESSING, RestarterState.ABORTED},
    RestarterState.PROCESSING: {RestarterState.COMPLETED, RestarterState.ABORTED},
    RestarterState.COMPLETED: {
        RestarterState.HANDLING_START,
        RestarterState.FINALIZED,
        RestarterState.ABORTED,
    },
    RestarterState.FINALIZED: set(),
    RestarterState.ABORTED: set(),
}


class RestartStateMachine:
    def __init__(self):
        self.state = RestarterState.UNINITIALIZED

    def transition(self, new_state: RestarterState) -> bool:
        """Apply a transition; invalid ones are logged and refused (a garbled
        observability signal must never crash the monitored rank)."""
        if new_state == self.state:
            return True
        if new_state not in _TRANSITIONS[self.state]:
            log.warning(
                "invalid restarter transition %s -> %s ignored",
                self.state.value, new_state.value,
            )
            return False
        self.state = new_state
        return True

    @property
    def in_restart(self) -> bool:
        return self.state in (RestarterState.HANDLING_START, RestarterState.PROCESSING)
