"""Barrier rendezvous over the KV store.

Capability parity with the reference's v2 "barrier rendezvous"
(``fault_tolerance/ft_rendezvous_barrier.py:543-2301``): an atomic,
store-based, round-numbered rendezvous with

- a round-open gate where late joiners and **hot spares** block (reference
  step 0, ``:1296,1842-1865``),
- atomic join counting + round-fenced per-node info writes (step 1,
  ``:1914-1997`` — every key embeds the round number so stale writers from a
  previous incarnation can never corrupt a newer round),
- host-side round closing and group-rank assignment (step 2, ``:1418,881``),
- a ``done`` fence all joiners read the assignment through (step 3, ``:1734``).

Re-designed for TPU: the "segment" constraint that keeps NVLink domains
whole (reference ``:757-1018``) becomes a **slice key** — nodes carry the TPU
slice/ICI-domain they belong to and assignment keeps slices contiguous and
whole, because a partial slice cannot form a usable ICI mesh.

Roles: nodes beyond ``max_nodes`` become STANDBY hot spares: they get no rank
and block at the next round's open gate, ready to replace a failed node
without waiting for scheduler capacity.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import socket
import time
from typing import Dict, List, Optional

from ..store.client import StoreTimeout
from ..store.protocol import ADD_SLOT
from ..telemetry import counter, episode as episode_mod, gauge, histogram
from ..utils.logging import get_logger
from ..utils.profiling import ProfilingEvent, record_event

log = get_logger("rendezvous")

_ROUNDS = counter("tpurx_rendezvous_rounds_total", "Rendezvous rounds opened")
_ROUND_NS = histogram(
    "tpurx_rendezvous_round_duration_ns",
    "Host-side round duration, open to published result",
)
_JOIN_NS = histogram(
    "tpurx_rendezvous_join_latency_ns",
    "Joiner-side latency from join entry to an assignment",
)
_PARTICIPANTS = gauge(
    "tpurx_rendezvous_participants", "Participant nodes in the last closed round"
)
_STANDBY = gauge(
    "tpurx_rendezvous_standby_nodes", "Standby (hot-spare) nodes in the last round"
)

# Store key schema.  Fixed pointers (round counter, cycle, shutdown flag)
# keep flat names; every per-round key is ROUND-FIRST (``rdzv/{n}/...``) so
# the sharded client's affinity routing hashes one round's keys as a unit —
# that co-location is what lets the one-RTT ops (ADD_SET join, WAIT_GE
# close) execute on a single shard.
K_ACTIVE_ROUND = "rdzv/active_round"
K_CYCLE = "rdzv/cycle"
K_SHUTDOWN = "rdzv/shutdown"


def k_restart_req(n: int) -> str:
    return f"rdzv/{n}/restart_req"


def k_shutdown_ack(node_id: str) -> str:
    """Per-node acknowledgement that ``K_SHUTDOWN`` was observed.  The agent
    hosting the store waits for these before tearing the store down, so peers
    provably saw the flag instead of racing a fixed grace sleep."""
    return f"{K_SHUTDOWN}/ack/{node_id}"


def request_restart(store, reason: str = "") -> None:
    """Any agent may request a new round after a failure; the host's round
    loop observes this and opens round N+1 (reference: any agent calls
    ``open_rendezvous``, ``ft_rendezvous_barrier.py:2273``)."""
    n = int(store.get(K_ACTIVE_ROUND))
    store.set(k_restart_req(n), reason or "restart")


def is_restart_requested(store) -> bool:
    n = int(store.get(K_ACTIVE_ROUND))
    return store.check([k_restart_req(n)])


def is_next_round_open(store, current_round: int) -> bool:
    """Healthy agents poll this to join peer-initiated restarts
    (reference ``launcher.py:677``)."""
    raw = store.try_get(K_ACTIVE_ROUND)
    return raw is not None and int(raw) > current_round


def k_open(n: int) -> str:
    return f"rdzv/{n}/open"


def k_closed(n: int) -> str:
    return f"rdzv/{n}/closed"


def k_count(n: int, c: int) -> str:
    """Exact-count marker: the c-th joiner of round n sets this key, so the
    host can block on 'count reached c' with one store WAIT instead of
    polling the counter.  LEGACY path only: stores with ``wait_ge`` block on
    the join counter itself and joiners skip the marker write entirely."""
    return f"rdzv/{n}/count/{c}"


def k_join_count(n: int) -> str:
    return f"rdzv/{n}/join_count"


def k_node(n: int, node_id: str) -> str:
    return f"rdzv/{n}/node/{node_id}"


def k_result(n: int) -> str:
    return f"rdzv/{n}/result"


def k_done(n: int) -> str:
    return f"rdzv/{n}/done"


def k_episode(n: int) -> str:
    """Fault episode round ``n`` belongs to — the flight-recorder join key
    that ties a rendezvous round to the fault that forced it."""
    return f"rdzv/{n}/episode"


def gc_round(store, n: int) -> None:
    """Delete every key round ``n`` may have created (idempotent).

    Only call on SETTLED rounds — the host GCs round ``i - keep`` when
    round ``i`` opens, mirroring ``gc_barrier``'s two-rounds-later
    discipline.  Per-node and per-count keys are enumerated from the store
    and deleted through the same helpers that wrote them."""
    # one delete per helper (not a loop over a tuple): TPURX013 matches
    # write sites to deletes by key-helper identity, and a loop variable
    # hides the helper from the template matcher
    store.delete(k_open(n))
    store.delete(k_closed(n))
    store.delete(k_join_count(n))
    store.delete(k_result(n))
    store.delete(k_done(n))
    store.delete(k_restart_req(n))
    store.delete(k_episode(n))
    for raw in store.list_keys(f"rdzv/{n}/node/"):
        store.delete(k_node(n, raw.decode().rsplit("/", 1)[-1]))
    for raw in store.list_keys(f"rdzv/{n}/count/"):
        tail = raw.decode().rsplit("/", 1)[-1]
        if tail.isdigit():
            store.delete(k_count(n, int(tail)))


class NodeRole(str, enum.Enum):
    PARTICIPANT = "participant"
    STANDBY = "standby"
    EXCLUDED = "excluded"


class RendezvousError(RuntimeError):
    pass


class RendezvousClosedError(RendezvousError):
    """Rendezvous shut down for good (max restarts / operator stop)."""


class RendezvousTimeout(RendezvousError, TimeoutError):
    pass


class UnhealthyNodeError(RendezvousError):
    """Local pre-join health check failed; node must not join."""


@dataclasses.dataclass
class NodeDesc:
    """What a node advertises when joining a round."""

    node_id: str
    hostname: str = ""
    slots: int = 1                      # worker processes this node contributes
    slice_key: str = ""                 # TPU slice / ICI-domain id (segment analog)
    prev_group_rank: Optional[int] = None  # for rank stability across rounds
    arrival: int = 0                    # join order within the round
    excluded: bool = False              # marked bad by workload control

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, raw: bytes | str) -> "NodeDesc":
        return cls(**json.loads(raw))

    @classmethod
    def create(cls, node_id: Optional[str] = None, slots: int = 1, slice_key: str = "") -> "NodeDesc":
        host = socket.gethostname()
        return cls(
            node_id=node_id or f"{host}:{slots}",
            hostname=host,
            slots=slots,
            slice_key=slice_key,
        )


def _desc_json_with_arrival_slot(desc: NodeDesc) -> bytes:
    """The node record JSON with the ``arrival`` field as the server-side
    ADD_SET splice marker: the arrival number is the post-add join counter,
    which only the server knows at send time.  ``json.dumps`` renders the
    int field with default separators, so ``"arrival": 0`` appears exactly
    once (a quote inside ``node_id`` JSON-escapes to ``\\"`` and cannot
    forge the pattern)."""
    base = dataclasses.replace(desc, arrival=0).to_json()
    return base.replace(
        '"arrival": 0', '"arrival": ' + ADD_SLOT.decode(), 1
    ).encode()


@dataclasses.dataclass
class RendezvousResult:
    round_num: int
    cycle: int
    role: NodeRole
    group_rank: Optional[int]           # this node's rank among participant nodes
    group_world_size: int               # number of participant nodes
    global_world_size: int              # total worker slots across participants
    rank_offset: int                    # first global worker rank on this node
    participants: List[str]             # node_ids in group-rank order
    store_addr: str = ""
    store_port: int = 0


def assign_group_ranks(
    nodes: List[NodeDesc],
    min_nodes: int,
    max_nodes: Optional[int],
    require_equal_slots: bool = True,
) -> Dict[str, Dict]:
    """Pure assignment policy (host side).

    Selection order favors (1) non-excluded nodes, (2) keeping whole slices
    together (nodes sharing a slice_key are sorted adjacent and a slice is
    only used if it fits entirely), (3) rank stability (previous group rank),
    (4) arrival order.  Returns {node_id: {"role", "group_rank"}}.
    """
    healthy = [n for n in nodes if not n.excluded]
    if require_equal_slots and healthy:
        slot_counts = {n.slots for n in healthy}
        if len(slot_counts) > 1:
            raise RendezvousError(f"heterogeneous slots per node: {sorted(slot_counts)}")
    cap = max_nodes if max_nodes is not None else len(healthy)

    def sort_key(n: NodeDesc):
        return (
            n.prev_group_rank if n.prev_group_rank is not None else 1 << 30,
            n.slice_key,
            n.arrival,
            n.node_id,
        )

    ordered = sorted(healthy, key=sort_key)

    # Keep slices whole: greedily take slice groups (in order of their best
    # member) while they fit entirely under the cap; single (keyless) nodes
    # fill the remainder.
    by_slice: Dict[str, List[NodeDesc]] = {}
    for n in ordered:
        by_slice.setdefault(n.slice_key, []).append(n)

    selected: List[NodeDesc] = []
    if len(by_slice) == 1:
        selected = ordered[:cap]
    else:
        slice_order = sorted(
            by_slice.items(), key=lambda kv: min(sort_key(n) for n in kv[1])
        )
        for key, members in slice_order:
            if key == "":
                continue
            if len(selected) + len(members) <= cap:
                selected.extend(members)
        for n in by_slice.get("", []):
            if len(selected) < cap:
                selected.append(n)
        # If slice-whole packing under-fills below min_nodes, fall back to
        # plain ordering (a degraded mesh beats no mesh).
        if len(selected) < min(min_nodes, len(ordered)):
            selected = ordered[:cap]

    if len(selected) < min_nodes:
        raise RendezvousError(
            f"not enough healthy nodes: {len(selected)} < min_nodes {min_nodes}"
        )

    selected_ids = {n.node_id for n in selected}
    out: Dict[str, Dict] = {}
    rank = 0
    for n in selected:
        out[n.node_id] = {"role": NodeRole.PARTICIPANT.value, "group_rank": rank}
        rank += 1
    for n in nodes:
        if n.node_id in selected_ids:
            continue
        role = NodeRole.EXCLUDED if n.excluded else NodeRole.STANDBY
        out[n.node_id] = {"role": role.value, "group_rank": None}
    return out


class RendezvousHost:
    """Round lifecycle owner — runs next to the store server (launcher of the
    store-hosting node, or the standalone control plane)."""

    def __init__(
        self,
        store,
        min_nodes: int,
        max_nodes: Optional[int] = None,
        settle_time: float = 2.0,
        close_poll_interval: float = 0.1,
        require_equal_slots: bool = True,
    ):
        self.store = store
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.settle_time = settle_time
        self.close_poll_interval = close_poll_interval
        self.require_equal_slots = require_equal_slots
        # round -> monotonic-ns open stamp (for the round-duration metric)
        self._opened_ns: Dict[int, int] = {}

    def _read_descs(self, keys) -> List[Optional[bytes]]:
        """Node records for ``keys``, batched into one round trip when the
        store supports ``multi_get`` (``None`` per vanished key)."""
        if not keys:
            return []
        multi_get = getattr(self.store, "multi_get", None)
        if multi_get is not None:
            return multi_get(keys)
        return [self.store.try_get(key) for key in keys]

    def _wait_next_arrival(self, n: int, count: int, timeout: float) -> None:
        """Block until joiner ``count + 1`` lands (raises StoreTimeout).

        Fast path: WAIT_GE on the join counter itself — works with both
        joiner generations, since legacy ADD and one-RTT ADD_SET both bump
        it.  Legacy stores block on the exact-count marker key instead."""
        wait_ge = getattr(self.store, "wait_ge", None)
        if wait_ge is not None:
            wait_ge(k_join_count(n), count + 1, timeout=timeout)
        else:
            self.store.wait([k_count(n, count + 1)], timeout=timeout)

    def bootstrap(self) -> None:
        """Initialize round/cycle counters if this is a fresh store."""
        self.store.compare_set(K_ACTIVE_ROUND, b"", b"0")
        self.store.compare_set(K_CYCLE, b"", b"0")

    def current_round(self) -> int:
        return int(self.store.get(K_ACTIVE_ROUND))

    def open_round(self) -> int:
        """Open the next round (called on start and on every restart decision).
        Idempotent per round transition thanks to CAS on the round pointer."""
        n = self.current_round()
        if self.store.check([k_done(n)]) or not self.store.check([k_open(n)]):
            target = n + 1 if self.store.check([k_open(n)]) else n
            # advance pointer (only one host instance does this; CAS guards
            # against double-open from re-entrant calls)
            self.store.compare_set(K_ACTIVE_ROUND, str(n).encode(), str(target).encode())
            self.store.set(k_open(target), b"1")
            cycle = self.store.add(K_CYCLE, 1) - 1
            self._gc_old_rounds(target)
            log.info("rendezvous round %s open (cycle %s)", target, cycle)
            _ROUNDS.inc()
            # stamps of rounds that never reached close must not accumulate
            # across a long crash loop
            self._opened_ns = {
                r: ns for r, ns in self._opened_ns.items() if r >= target - 2
            }
            self._opened_ns[target] = time.monotonic_ns()
            # stamp the live fault episode (if any) onto the round — joins
            # this round's records to the flight-recorder episode timeline
            eid = episode_mod.adopt(self.store)
            if eid:
                self.store.set(k_episode(target), eid)
            record_event(ProfilingEvent.RENDEZVOUS_STARTED, round=target, cycle=cycle)
            return target
        return n

    def _gc_old_rounds(self, current: int, keep: int = 2) -> None:
        """Delete keys of rounds older than ``current - keep``: a job crash-
        looping for days must not grow the store unboundedly.  Stale writers
        are already fenced by round-numbered keys; GC only reclaims memory.
        The round-first layout makes discovery one prefix scan: any
        ``rdzv/{digits}/...`` key names its round in the second segment."""
        cutoff = current - keep
        if cutoff < 0:
            return
        try:
            rounds = set()
            for key in self.store.list_keys("rdzv/"):
                parts = key.decode().split("/", 2)
                if len(parts) >= 2 and parts[1].isdigit():
                    rounds.add(int(parts[1]))
            for r in sorted(rounds):
                if r < cutoff:
                    gc_round(self.store, r)
        except Exception:  # noqa: BLE001 - GC must never break a round open
            log.exception("round GC failed (continuing)")

    def close_round_when_ready(self, timeout: float = 600.0) -> int:
        """Step 2: wait for >= min_nodes joiners (plus a settle window to let
        stragglers/spares in, ended early once max_nodes reached), then fence
        the round, assign ranks, publish the result."""
        n = self.current_round()
        deadline = time.monotonic() + timeout
        settle_deadline: Optional[float] = None
        # Node records are fetched once per key (O(N) total store reads for
        # the whole close, not O(N^2) across wakes).  A record CAN be
        # overwritten within a round (same node rejoining); the cache may
        # then gate on a stale health bit — harmless: the authoritative
        # re-read below the loop drives the actual assignment, and a
        # too-early close surfaces as the assignment error the launcher
        # already retries on.
        desc_cache: Dict[bytes, NodeDesc] = {}
        while True:
            count = int(self.store.try_get(k_join_count(n)) or b"0")
            missing = [
                key for key in self.store.list_keys(f"rdzv/{n}/node/")
                if key not in desc_cache
            ]
            if missing:
                # batched: at 10k nodes, per-key GETs would cost O(N)
                # sequential round trips per close-loop wake
                for key, raw in zip(missing, self._read_descs(missing)):
                    if raw is not None:
                        desc_cache[key] = NodeDesc.from_json(raw)
            nodes_now = list(desc_cache.values())
            if len(nodes_now) < count:
                # arrival counters lead their node records by a few writes;
                # the records carry the health bits the decisions below
                # need.  A PERMANENT mismatch (joiner died between its ADD
                # and its record write) must still honor the deadline.
                if time.monotonic() >= deadline:
                    if sum(1 for d in nodes_now if not d.excluded) >= self.min_nodes:
                        break
                    raise RendezvousTimeout(
                        f"round {n}: {count} arrivals but only "
                        f"{len(nodes_now)} node records"
                    )
                time.sleep(0.01)
                continue
            # The EARLY-close gate runs on HEALTHY joiners: with
            # event-driven joins an excluded node can re-join a fresh round
            # milliseconds before its replacement spare, and closing on raw
            # arrivals would fail assignment before the spare lands.  Health
            # only defers closing WITHIN the settle window though — once it
            # expires the round closes with whatever arrived and
            # ``assign_group_ranks`` arbitrates (its 'not enough healthy
            # nodes' error is the prompt, precise failure a fleet with no
            # spare must surface).
            healthy = sum(1 for d in nodes_now if not d.excluded)
            if self.max_nodes is not None and healthy >= self.max_nodes:
                break
            now = time.monotonic()
            remaining = deadline - now
            if count >= self.min_nodes:
                # fixed settle window from the moment min ARRIVALS was first
                # reached (a trickle of joiners must not extend it); each
                # arrival inside the window re-evaluates via its count marker
                if settle_deadline is None:
                    settle_deadline = now + self.settle_time
                wait_s = min(settle_deadline - now, remaining)
                if wait_s <= 0:
                    break
                try:
                    self._wait_next_arrival(n, count, max(0.01, wait_s))
                    continue  # someone arrived: re-evaluate health/max
                except StoreTimeout:
                    break  # settle expired with nobody new
            settle_deadline = None
            if remaining <= 0:
                raise RendezvousTimeout(
                    f"round {n}: only {count}/{self.min_nodes} nodes joined"
                )
            # block until the next joiner arrives (bounded chunks so the
            # overall timeout is still honored)
            try:
                self._wait_next_arrival(
                    n, count, max(0.01, min(remaining, 30.0))
                )
            except StoreTimeout:
                continue

        self.store.set(k_closed(n), b"1")
        # small grace for in-flight joiners who passed the open-gate check
        time.sleep(self.close_poll_interval)
        count = int(self.store.try_get(k_join_count(n)) or b"0")
        nodes = [
            NodeDesc.from_json(raw)
            for raw in self._read_descs(self.store.list_keys(f"rdzv/{n}/node/"))
            if raw is not None
        ]
        assignment = assign_group_ranks(
            nodes, self.min_nodes, self.max_nodes,
            require_equal_slots=self.require_equal_slots,
        )
        participants = sorted(
            (nid for nid, a in assignment.items() if a["group_rank"] is not None),
            key=lambda nid: assignment[nid]["group_rank"],
        )
        slots = {d.node_id: d.slots for d in nodes}
        result = {
            "assignment": assignment,
            "participants": participants,
            "slots": slots,
            "cycle": int(self.store.get(
                K_CYCLE, timeout=max(0.01, deadline - time.monotonic()),
            )) - 1,
            "episode": (self.store.try_get(k_episode(n)) or b"").decode(),
        }
        self.store.set(k_result(n), json.dumps(result))
        self.store.set(k_done(n), b"1")
        standby = sum(
            1 for a in assignment.values() if a["role"] == NodeRole.STANDBY.value
        )
        _PARTICIPANTS.set(len(participants))
        _STANDBY.set(standby)
        opened = self._opened_ns.pop(n, None)
        if opened is not None:
            _ROUND_NS.observe(time.monotonic_ns() - opened)
        log.info(
            "round %s closed: %s participants, %s standby",
            n,
            len(participants),
            standby,
        )
        record_event(
            ProfilingEvent.RENDEZVOUS_COMPLETED, round=n, participants=len(participants)
        )
        return n

    def shutdown(self, reason: str = "") -> None:
        self.store.set(K_SHUTDOWN, reason or "shutdown")


class RendezvousJoiner:
    """Node-side protocol (steps 0/1/3)."""

    def __init__(
        self,
        store,
        desc: NodeDesc,
        pre_join_health_check=None,
        open_poll_interval: float = 0.25,
    ):
        self.store = store
        self.desc = desc
        self.pre_join_health_check = pre_join_health_check
        self.open_poll_interval = open_poll_interval

    def _check_shutdown(self) -> None:
        reason = self.store.try_get(K_SHUTDOWN)
        if reason is not None:
            raise RendezvousClosedError(reason.decode() or "shutdown")

    def wait_round_open(self, timeout: float = 600.0) -> int:
        """Step 0: block until a joinable (open, not closed) round exists.
        Hot spares and late arrivals park here.  Event-driven: when the
        current round is already closed, the next one can only be ``n+1``
        (``open_round`` advances the pointer by one), so block on that
        round's open key with a store WAIT instead of polling — bounded
        chunks keep the shutdown check and overall timeout honored."""
        deadline = time.monotonic() + timeout
        while True:
            self._check_shutdown()
            raw = self.store.try_get(K_ACTIVE_ROUND)
            remaining = deadline - time.monotonic()
            if raw is not None:
                n = int(raw)
                closed = self.store.check([k_closed(n)])
                if self.store.check([k_open(n)]) and not closed:
                    return n
                if remaining <= 0:
                    raise RendezvousTimeout("no open rendezvous round")
                # round n closed -> the next joinable one is n+1; round n
                # merely not-yet-open (bootstrap set the pointer before
                # open_round set the key) -> wait on n itself
                target = n + 1 if closed else n
                try:
                    self.store.wait(
                        [k_open(target)],
                        timeout=max(0.01, min(remaining, 2.0)),
                    )
                except StoreTimeout:
                    pass
                continue
            if remaining <= 0:
                raise RendezvousTimeout("no open rendezvous round")
            time.sleep(self.open_poll_interval)

    def join(self, timeout: float = 600.0) -> RendezvousResult:
        """Full join: wait for open round → health check → register → await
        assignment.  Raises UnhealthyNodeError if the local check fails."""
        deadline = time.monotonic() + timeout
        join_t0 = time.monotonic_ns()
        while True:
            n = self.wait_round_open(timeout=deadline - time.monotonic())
            if self.pre_join_health_check is not None:
                self.pre_join_health_check()  # raises UnhealthyNodeError
            add_set = getattr(self.store, "add_set", None)
            if add_set is not None:
                # One-RTT registration: counter bump + node record in one
                # atomic op, the arrival number spliced server-side into the
                # record.  No count marker — a WAIT_GE host blocks on the
                # join counter, which this same op advances, and the record
                # is readable the instant the counter moves (both mutate in
                # one server step, where the legacy path's counter led its
                # record by a round trip).
                add_set(
                    k_join_count(n), 1, k_node(n, self.desc.node_id),
                    _desc_json_with_arrival_slot(self.desc),
                )
            else:
                arrival = self.store.add(k_join_count(n), 1)
                desc = dataclasses.replace(self.desc, arrival=arrival)
                self.store.set(k_node(n, desc.node_id), desc.to_json())
                # exact-count marker AFTER the node record: when the host's
                # wait on this key fires, the node info is readable
                self.store.set(k_count(n, arrival), b"1")
            try:
                self.store.wait([k_done(n)], timeout=max(1.0, deadline - time.monotonic()))
            except Exception as exc:
                self._check_shutdown()
                raise RendezvousTimeout(f"round {n} never completed: {exc}") from exc
            result = json.loads(self.store.get(k_result(n)))
            # adopt the fault episode the round belongs to: this joiner's
            # flight/profiling events join the same cross-host timeline
            episode_mod.adopt(self.store)
            mine = result["assignment"].get(self.desc.node_id)
            if mine is None:
                # Raced the round close: our info write landed after the host
                # read the node list.  Not fatal — retry at the next round's
                # open gate like a hot spare.
                log.warning(
                    "node %s joined round %s too late for assignment; retrying",
                    self.desc.node_id, n,
                )
                time.sleep(self.open_poll_interval)
                continue
            role = NodeRole(mine["role"])
            participants = result["participants"]
            slots = result["slots"]
            global_world = sum(slots[p] for p in participants)
            _JOIN_NS.observe(time.monotonic_ns() - join_t0)
            if role == NodeRole.PARTICIPANT:
                grank = mine["group_rank"]
                self.desc.prev_group_rank = grank
                rank_offset = sum(slots[p] for p in participants[:grank])
                return RendezvousResult(
                    round_num=n,
                    cycle=result["cycle"],
                    role=role,
                    group_rank=grank,
                    group_world_size=len(participants),
                    global_world_size=global_world,
                    rank_offset=rank_offset,
                    participants=participants,
                )
            if role == NodeRole.EXCLUDED:
                raise RendezvousClosedError(f"node {self.desc.node_id} excluded")
            # STANDBY: hot spare — park at the next round's open gate by
            # looping (the next wait_round_open only returns on a new round).
            log.info("node %s standby for round %s; waiting as hot spare", self.desc.node_id, n)
            if time.monotonic() >= deadline:
                return RendezvousResult(
                    round_num=n,
                    cycle=result["cycle"],
                    role=role,
                    group_rank=None,
                    group_world_size=len(participants),
                    global_world_size=global_world,
                    rank_offset=0,
                    participants=participants,
                )
            while (
                self.store.check([k_closed(n)])
                and int(self.store.get(K_ACTIVE_ROUND)) == n
            ):
                self._check_shutdown()
                if time.monotonic() >= deadline:
                    raise RendezvousTimeout("standby node: no new round opened")
                try:  # spare promotion is latency-sensitive: block, don't poll
                    self.store.wait(
                        [k_open(n + 1)],
                        timeout=max(
                            0.01,
                            min(deadline - time.monotonic(), 2.0),
                        ),
                    )
                except StoreTimeout:
                    pass  # re-check shutdown / active round and re-wait
