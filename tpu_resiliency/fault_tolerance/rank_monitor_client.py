"""In-rank monitoring API.

Capability parity with ``fault_tolerance/rank_monitor_client.py:52-567``
(``RankMonitorClient``): connect to the per-rank monitor over UDS, send
init/heartbeat/section messages (unidirectional fast path when
``skip_section_response``), locally observe intervals via
:class:`TimeoutsCalc`, synchronize and push calculated timeouts, persist them
across restarts via ``state_dict()``.

The heartbeat send is the hot path: with ``skip_section_response=True`` it is
one 4-byte-framed JSON write on a connected UDS — O(10µs), negligible next to
a training step.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time
from typing import Any, Dict, Optional

from ..telemetry import counter, flight, histogram
from ..utils import env
from ..utils.ipc import recv_msg, send_msg
from ..utils.logging import get_logger
from .config import FaultToleranceConfig
from .data import (
    HeartbeatTimeouts,
    MsgType,
    RankInfo,
    SectionTimeouts,
    WorkloadAction,
    WorkloadControlRequest,
    heartbeat_timeouts_from_dict,
    heartbeat_timeouts_to_dict,
    section_timeouts_from_dict,
    section_timeouts_to_dict,
)
from .timeouts import TimeoutsCalc

log = get_logger("rank_monitor_client")

_HB_SENT = counter(
    "tpurx_heartbeat_sent_total", "Heartbeats sent to the rank monitor"
)
_HB_SEND_NS = histogram(
    "tpurx_heartbeat_send_latency_ns",
    "Heartbeat send latency over the monitor UDS (ack wait included when "
    "skip_section_response is off)",
)
_SECTION_NS = histogram(
    "tpurx_monitor_section_msg_latency_ns",
    "Section start/end message latency over the monitor UDS",
)

ENV_MONITOR_SOCKET = env.RANK_MONITOR_SOCKET.name
ENV_LAUNCHER_IPC_SOCKET = env.LAUNCHER_IPC_SOCKET.name

# flight-recorder events: a fault-time dump shows the monitored workload's
# last heartbeats and which instrumented section it died inside
EV_HEARTBEAT = flight.declare_event("monitor.heartbeat", "cycle")
EV_SECTION_BEGIN = flight.declare_event("monitor.section_begin", "section")
EV_SECTION_END = flight.declare_event("monitor.section_end", "section")


class RankMonitorClientError(RuntimeError):
    pass


class RankMonitorClient:
    def __init__(self, cfg: Optional[FaultToleranceConfig] = None):
        self.cfg = cfg or FaultToleranceConfig().merged_with_env()
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self.rank_info: Optional[RankInfo] = None
        self.hb_timeouts: Optional[HeartbeatTimeouts] = None
        self.section_timeouts: Optional[SectionTimeouts] = None
        self.cycle: int = 0
        self.timeouts_calc: Optional[TimeoutsCalc] = None
        self._loaded_state: Optional[Dict[str, Any]] = None

    # -- connection / init -------------------------------------------------

    def init_workload_monitoring(
        self, socket_path: Optional[str] = None,
        rank_info: Optional[RankInfo] = None,
        op_ring_shm: Optional[str] = None,
    ) -> None:
        path = socket_path or env.RANK_MONITOR_SOCKET.get()
        if not path:
            raise RankMonitorClientError(
                f"no monitor socket: set {ENV_MONITOR_SOCKET} or pass socket_path"
            )
        self.rank_info = rank_info or RankInfo.from_env()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(30.0)
        self._sock.connect(path)
        init: Dict[str, Any] = {
            "type": MsgType.INIT.value,
            "rank": self.rank_info.global_rank,
            "local_rank": self.rank_info.local_rank,
            "pid": self.rank_info.pid,
        }
        # straggler op-ring arena name: lets the monitor read this rank's
        # per-op stats POST-MORTEM while the trainer is wedged (the
        # CUPTI-buffers-outlive-the-launch property)
        ring = op_ring_shm or env.OPRING_SHM.get()
        if ring:
            init["op_ring_shm"] = ring
        if self._loaded_state:
            init["hb_timeouts"] = self._loaded_state.get("hb_timeouts")
            init["section_timeouts"] = self._loaded_state.get("section_timeouts")
        reply = self._request(init)
        self.hb_timeouts = heartbeat_timeouts_from_dict(reply["hb_timeouts"])
        self.section_timeouts = section_timeouts_from_dict(reply["section_timeouts"])
        self.cycle = int(reply.get("cycle", 0))
        self.timeouts_calc = TimeoutsCalc(
            safety_factor=self.cfg.safety_factor,
            sections=tuple(self.section_timeouts.section),
        )
        log.info(
            "workload monitoring initialized (rank=%s cycle=%s)",
            self.rank_info.global_rank, self.cycle,
        )
        from ..telemetry.exporter import serve_from_env_once

        serve_from_env_once()  # per-rank scrape endpoint, when env asks

    def shutdown_workload_monitoring(self) -> None:
        with self._lock:
            if self._sock is not None:
                with contextlib.suppress(OSError):
                    self._sock.close()
                self._sock = None

    @property
    def is_initialized(self) -> bool:
        return self._sock is not None

    # -- message plumbing --------------------------------------------------

    def _send(self, payload: Dict[str, Any], want_ack: bool) -> Optional[Dict[str, Any]]:
        if self._sock is None:
            raise RankMonitorClientError("not initialized")
        if not want_ack:
            payload = {**payload, "noack": True}
        with self._lock:
            send_msg(self._sock, payload)
            if not want_ack:
                return None
            reply = recv_msg(self._sock)
        if reply is None:
            raise RankMonitorClientError("monitor connection closed")
        if reply.get("type") == MsgType.ERROR.value:
            raise RankMonitorClientError(reply.get("error", "monitor error"))
        return reply

    def _request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        reply = self._send(payload, want_ack=True)
        assert reply is not None
        return reply

    # -- heartbeats / sections --------------------------------------------

    def send_heartbeat(self) -> None:
        ack = not self.cfg.skip_section_response
        flight.record(EV_HEARTBEAT, self.cycle)
        t0 = time.monotonic_ns()
        self._send({"type": MsgType.HEARTBEAT.value}, want_ack=ack)
        _HB_SEND_NS.observe(time.monotonic_ns() - t0)
        _HB_SENT.inc()
        if self.timeouts_calc is not None:
            self.timeouts_calc.update_on_heartbeat()

    def start_section(self, name: str) -> None:
        ack = not self.cfg.skip_section_response
        flight.record(EV_SECTION_BEGIN, name)
        t0 = time.monotonic_ns()
        self._send({"type": MsgType.SECTION_START.value, "name": name}, want_ack=ack)
        _SECTION_NS.observe(time.monotonic_ns() - t0)
        if self.timeouts_calc is not None:
            self.timeouts_calc.update_on_section_start(name)

    def end_section(self, name: str) -> None:
        ack = not self.cfg.skip_section_response
        flight.record(EV_SECTION_END, name)
        t0 = time.monotonic_ns()
        self._send({"type": MsgType.SECTION_END.value, "name": name}, want_ack=ack)
        _SECTION_NS.observe(time.monotonic_ns() - t0)
        if self.timeouts_calc is not None:
            self.timeouts_calc.update_on_section_end(name)

    @contextlib.contextmanager
    def section(self, name: str):
        self.start_section(name)
        try:
            yield
        finally:
            self.end_section(name)

    # -- timeout calculation ----------------------------------------------

    def calculate_and_set_hb_timeouts(
        self, store=None, rank=None, world_size=None, reduce_fn=None
    ) -> HeartbeatTimeouts:
        """Sync observed maxima across ranks, derive timeouts, push to monitor.

        Pass either store+rank+world_size (DCN path) or reduce_fn (device
        pmax), or nothing for purely local calculation (single rank)."""
        assert self.timeouts_calc is not None
        if reduce_fn is not None or store is not None:
            self.timeouts_calc.synchronize_all(
                store=store, rank=rank, world_size=world_size, reduce_fn=reduce_fn,
                namespace=f"cycle{self.cycle}",
            )
        new = self.timeouts_calc.calculate_hb_timeouts(self.hb_timeouts)
        self.hb_timeouts = new
        self._request(
            {
                "type": MsgType.UPDATE_TIMEOUTS.value,
                "hb_timeouts": heartbeat_timeouts_to_dict(new),
            }
        )
        return new

    def calculate_and_set_section_timeouts(
        self, selection=None, store=None, rank=None, world_size=None, reduce_fn=None
    ) -> SectionTimeouts:
        assert self.timeouts_calc is not None
        if reduce_fn is not None or store is not None:
            self.timeouts_calc.synchronize_all(
                store=store, rank=rank, world_size=world_size, reduce_fn=reduce_fn,
                namespace=f"cycle{self.cycle}",
            )
        new = self.timeouts_calc.calculate_section_timeouts(
            self.section_timeouts, selection=selection
        )
        self.section_timeouts = new
        self._request(
            {
                "type": MsgType.UPDATE_TIMEOUTS.value,
                "section_timeouts": section_timeouts_to_dict(new),
            }
        )
        return new

    # -- persistence (reference `state_dict` :496-550) ---------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "hb_timeouts": heartbeat_timeouts_to_dict(self.hb_timeouts)
            if self.hb_timeouts
            else None,
            "section_timeouts": section_timeouts_to_dict(self.section_timeouts)
            if self.section_timeouts
            else None,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._loaded_state = state
        if self.is_initialized:
            # already connected: push restored timeouts immediately
            payload: Dict[str, Any] = {"type": MsgType.UPDATE_TIMEOUTS.value}
            if state.get("hb_timeouts"):
                self.hb_timeouts = heartbeat_timeouts_from_dict(state["hb_timeouts"])
                payload["hb_timeouts"] = state["hb_timeouts"]
            if state.get("section_timeouts"):
                self.section_timeouts = section_timeouts_from_dict(
                    state["section_timeouts"]
                )
                payload["section_timeouts"] = state["section_timeouts"]
            self._request(payload)

    # -- workload control (rank → launcher) --------------------------------

    def send_workload_control_request(
        self, action: WorkloadAction, reason: str = ""
    ) -> None:
        """Ask the launcher to exclude this node / shut down the workload
        (reference ``WorkloadControlRequest``, ``data.py:272``)."""
        from ..utils.ipc import IpcConnector

        path = env.LAUNCHER_IPC_SOCKET.get()
        if not path:
            raise RankMonitorClientError(f"{ENV_LAUNCHER_IPC_SOCKET} not set")
        req = WorkloadControlRequest(action=action, reason=reason)
        IpcConnector(path).send(
            {"kind": "workload_control", "action": req.action.value, "reason": req.reason}
        )
