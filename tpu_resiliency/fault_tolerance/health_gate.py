"""Pre-rendezvous node health gate.

Capability parity with the reference's pre-join hook health checks
(``ft_rendezvous_barrier.py:1902`` UnhealthyNodeException path) plus the
env-driven failure injector used for spare-node testing
(``testing_utils/health_check_injector.py:17-60``:
``NVRX_INJECT_GPU_FAILURE="cycle:infra_rank"``).

TPURX_INJECT_NODE_FAILURE="<cycle>:<node_id_substring>" makes the gate fail
for a matching node at a matching cycle — simulating device loss so tests can
exercise hot-spare replacement without real hardware faults.
"""

from __future__ import annotations


from ..utils import env
from ..utils.logging import get_logger
from .config import FaultToleranceConfig
from .rendezvous import UnhealthyNodeError

log = get_logger("health_gate")

ENV_INJECT = env.INJECT_NODE_FAILURE.name


def _injected_failure(node_id: str, current_cycle: int) -> bool:
    spec = env.INJECT_NODE_FAILURE.get()
    if not spec:
        return False
    try:
        cycle_s, _, node_sub = spec.partition(":")
        cycle = int(cycle_s)
    except ValueError:
        return False
    # fire at the given cycle or later (a dead node stays dead)
    return current_cycle >= cycle and node_sub in node_id


def pre_rendezvous_health_check(
    cfg: FaultToleranceConfig, node_id: str, current_cycle: int = 0
) -> None:
    """Raise UnhealthyNodeError if this node must not join the round."""
    if _injected_failure(node_id, current_cycle):
        raise UnhealthyNodeError(f"injected node failure for {node_id}")
    if cfg.enable_device_health_check:
        from ..health import DeviceHealthCheck

        check = DeviceHealthCheck()
        result = check.run()
        if not result.healthy:
            raise UnhealthyNodeError(f"device health check failed: {result.message}")
    if cfg.enable_storage_health_check and cfg.storage_health_check_path:
        from ..health import StoragePathHealthCheck

        result = StoragePathHealthCheck(cfg.storage_health_check_path).run()
        if not result.healthy:
            raise UnhealthyNodeError(f"storage health check failed: {result.message}")
