"""Timeout calculator: derive hang-detection timeouts from observed intervals.

Capability parity with ``fault_tolerance/timeouts_calc.py:33-281``
(``TimeoutsCalc``): track max observed heartbeat interval and per-section
durations, synchronize the MAX across ranks, and produce
timeout = safety_factor × observed-max, EMA-merged with the current timeout.

The cross-rank MAX reduction is the TPU twist: the reference all-reduces a
tensor over NCCL/Gloo (``timeouts_calc.py:74-91``).  Here the default path is
a KV-store gather-max over DCN (control plane — always available, even when
ranks hold no devices), and callers inside a live JAX mesh can pass
``reduce_fn`` from ``tpu_resiliency.parallel.collectives.make_timeouts_reduce_fn``
for the device lane — a wrapped (deadlined, telemetered, degradable)
all-gather-max through the self-healing collective layer
(``docs/collectives.md``); a wedged mesh raises ``CollectiveTimeout``
instead of hanging the sync, and the store path remains the mesh-free
fallback.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional, Sequence

from ..store.tree import tree_gather
from .data import HeartbeatTimeouts, SectionTimeouts


def _combine_keywise_max(payloads) -> bytes:
    """Tree combiner: key-wise max over ``{stat_key: value}`` JSON dicts."""
    merged: Dict[str, float] = {}
    for raw in payloads:
        for k, v in json.loads(
            raw if isinstance(raw, str) else raw.decode()
        ).items():
            merged[k] = max(merged.get(k, float("-inf")), v)
    return json.dumps(merged).encode()


class TimeoutsCalcError(RuntimeError):
    pass


class TimeoutsCalc:
    def __init__(
        self,
        start_time: Optional[float] = None,
        safety_factor: float = 5.0,
        ema_alpha: float = 0.5,
        sections: Sequence[str] = (),
    ):
        if safety_factor <= 1.0:
            raise ValueError("safety_factor must be > 1.0")
        self._safety_factor = safety_factor
        self._ema_alpha = ema_alpha
        self._start_time = start_time if start_time is not None else time.monotonic()
        self._last_hb_time: Optional[float] = None
        self.initial_max: float = float("-inf")
        self.subsequent_max: float = float("-inf")
        # sections
        self._section_open: Dict[str, float] = {}
        self.section_max: Dict[str, float] = {s: float("-inf") for s in sections}
        self.out_of_section_max: float = float("-inf")
        self._last_section_close: Optional[float] = None
        self._sync_gen = 0

    # -- observation -------------------------------------------------------

    def update_on_heartbeat(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last_hb_time is None:
            self.initial_max = max(self.initial_max, now - self._start_time)
        else:
            self.subsequent_max = max(self.subsequent_max, now - self._last_hb_time)
        self._last_hb_time = now

    def update_on_section_start(self, name: str, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if name in self._section_open:
            raise TimeoutsCalcError(f"section {name!r} already open")
        # gap since last activity counts as out-of-section time
        ref = self._last_section_close if self._last_section_close is not None else self._start_time
        if not self._section_open:
            self.out_of_section_max = max(self.out_of_section_max, now - ref)
        self._section_open[name] = now
        self.section_max.setdefault(name, float("-inf"))

    def update_on_section_end(self, name: str, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        start = self._section_open.pop(name, None)
        if start is None:
            raise TimeoutsCalcError(f"section {name!r} not open")
        self.section_max[name] = max(self.section_max.get(name, float("-inf")), now - start)
        if not self._section_open:
            self._last_section_close = now

    @property
    def can_get_hb_timeouts(self) -> bool:
        return self.initial_max > float("-inf") and self.subsequent_max > float("-inf")

    # -- cross-rank MAX sync ----------------------------------------------

    # Stats travel as {key: value} dicts (not positional vectors) so ranks
    # that observed different section sets merge by key union instead of
    # silently misaligning columns.
    def _values(self) -> Dict[str, float]:
        out = {
            "__initial__": self.initial_max,
            "__subsequent__": self.subsequent_max,
            "__oos__": self.out_of_section_max,
        }
        for n, v in self.section_max.items():
            out["s:" + n] = v
        return out

    def _load_values(self, vals: Dict[str, float]) -> None:
        self.initial_max = vals.get("__initial__", self.initial_max)
        self.subsequent_max = vals.get("__subsequent__", self.subsequent_max)
        self.out_of_section_max = vals.get("__oos__", self.out_of_section_max)
        for k, v in vals.items():
            if k.startswith("s:"):
                self.section_max[k[2:]] = v

    def synchronize_all(
        self,
        store=None,
        rank: Optional[int] = None,
        world_size: Optional[int] = None,
        reduce_fn: Optional[Callable[[Dict[str, float]], Dict[str, float]]] = None,
        timeout: float = 60.0,
        namespace: str = "",
    ) -> None:
        """Key-wise MAX of observed stats across ranks.

        Either pass ``reduce_fn`` (the device lane:
        ``parallel.collectives.make_timeouts_reduce_fn()`` — a wrapped
        all-gather-max taking and returning the ``{stat_key: value}``
        dict, deadlined and degradable like every resiliency-layer
        collective) or a store + rank + world_size for the DCN
        gather-max path.

        ``namespace`` must be shared by all ranks of one incarnation but
        unique across restarts (e.g. the restart cycle number) — the store
        outlives worker incarnations, and reusing ``tc_sync`` keys from a
        previous cycle would corrupt the gather barrier.
        """
        vals = self._values()
        if reduce_fn is not None:
            self._load_values(dict(reduce_fn(vals)))
            return
        if store is None or rank is None or world_size is None:
            raise TimeoutsCalcError("need store+rank+world_size or reduce_fn")
        gen = self._sync_gen
        self._sync_gen += 1
        base = f"tc_sync/{namespace}" if namespace else "tc_sync"
        # key-wise max over the reduction tree, result broadcast back: every
        # rank reads O(fanout) inbound payloads, and no read fence is needed
        # (parents delete child keys they alone consume; the stale result
        # key is GC'd two generations later)
        merged_raw = tree_gather(
            store,
            rank,
            world_size,
            prefix=f"{base}/{gen}",
            payload=json.dumps(vals).encode(),
            combine=_combine_keywise_max,
            timeout=timeout,
            broadcast=True,
            site="timeouts",
            gc_prefix=f"{base}/{gen - 2}/" if gen >= 2 else None,
        )
        self._load_values(dict(json.loads(merged_raw)))

    # -- timeout derivation ------------------------------------------------

    def _merge(self, current: Optional[float], observed: float) -> float:
        new = self._safety_factor * observed
        if current is None:
            return new
        # EMA, but never shrink below what we just observed needs
        merged = self._ema_alpha * new + (1 - self._ema_alpha) * current
        return max(merged, new)

    def calculate_hb_timeouts(
        self, current: Optional[HeartbeatTimeouts] = None
    ) -> HeartbeatTimeouts:
        if not self.can_get_hb_timeouts:
            raise TimeoutsCalcError("not enough heartbeats observed")
        cur_ini = current.initial if current and current.were_calculated else None
        cur_sub = current.subsequent if current and current.were_calculated else None
        return HeartbeatTimeouts(
            initial=self._merge(cur_ini, self.initial_max),
            subsequent=self._merge(cur_sub, self.subsequent_max),
            were_calculated=True,
        )

    def calculate_section_timeouts(
        self,
        current: Optional[SectionTimeouts] = None,
        selection: Optional[Sequence[str]] = None,
        calc_out_of_section: bool = True,
    ) -> SectionTimeouts:
        names = list(selection) if selection is not None else sorted(self.section_max)
        section: Dict[str, Optional[float]] = dict(current.section) if current else {}
        calculated = set(current.calculated_sections) if current else set()
        for n in names:
            observed = self.section_max.get(n, float("-inf"))
            if observed == float("-inf"):
                continue
            cur = section.get(n) if n in calculated else None
            section[n] = self._merge(cur, observed)
            calculated.add(n)
        oos = current.out_of_section if current else None
        calc_oos = current.calculated_out_of_section if current else False
        if calc_out_of_section and self.out_of_section_max > float("-inf"):
            oos = self._merge(oos if calc_oos else None, self.out_of_section_max)
            calc_oos = True
        return SectionTimeouts(
            section=section,
            out_of_section=oos,
            calculated_sections=tuple(sorted(calculated)),
            calculated_out_of_section=calc_oos,
        )
