"""Standalone control-plane process (reference ``control_plane.py:266``,
CLI ``nvrx-control``).

Hosts the KV store + the rendezvous round loop outside any compute node, so
launchers are pure store clients: the control plane survives every compute
node dying, and job-level restarts (new SLURM/GKE job, same control plane)
keep cycle numbering and rendezvous state.

    python -m tpu_resiliency.fault_tolerance.control_plane \
        --port 29500 --min-nodes 2 --max-nodes 4

Launchers then run WITHOUT ``--host-store``, pointing at this endpoint.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time

from ..store import StoreClient, StoreServer
from ..utils.logging import get_logger, setup_logger
from .launcher import HostRoundLoop
from .rendezvous import K_SHUTDOWN, RendezvousHost

log = get_logger("control_plane")


def run(
    host: str,
    port: int,
    min_nodes: int,
    max_nodes: int | None,
    round_timeout: float,
    settle_time: float,
    native: bool = False,
    journal: str | None = None,
    require_equal_slots: bool = True,
    shards: int = 1,
) -> int:
    if shards > 1:
        return _run_sharded(
            host, port, min_nodes, max_nodes, round_timeout, settle_time,
            journal, require_equal_slots, shards,
        )
    if native:
        from ..store.native import NativeStoreServer

        # tpurx: disable=TPURX012 -- round_timeout bounds rendezvous rounds, not server startup: start()'s own default bounds the native-store spawn probe
        server = NativeStoreServer(
            host=host, port=port, journal=journal,
            journal_strip_prefixes=[K_SHUTDOWN],
        ).start()
        if journal and server.replayed_keys:
            log.info(
                "control-plane state restored from %s (%d keys) by the "
                "native store: cycle numbering and rendezvous rounds "
                "continue", journal, server.replayed_keys,
            )
    else:
        # rounds/cycle numbering must survive a control-plane restart, but
        # job-terminal state must not: a replayed shutdown flag (+ acks)
        # would terminate the next job, so it is stripped during replay —
        # BEFORE the listener opens (an agent connecting in a post-listen
        # cleanup window could read the stale flag and self-terminate)
        server = StoreServer(
            host=host, port=port, journal_path=journal,
            journal_strip_prefixes=[K_SHUTDOWN.encode()],
        ).start_in_thread()
        if journal and server.replayed_keys:
            log.info(
                "control-plane state restored from %s (%d keys): cycle "
                "numbering and rendezvous rounds continue",
                journal, server.replayed_keys,
            )
    client = StoreClient("127.0.0.1", server.port, timeout=round_timeout)
    rdzv = RendezvousHost(
        client, min_nodes=min_nodes, max_nodes=max_nodes,
        settle_time=settle_time, require_equal_slots=require_equal_slots,
    )
    loop = HostRoundLoop(rdzv, round_timeout)
    loop.start()
    log.info(
        "control plane up on %s:%s (min_nodes=%s max_nodes=%s)",
        host, server.port, min_nodes, max_nodes,
    )
    stop = {"flag": False}

    def _sig(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            shutdown = client.try_get(K_SHUTDOWN)
            if shutdown is not None:
                log.info("workload shut down: %s", shutdown.decode())
                # linger so late agents can observe the flag
                time.sleep(5.0)
                return 0 if shutdown == b"success" else 1
            time.sleep(0.5)
        return 0
    finally:
        loop.stop()
        server.stop()


def _run_sharded(
    host: str,
    port: int,
    min_nodes: int,
    max_nodes: int | None,
    round_timeout: float,
    settle_time: float,
    journal: str | None,
    require_equal_slots: bool,
    shards: int,
) -> int:
    """Host K store shards (consistent-hash keyspace, per-shard journal) +
    the rendezvous round loop.  Shard 0 binds the advertised ``port`` — the
    rendezvous bootstrap seed — and the shard map is published there, so
    agents may either set ``TPURX_STORE_SHARDS`` to the logged endpoint
    list or call ``ShardedStoreClient.from_bootstrap(addr, port)`` knowing
    only the seed.  Per-shard journals keep every shard independently
    journal-replayable: one shard dying mid-restart is a reconnect, not a
    control-plane loss."""
    from ..store.server import StoreServer
    from ..store.sharding import ShardMap, ShardedStoreClient, publish_shard_map

    servers = []
    for i in range(shards):
        # deterministic ports (seed+i): the failover contract is same-
        # endpoint replacement, so a restarted control plane must re-bind
        # the SAME ports for live clients to reconnect to their shards
        servers.append(
            StoreServer(
                host=host,
                port=port + i,
                journal_path=f"{journal}.shard{i}" if journal else None,
                journal_strip_prefixes=[K_SHUTDOWN.encode()],
            ).start_in_thread()
        )
    endpoints = [f"127.0.0.1:{s.port}" for s in servers]
    seed = StoreClient("127.0.0.1", servers[0].port)
    publish_shard_map(seed, ShardMap(endpoints))
    seed.close()
    restored = sum(s.replayed_keys for s in servers)
    if journal and restored:
        log.info(
            "control-plane state restored across %d shard journals "
            "(%d keys): cycle numbering and rendezvous rounds continue",
            shards, restored,
        )
    client = ShardedStoreClient(endpoints, timeout=round_timeout)
    rdzv = RendezvousHost(
        client, min_nodes=min_nodes, max_nodes=max_nodes,
        settle_time=settle_time, require_equal_slots=require_equal_slots,
    )
    loop = HostRoundLoop(rdzv, round_timeout)
    loop.start()
    log.info(
        "sharded control plane up: %d shards on %s (seed %s:%s) — set "
        "TPURX_STORE_SHARDS=%s",
        shards, host, host, servers[0].port, ",".join(endpoints),
    )
    stop = {"flag": False}

    def _sig(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            shutdown = client.try_get(K_SHUTDOWN)
            if shutdown is not None:
                log.info("workload shut down: %s", shutdown.decode())
                time.sleep(5.0)  # linger so late agents observe the flag
                return 0 if shutdown == b"success" else 1
            time.sleep(0.5)
        return 0
    finally:
        loop.stop()
        for s in servers:
            s.stop()


def main(argv=None) -> None:
    setup_logger()
    p = argparse.ArgumentParser(prog="tpurx-control")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=29500)
    p.add_argument("--min-nodes", type=int, default=1)
    p.add_argument("--max-nodes", type=int, default=None)
    p.add_argument("--round-timeout", type=float, default=600.0)
    p.add_argument("--settle-time", type=float, default=2.0)
    p.add_argument(
        "--native-store", action="store_true",
        help="serve the KV store from the C++ epoll server",
    )
    p.add_argument(
        "--journal", default=None,
        help="journal file: control-plane restarts keep cycle numbering",
    )
    p.add_argument(
        "--allow-heterogeneous", action="store_true",
        help="accept nodes with differing worker counts (mixed slot fleets)",
    )
    p.add_argument(
        "--shards", type=int, default=1,
        help="host this many store shards (consistent-hash keyspace, "
             "per-shard journal); shard 0 binds --port as the bootstrap seed",
    )
    args = p.parse_args(argv)
    sys.exit(
        run(
            args.host, args.port, args.min_nodes, args.max_nodes,
            args.round_timeout, args.settle_time, native=args.native_store,
            journal=args.journal,
            require_equal_slots=not args.allow_heterogeneous,
            shards=args.shards,
        )
    )


if __name__ == "__main__":
    main()
