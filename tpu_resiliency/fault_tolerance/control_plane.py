"""Standalone control-plane process (reference ``control_plane.py:266``,
CLI ``nvrx-control``).

Hosts the KV store + the rendezvous round loop outside any compute node, so
launchers are pure store clients: the control plane survives every compute
node dying, and job-level restarts (new SLURM/GKE job, same control plane)
keep cycle numbering and rendezvous state.

    python -m tpu_resiliency.fault_tolerance.control_plane \
        --port 29500 --min-nodes 2 --max-nodes 4

Launchers then run WITHOUT ``--host-store``, pointing at this endpoint.
"""

from __future__ import annotations

import argparse
import signal
import subprocess
import sys
import threading
import time

from ..store import StoreClient, StoreError, StoreServer
from ..utils.logging import get_logger, setup_logger
from .launcher import HostRoundLoop
from .rendezvous import K_SHUTDOWN, RendezvousHost

log = get_logger("control_plane")


class PolicyClient:
    """Per-rank side of the adaptive policy loop.

    The job-level controller (hosted by smonsvc, or rank 0) publishes each
    decision batch under ``policy/decision/latest``; every rank polls that
    one key and re-applies the published actions locally through the
    actuator — knob reads on this rank then see the controller's values
    via the ``utils/env`` runtime-override layer, with no env mutation
    and no per-rank re-deciding.
    """

    def __init__(self, store, actuator=None, poll_interval_s: float | None = None):
        from ..policy import Actuator
        from ..utils import env

        self.store = store
        self.actuator = actuator or Actuator()
        self.poll_interval_s = (
            env.POLICY_INTERVAL_S.get()
            if poll_interval_s is None
            else float(poll_interval_s)
        )
        self.applied_seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def poll_once(self) -> int:
        """Apply any decision batch newer than the last applied one;
        returns the number of actions applied."""
        from ..policy import K_DECISION_LATEST, decisions_from_json

        raw = self.store.try_get(K_DECISION_LATEST)
        if raw is None:
            return 0
        try:
            seq, actions = decisions_from_json(raw)
        except (ValueError, KeyError) as e:
            log.warning("undecodable policy decision payload: %s", e)
            return 0
        if seq <= self.applied_seq:
            return 0
        for action in actions:
            try:
                self.actuator.apply(action)
            except (KeyError, ValueError) as e:
                # a newer controller may publish knobs this rank's build
                # does not declare — skip them, apply the rest
                log.warning("skipping unappliable policy action %s: %s",
                            action, e)
        self.applied_seq = seq
        log.info(
            "applied policy decision batch seq=%d (%d action(s))",
            seq, len(actions),
        )
        return len(actions)

    def start(self) -> "PolicyClient":
        if self._thread is not None:
            return self

        def _loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.poll_once()
                except StoreError:
                    pass  # store outage: the next poll retries

        self._thread = threading.Thread(
            target=_loop, name="tpurx-policy-client", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def run(
    host: str,
    port: int,
    min_nodes: int,
    max_nodes: int | None,
    round_timeout: float,
    settle_time: float,
    native: bool = False,
    journal: str | None = None,
    require_equal_slots: bool = True,
    shards: int = 1,
    spares: int = 0,
) -> int:
    if shards > 1 or spares > 0:
        return _run_sharded(
            host, port, min_nodes, max_nodes, round_timeout, settle_time,
            journal, require_equal_slots, max(shards, 1), spares,
        )
    if native:
        from ..store.native import NativeStoreServer

        # tpurx: disable=TPURX012 -- round_timeout bounds rendezvous rounds, not server startup: start()'s own default bounds the native-store spawn probe
        server = NativeStoreServer(
            host=host, port=port, journal=journal,
            journal_strip_prefixes=[K_SHUTDOWN],
        ).start()
        if journal and server.replayed_keys:
            log.info(
                "control-plane state restored from %s (%d keys) by the "
                "native store: cycle numbering and rendezvous rounds "
                "continue", journal, server.replayed_keys,
            )
    else:
        # rounds/cycle numbering must survive a control-plane restart, but
        # job-terminal state must not: a replayed shutdown flag (+ acks)
        # would terminate the next job, so it is stripped during replay —
        # BEFORE the listener opens (an agent connecting in a post-listen
        # cleanup window could read the stale flag and self-terminate)
        server = StoreServer(
            host=host, port=port, journal_path=journal,
            journal_strip_prefixes=[K_SHUTDOWN.encode()],
        ).start_in_thread()
        if journal and server.replayed_keys:
            log.info(
                "control-plane state restored from %s (%d keys): cycle "
                "numbering and rendezvous rounds continue",
                journal, server.replayed_keys,
            )
    client = StoreClient("127.0.0.1", server.port, timeout=round_timeout)
    rdzv = RendezvousHost(
        client, min_nodes=min_nodes, max_nodes=max_nodes,
        settle_time=settle_time, require_equal_slots=require_equal_slots,
    )
    loop = HostRoundLoop(rdzv, round_timeout)
    loop.start()
    log.info(
        "control plane up on %s:%s (min_nodes=%s max_nodes=%s)",
        host, server.port, min_nodes, max_nodes,
    )
    stop = {"flag": False}

    def _sig(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop["flag"]:
            shutdown = client.try_get(K_SHUTDOWN)
            if shutdown is not None:
                log.info("workload shut down: %s", shutdown.decode())
                # linger so late agents can observe the flag
                time.sleep(5.0)
                return 0 if shutdown == b"success" else 1
            time.sleep(0.5)
        return 0
    finally:
        loop.stop()
        server.stop()


def _promote_dead_shards(procs, endpoints, spare_ports, journal) -> None:
    """One watchdog sweep: any subprocess shard that exited is replaced by
    a spare on a FRESH endpoint — the spare replays the dead shard's
    journal, then a CAS'd epoch bump on the published map re-points the
    shard index at it (:func:`promote_spare`).  Clients riding a
    ``store_shard_failover`` episode against the dead endpoint re-fetch the
    map and land on the spare; the dead endpoint is never reused."""
    from ..store.sharding import promote_spare, spawn_shard_subprocess

    for i, proc in enumerate(procs):
        if proc is None or proc.poll() is None:
            continue
        rc = proc.returncode
        if not spare_ports:
            log.error(
                "shard %d (%s) died (rc=%s) with no spare endpoints left; "
                "its keyspace is down until the control plane restarts",
                i, endpoints[i], rc,
            )
            procs[i] = None
            continue
        spare_port = spare_ports.pop(0)
        spare_ep = f"127.0.0.1:{spare_port}"
        log.warning(
            "shard %d (%s) died (rc=%s): restoring its journal on spare %s",
            i, endpoints[i], rc, spare_ep,
        )
        procs[i] = spawn_shard_subprocess(
            spare_port,
            journal=f"{journal}.shard{i}" if journal else None,
        )
        # the map key lives on the seed shard (index 0); when the seed
        # itself died, its journal-restored spare now serves that key
        seed_ep = spare_ep if i == 0 else endpoints[0]
        seed_host, seed_port = seed_ep.rsplit(":", 1)
        map_client = StoreClient(seed_host, int(seed_port), timeout=10.0)
        try:
            promote_spare(map_client, i, spare_ep)
        finally:
            map_client.close()
        endpoints[i] = spare_ep


def _run_sharded(
    host: str,
    port: int,
    min_nodes: int,
    max_nodes: int | None,
    round_timeout: float,
    settle_time: float,
    journal: str | None,
    require_equal_slots: bool,
    shards: int,
    spares: int = 0,
) -> int:
    """Host K store shards (consistent-hash keyspace, per-shard journal) +
    the rendezvous round loop.  Shard 0 binds the advertised ``port`` — the
    rendezvous bootstrap seed — and the shard map is published there, so
    agents may either set ``TPURX_STORE_SHARDS`` to the logged endpoint
    list or call ``ShardedStoreClient.from_bootstrap(addr, port)`` knowing
    only the seed.  Per-shard journals keep every shard independently
    journal-replayable: one shard dying mid-restart is a reconnect, not a
    control-plane loss.

    With ``spares > 0`` the shards run as subprocesses (so one can die
    without taking the control plane with it) and a watchdog promotes a
    spare endpoint — fresh port, dead shard's journal — via a CAS'd epoch
    bump on the published map whenever a shard exits."""
    from ..store.server import StoreServer
    from ..store.sharding import (
        ShardMap, ShardedStoreClient, publish_shard_map,
        spawn_shard_subprocess,
    )

    # Deterministic shard ports (seed+i, spares after): a control plane
    # RESTART re-binds the same ports so live clients reconnect in place.
    # A shard dying while the control plane stays up is the other failure
    # mode: with spares configured its keyspace moves to a fresh spare
    # endpoint via a CAS'd epoch bump on the published map — the dead
    # endpoint is never reused, clients re-fetch the map mid-failover.
    servers = []  # in-thread shards (spares == 0)
    procs = []    # subprocess shards (spares > 0): independently killable
    for i in range(shards):
        shard_journal = f"{journal}.shard{i}" if journal else None
        if spares > 0:
            procs.append(
                spawn_shard_subprocess(port + i, journal=shard_journal)
            )
        else:
            servers.append(
                StoreServer(
                    host=host,
                    port=port + i,
                    journal_path=shard_journal,
                    journal_strip_prefixes=[K_SHUTDOWN.encode()],
                ).start_in_thread()
            )
    endpoints = [f"127.0.0.1:{port + i}" for i in range(shards)]
    spare_ports = [port + shards + i for i in range(spares)]
    spare_eps = [f"127.0.0.1:{p}" for p in spare_ports]
    seed = StoreClient("127.0.0.1", port)
    publish_shard_map(seed, ShardMap(endpoints, spares=spare_eps))
    seed.close()
    restored = sum(s.replayed_keys for s in servers)
    if journal and restored:
        log.info(
            "control-plane state restored across %d shard journals "
            "(%d keys): cycle numbering and rendezvous rounds continue",
            shards, restored,
        )
    client = ShardedStoreClient(
        endpoints, timeout=round_timeout, spares=spare_eps,
    )
    rdzv = RendezvousHost(
        client, min_nodes=min_nodes, max_nodes=max_nodes,
        settle_time=settle_time, require_equal_slots=require_equal_slots,
    )
    loop = HostRoundLoop(rdzv, round_timeout)
    loop.start()
    log.info(
        "sharded control plane up: %d shards on %s (seed %s:%s, %d spares) "
        "— set TPURX_STORE_SHARDS=%s",
        shards, host, host, port, spares, ",".join(endpoints),
    )
    stop = {"flag": False}

    def _sig(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    # The watchdog runs on its own thread: the shutdown poll below can sit
    # inside a failover episode for tens of seconds when a shard is down,
    # and promotion must not wait behind it.
    watchdog_stop = threading.Event()

    def _watchdog():
        while not watchdog_stop.wait(0.5):
            try:
                _promote_dead_shards(procs, endpoints, spare_ports, journal)
            except Exception:
                log.exception("shard watchdog sweep failed; retrying")

    watchdog = None
    if procs:
        watchdog = threading.Thread(
            target=_watchdog, name="shard-watchdog", daemon=True,
        )
        watchdog.start()
    try:
        while not stop["flag"]:
            try:
                shutdown = client.try_get(K_SHUTDOWN)
            except StoreError:
                # shard outage mid-poll: the watchdog is promoting a spare;
                # keep the control plane up and poll again
                shutdown = None
            if shutdown is not None:
                log.info("workload shut down: %s", shutdown.decode())
                time.sleep(5.0)  # linger so late agents observe the flag
                return 0 if shutdown == b"success" else 1
            time.sleep(0.5)
        return 0
    finally:
        watchdog_stop.set()
        if watchdog is not None:
            watchdog.join(timeout=5.0)
        loop.stop()
        for s in servers:
            s.stop()
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()


def main(argv=None) -> None:
    setup_logger()
    p = argparse.ArgumentParser(prog="tpurx-control")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=29500)
    p.add_argument("--min-nodes", type=int, default=1)
    p.add_argument("--max-nodes", type=int, default=None)
    p.add_argument("--round-timeout", type=float, default=600.0)
    p.add_argument("--settle-time", type=float, default=2.0)
    p.add_argument(
        "--native-store", action="store_true",
        help="serve the KV store from the C++ epoll server",
    )
    p.add_argument(
        "--journal", default=None,
        help="journal file: control-plane restarts keep cycle numbering",
    )
    p.add_argument(
        "--allow-heterogeneous", action="store_true",
        help="accept nodes with differing worker counts (mixed slot fleets)",
    )
    p.add_argument(
        "--shards", type=int, default=1,
        help="host this many store shards (consistent-hash keyspace, "
             "per-shard journal); shard 0 binds --port as the bootstrap seed",
    )
    p.add_argument(
        "--spares", type=int, default=0,
        help="hold this many spare store endpoints (ports after the shard "
             "range); shards run as subprocesses and a dead shard is "
             "re-pointed to a spare via a CAS'd epoch bump on the shard map",
    )
    args = p.parse_args(argv)
    sys.exit(
        run(
            args.host, args.port, args.min_nodes, args.max_nodes,
            args.round_timeout, args.settle_time, native=args.native_store,
            journal=args.journal,
            require_equal_slots=not args.allow_heterogeneous,
            shards=args.shards,
            spares=args.spares,
        )
    )


if __name__ == "__main__":
    main()
