"""In-job restart ring (reference: ``fault_tolerance/``).

Per-host elastic launcher + barrier rendezvous + per-rank monitor processes
with heartbeat/section hang detection, re-designed for JAX/TPU workloads:
ranks are TPU hosts/chips, the control plane is the tpurx KV store over DCN,
and timeout synchronization uses store max-reduction (device quorum kernel in
``tpu_resiliency.ops`` is the fast path).
"""

from .config import FaultToleranceConfig
from .data import (
    HeartbeatTimeouts,
    RankInfo,
    SectionTimeouts,
    WorkloadAction,
    WorkloadControlRequest,
)
from .rank_monitor_client import RankMonitorClient
from .rank_monitor_server import RankMonitorServer
from .timeouts import TimeoutsCalc

__all__ = [
    "FaultToleranceConfig",
    "RankInfo",
    "HeartbeatTimeouts",
    "SectionTimeouts",
    "WorkloadAction",
    "WorkloadControlRequest",
    "RankMonitorClient",
    "RankMonitorServer",
    "TimeoutsCalc",
]
