"""Training-progress tracker: stop restart loops that make no progress.

Capability parity with ``fault_tolerance/progress_tracker.py:40-212``
(``TrainingProgressTracker``): each cycle, read the max training iteration the
workload reached (from an iteration file the workload/checkpointing layer
maintains); if ``max_no_progress_cycles`` consecutive cycles end without the
iteration advancing, tell the launcher to terminate early instead of burning
the allocation on a crash loop.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.logging import get_logger

log = get_logger("progress_tracker")


class TrainingProgressTracker:
    def __init__(
        self,
        iteration_file: Optional[str] = None,
        max_no_progress_cycles: int = 3,
    ):
        self.iteration_file = iteration_file
        self.max_no_progress_cycles = max_no_progress_cycles
        self.best_iteration: Optional[int] = None
        self.no_progress_cycles = 0

    def read_current_iteration(self) -> Optional[int]:
        if not self.iteration_file or not os.path.exists(self.iteration_file):
            return None
        try:
            with open(self.iteration_file) as f:
                return int(f.read().strip() or "0")
        except (OSError, ValueError):
            log.warning("unreadable iteration file %s", self.iteration_file)
            return None

    def analyze_previous_cycle(self) -> bool:
        """Called by the launcher right before deciding a restart.
        Returns True if the previous cycle made progress."""
        current = self.read_current_iteration()
        if current is None:
            # no signal — count as no-progress only if tracking is possible
            if self.iteration_file:
                self.no_progress_cycles += 1
            return False
        if self.best_iteration is None or current > self.best_iteration:
            self.best_iteration = current
            self.no_progress_cycles = 0
            return True
        self.no_progress_cycles += 1
        log.warning(
            "no training progress in previous cycle (iteration stuck at %s, %s/%s)",
            current, self.no_progress_cycles, self.max_no_progress_cycles,
        )
        return False

    def should_terminate_early(self) -> bool:
        return (
            self.max_no_progress_cycles > 0
            and self.no_progress_cycles >= self.max_no_progress_cycles
        )


def write_progress_iteration(path: str, iteration: int) -> None:
    """Workload-side helper: atomically record the reached iteration."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(int(iteration)))
    os.replace(tmp, path)
