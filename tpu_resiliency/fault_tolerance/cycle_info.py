"""Per-cycle info JSON for external consumers.

Capability parity with ``fault_tolerance/cycle_info_writer.py`` (427 LoC):
the store-hosting agent writes one JSON document per restart cycle —
participants, spares, failure that ended the previous cycle, timestamps —
plus a ``cycle_info.<job>.current`` symlink external tooling (job monitors,
attribution services) tails without knowing cycle numbers.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import get_logger

log = get_logger("cycle_info")


class CycleInfoReporter:
    def __init__(self, out_dir: str, job_name: str = "job"):
        self.out_dir = out_dir
        self.job_name = job_name
        os.makedirs(out_dir, exist_ok=True)
        self._current: Optional[Dict[str, Any]] = None

    def _path(self, cycle: int) -> str:
        return os.path.join(self.out_dir, f"cycle_info.{self.job_name}.{cycle}.json")

    def start_cycle(
        self,
        cycle: int,
        round_num: int,
        participants: List[str],
        standby: List[str],
        global_world_size: int,
    ) -> None:
        self._current = {
            "job": self.job_name,
            "cycle": cycle,
            "round": round_num,
            "started_at": time.time(),
            "participants": participants,
            "standby": standby,
            "global_world_size": global_world_size,
            "ended_at": None,
            "end_reason": None,
            "failed_ranks": [],
        }
        self._write(cycle)

    def end_cycle(self, reason: str, failed_ranks: Optional[List[int]] = None) -> None:
        if self._current is None:
            return
        self._current["ended_at"] = time.time()
        self._current["end_reason"] = reason
        self._current["failed_ranks"] = failed_ranks or []
        self._write(self._current["cycle"])

    def _write(self, cycle: int) -> None:
        path = self._path(cycle)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._current, f, indent=2)
        os.replace(tmp, path)
        current = os.path.join(self.out_dir, f"cycle_info.{self.job_name}.current")
        tmp_link = current + ".tmp"
        try:
            if os.path.lexists(tmp_link):
                os.unlink(tmp_link)
            os.symlink(os.path.basename(path), tmp_link)
            os.replace(tmp_link, current)
        except OSError:
            log.warning("could not update current cycle symlink")
