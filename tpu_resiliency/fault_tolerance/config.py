"""Fault tolerance configuration.

Capability parity with ``fault_tolerance/config.py:27-396``
(``FaultToleranceConfig``): heartbeat/section timeouts, health-check toggles,
restart policy, progress tracking — merged from dataclass defaults, a YAML
section, and CLI/env overrides (in that order of precedence, lowest first).

TPU-specific fields replace CUDA ones: no GPU-memory-reclaim wait (XLA owns
HBM per-process; freeing is process exit), instead a device-availability
probe; NUMA binding kept (TPU hosts are NUMA machines too).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional

import yaml

from ..utils import env as _env


@dataclasses.dataclass
class FaultToleranceConfig:
    # --- heartbeat hang detection ---
    initial_rank_heartbeat_timeout: Optional[float] = 60.0 * 60
    rank_heartbeat_timeout: Optional[float] = 45.0 * 60
    workload_check_interval: float = 1.0
    safety_factor: float = 5.0
    # --- section hang detection ---
    rank_section_timeouts: Dict[str, Optional[float]] = dataclasses.field(
        default_factory=dict
    )
    rank_out_of_section_timeout: Optional[float] = None
    # fast path: do not wait for monitor ACK on section/heartbeat messages
    skip_section_response: bool = True
    # --- restart policy ---
    max_rank_restarts: int = 0  # in-job worker restarts before giving up (0 = unlimited)
    max_no_progress_cycles: int = 3
    term_signal: str = "SIGKILL"
    workers_stop_timeout: float = 15.0
    # graceful signal sent to worker process groups before the KILL sweep
    # (reference --term-timeout/--kill-signal operator surface)
    worker_stop_signal: str = "SIGTERM"
    # "any-failed": one non-zero worker exit fails the cycle (default).
    # "min-healthy": the cycle fails only when fewer than
    # min_healthy_workers local workers are still healthy (running or
    # exited 0) — tolerates loss of non-collective sidecar workers.
    restart_policy: str = "any-failed"
    min_healthy_workers: int = -1  # min-healthy policy: -1 = all workers
    # bind worker i to NUMA node (i * nodes // nproc) via numactl when available
    numa_binding: bool = False
    # --- rendezvous ---
    rdzv_round_timeout: float = 600.0
    # how long an agent keeps retrying a vanished store before giving up —
    # must exceed a control-plane restart (--journal re-hosts state) or the
    # fleet is gone by the time the restored store comes back
    store_rejoin_window: float = 180.0
    min_nodes: int = 1
    max_nodes: Optional[int] = None
    node_group_key: Optional[str] = None  # TPU slice/ICI-domain segment constraint
    # False: allow heterogeneous worker counts per node (e.g. a v5e-4 host
    # joining a fleet of v5e-8s) — global ranks are offset by each node's
    # actual slot count
    require_equal_slots: bool = True
    # --- health checks ---
    enable_device_health_check: bool = True
    enable_storage_health_check: bool = False
    storage_health_check_path: Optional[str] = None
    # --- monitor-hosted periodic health loop (passive checks only;
    #     reference hosts GPU/NIC loops in the watchdog,
    #     rank_monitor_server.py:122) ---
    monitor_health_check_interval: float = 0.0  # seconds; 0 disables
    monitor_health_checks: str = (
        "node_resources,nic_link,tpu_sys,kernel_log,counter_window,node_daemon"
    )
    # kernel log source override: "auto" | "kmsg" | "dmesg" | a file path
    monitor_health_kernel_log: Optional[str] = None
    # --- progress tracking ---
    enable_progress_tracking: bool = True
    progress_iteration_file: Optional[str] = None
    # --- attribution gate (restart decisions consult the log analyzer) ---
    enable_attribution_gate: bool = False
    # "inline": gate runs the in-process analyzer; "spawn": the store-hosting
    # launcher spawns attrsvc, publishes its endpoint in the store, monitors
    # and restarts it; "external": operator-run service at
    # attribution_service_url (gate falls back inline when unhealthy)
    attribution_service_mode: str = "inline"
    attribution_service_url: Optional[str] = None
    # --- logging / observability ---
    log_level: str = "INFO"
    per_cycle_log_dir: Optional[str] = None
    cycle_info_dir: Optional[str] = None
    profiling_file: Optional[str] = None
    # --- timeouts persistence ---
    state_dict_path: Optional[str] = None

    ENV_PREFIX = _env.FT_OVERRIDES.prefix

    @classmethod
    def field_names(cls) -> list[str]:
        return [f.name for f in dataclasses.fields(cls)]

    @classmethod
    def from_yaml(cls, path: str, section: str = "fault_tolerance") -> "FaultToleranceConfig":
        """Load from a YAML file; searches for the `section` key at any top level
        (the reference discovers its section inside arbitrary trainer configs,
        ``config.py:186-240``)."""
        with open(path) as f:
            tree = yaml.safe_load(f) or {}
        found = _find_section(tree, section)
        if found is None:
            raise ValueError(f"section {section!r} not found in {path}")
        return cls.from_dict(found)

    @classmethod
    def from_dict(cls, values: Mapping[str, Any]) -> "FaultToleranceConfig":
        known = {k: v for k, v in values.items() if k in cls.field_names()}
        unknown = set(values) - set(known)
        if unknown:
            raise ValueError(f"unknown fault_tolerance config keys: {sorted(unknown)}")
        return cls(**known)

    def merged_with(
        self, overrides: Mapping[str, Any], allow_none: bool = False
    ) -> "FaultToleranceConfig":
        """Apply overrides.  With ``allow_none=False`` (CLI defaults path) a
        None value means "not provided" and is skipped; with ``allow_none=True``
        (env path, where the key's very presence is the override) None is an
        explicit value — e.g. TPURX_FT_RANK_HEARTBEAT_TIMEOUT=null disables
        that timeout."""
        vals = dataclasses.asdict(self)
        for k, v in overrides.items():
            if v is None and not allow_none:
                continue
            if k not in vals:
                raise ValueError(f"unknown fault_tolerance config key: {k}")
            vals[k] = v
        return FaultToleranceConfig(**vals)

    def merged_with_env(self) -> "FaultToleranceConfig":
        """TPURX_FT_<UPPER_FIELD> env overrides (highest precedence)."""
        overrides: Dict[str, Any] = {}
        for f in dataclasses.fields(self):
            env_val = _env.FT_OVERRIDES.raw(f.name)
            if env_val is None:
                continue
            overrides[f.name] = _coerce(env_val, f.type)
        return self.merged_with(overrides, allow_none=True)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _find_section(tree: Any, section: str) -> Optional[Mapping[str, Any]]:
    if isinstance(tree, Mapping):
        if section in tree and isinstance(tree[section], Mapping):
            return tree[section]
        for v in tree.values():
            found = _find_section(v, section)
            if found is not None:
                return found
    return None


def _coerce(value: str, type_hint: Any) -> Any:
    hint = str(type_hint)
    lowered = value.strip().lower()
    if lowered in ("null", "none", ""):
        return None
    if "Dict" in hint or "dict" in hint:
        return yaml.safe_load(value)
    if "bool" in hint:
        return lowered in ("1", "true", "yes", "on")
    if "int" in hint and "float" not in hint:
        return int(value)
    if "float" in hint:
        return float(value)
    return value
