"""IPC message model for rank ↔ monitor ↔ launcher communication.

Capability parity with ``fault_tolerance/data.py`` (RankInfo,
Heartbeat/SectionTimeouts, message dataclasses, WorkloadAction/
WorkloadControlRequest).  Messages serialize to JSON (not pickle): the
channel crosses a process boundary only on the same host, but JSON keeps the
protocol language-neutral for native monitor implementations.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import math
from typing import Any, Dict, Optional

from ..utils import env as _env


@dataclasses.dataclass
class RankInfo:
    global_rank: int
    local_rank: int
    host: str = ""
    pid: int = 0

    @classmethod
    def from_env(cls) -> "RankInfo":
        import os
        import socket

        return cls(
            global_rank=_env.RANK.get(),
            local_rank=_env.LOCAL_RANK.get(),
            host=socket.gethostname(),
            pid=os.getpid(),
        )


@dataclasses.dataclass
class HeartbeatTimeouts:
    """Initial (first heartbeat after start) and subsequent heartbeat timeouts.

    ``were_calculated`` marks values derived from observed intervals rather
    than configured (reference ``data.py:73-98``)."""

    initial: Optional[float] = None
    subsequent: Optional[float] = None
    were_calculated: bool = False

    @property
    def are_valid(self) -> bool:
        return self.initial is not None and self.subsequent is not None


@dataclasses.dataclass
class SectionTimeouts:
    """Per-section timeouts + the out-of-section gap timeout.

    ``calculated_sections`` lists section names whose timeouts are observed,
    not configured (reference ``data.py:99-140``)."""

    section: Dict[str, Optional[float]] = dataclasses.field(default_factory=dict)
    out_of_section: Optional[float] = None
    calculated_sections: tuple = ()
    calculated_out_of_section: bool = False

    def is_valid_for(self, name: str) -> bool:
        return self.section.get(name) is not None


class MsgType(str, enum.Enum):
    INIT = "init"
    HEARTBEAT = "heartbeat"
    SECTION_START = "section_start"
    SECTION_END = "section_end"
    UPDATE_TIMEOUTS = "update_timeouts"
    OK = "ok"
    ERROR = "error"


class WorkloadAction(str, enum.Enum):
    Continue = "continue"
    ExcludeThisNode = "exclude_this_node"
    ShutdownWorkload = "shutdown_workload"
    # restart the cycle NOW (quorum tripwire / in-workload hang detection)
    # without waiting for the rank-heartbeat timeout to kill the hung rank
    RestartWorkload = "restart_workload"


@dataclasses.dataclass
class WorkloadControlRequest:
    action: WorkloadAction
    reason: str = ""

    def to_json(self) -> str:
        return json.dumps({"action": self.action.value, "reason": self.reason})

    @classmethod
    def from_json(cls, raw: str) -> "WorkloadControlRequest":
        d = json.loads(raw)
        return cls(action=WorkloadAction(d["action"]), reason=d.get("reason", ""))


# --- JSON (de)serialization for the UDS channel -----------------------------

def _none_safe(v: Optional[float]) -> Optional[float]:
    if v is None or (isinstance(v, float) and math.isinf(v)):
        return None
    return v


def encode_msg(msg_type: MsgType, payload: Optional[Dict[str, Any]] = None) -> bytes:
    return json.dumps({"type": msg_type.value, **(payload or {})}).encode()


def decode_msg(raw: bytes) -> Dict[str, Any]:
    return json.loads(raw.decode())


def heartbeat_timeouts_to_dict(t: HeartbeatTimeouts) -> Dict[str, Any]:
    return {
        "initial": _none_safe(t.initial),
        "subsequent": _none_safe(t.subsequent),
        "were_calculated": t.were_calculated,
    }


def heartbeat_timeouts_from_dict(d: Dict[str, Any]) -> HeartbeatTimeouts:
    return HeartbeatTimeouts(
        initial=d.get("initial"),
        subsequent=d.get("subsequent"),
        were_calculated=bool(d.get("were_calculated", False)),
    )


def section_timeouts_to_dict(t: SectionTimeouts) -> Dict[str, Any]:
    return {
        "section": {k: _none_safe(v) for k, v in t.section.items()},
        "out_of_section": _none_safe(t.out_of_section),
        "calculated_sections": list(t.calculated_sections),
        "calculated_out_of_section": t.calculated_out_of_section,
    }


def section_timeouts_from_dict(d: Dict[str, Any]) -> SectionTimeouts:
    return SectionTimeouts(
        section=dict(d.get("section", {})),
        out_of_section=d.get("out_of_section"),
        calculated_sections=tuple(d.get("calculated_sections", ())),
        calculated_out_of_section=bool(d.get("calculated_out_of_section", False)),
    )
