"""Device mesh construction.

The resiliency layer is parallelism-agnostic (like the reference, SURVEY.md
§2.8) but needs topology awareness: the slice structure feeds rendezvous
segment keys, and its own tiny syncs ride the same mesh as the workload.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(
    axis_names: Sequence[str] = ("data", "model"),
    axis_sizes: Optional[Sequence[int]] = None,
    devices=None,
):
    """Build a Mesh over all (or given) devices.

    With ``axis_sizes=None`` the last axis gets 1 and the first absorbs all
    devices.  ``-1`` in axis_sizes means "infer".
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    sizes = list(axis_sizes)
    if sizes.count(-1) > 1:
        raise ValueError("at most one -1 axis")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {sizes} != {n} devices")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(axis_names))


def mesh_axis_sizes(mesh) -> Tuple[int, ...]:
    return tuple(mesh.devices.shape)
