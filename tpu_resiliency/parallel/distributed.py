"""Multi-host JAX bootstrap from launcher-provided env.

The reference's workloads call ``torch.distributed.init_process_group`` from
torchelastic env; the JAX analog is ``jax.distributed.initialize`` with a
coordinator address.  The tpurx launcher already exports rank/world/store
env; this helper derives the coordinator from them so workloads need one
line:

    from tpu_resiliency.parallel import init_distributed
    init_distributed()          # no-op single-process; idempotent

The coordinator runs on the node hosting the KV store (same machine that
already owns the control plane), port = store port + 1 by default, or
``TPURX_JAX_COORDINATOR`` overrides.
"""

from __future__ import annotations

from typing import Optional

from ..utils import env as _env
from ..utils.logging import get_logger

log = get_logger("distributed")

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from tpurx env. Returns True if initialized
    (False for single-process runs where it is unnecessary)."""
    global _initialized
    if _initialized:
        return True
    if num_processes is None:
        num_processes = _env.NNODES.get()
    if process_id is None:
        process_id = _env.GROUP_RANK.get()
    if num_processes <= 1:
        return False
    if coordinator_address is None:
        coordinator_address = _env.JAX_COORDINATOR.get()
    if coordinator_address is None:
        host = _env.STORE_ADDR.get()
        port = _env.STORE_PORT.get() + 1
        coordinator_address = f"{host}:{port}"
    import jax

    log.info(
        "jax.distributed.initialize(%s, num_processes=%s, process_id=%s)",
        coordinator_address, num_processes, process_id,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True
