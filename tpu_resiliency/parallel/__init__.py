"""Mesh and collective helpers used by the resiliency layer and workloads.

The collective surface is the self-healing wrapper layer
(``docs/collectives.md``): :class:`ResilientCollective` deadlines,
telemeters, and degrades every resiliency-layer collective; raw
``multihost_utils``/``lax.p*`` calls outside this package are banned by
lint rule TPURX014.
"""

from .mesh import make_mesh, mesh_axis_sizes
from .collectives import (
    ResilientCollective,
    build_shift_permute,
    device_max_reduce,
    instrument_dispatch,
    make_timeouts_reduce_fn,
    observe_latency_ns,
    wrap_collective,
)
from .deadline import CollectiveTimeout, DeadlineLane, shared_lane
from .degrade import DegradePolicy
from .health import RouteHealth, health
from .distributed import init_distributed

__all__ = [
    "make_mesh",
    "mesh_axis_sizes",
    "ResilientCollective",
    "CollectiveTimeout",
    "DeadlineLane",
    "DegradePolicy",
    "RouteHealth",
    "build_shift_permute",
    "device_max_reduce",
    "health",
    "instrument_dispatch",
    "make_timeouts_reduce_fn",
    "observe_latency_ns",
    "shared_lane",
    "wrap_collective",
    "init_distributed",
]
