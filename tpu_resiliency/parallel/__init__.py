"""Mesh and collective helpers used by the resiliency layer and workloads."""

from .mesh import make_mesh, mesh_axis_sizes
from .collectives import device_max_reduce, make_timeouts_reduce_fn
from .distributed import init_distributed

__all__ = [
    "make_mesh",
    "mesh_axis_sizes",
    "device_max_reduce",
    "make_timeouts_reduce_fn",
    "init_distributed",
]
