"""Link/route health state for wrapped collectives.

Tracks per-(op, axis) outcome history so the degrade policy can start at
the right rung instead of re-walking the ladder from the top every call:

- a route that just timed out N times in a row has a *suspect* link — the
  next call should not burn N more deadlines re-proving it;
- the at-abort trace-analyzer verdict (``attribution/trace_analyzer.py``)
  is consumed here on the restart path: a machine-readable
  :class:`~tpu_resiliency.attribution.trace_analyzer.DegradeVerdict`
  pre-arms the implicated op's route so the first post-restart call starts
  at the verdict's rung.

State is process-local and advisory: it biases the ladder's starting rung;
it never skips the final fail-fast raise when every rung is exhausted.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Tuple

from ..telemetry import gauge
from ..utils.logging import get_logger

log = get_logger("coll.health")

# consecutive deadline trips after which a route is suspect (the next call
# skips the retry rung: re-trying a known-bad link burns whole deadlines)
SUSPECT_AFTER = 2

_EWMA_ALPHA = 0.2

# the RankRiskModel's route component: worst consecutive-trip pressure
# across this process's routes, normalized so a route reaching suspect is
# 0.5 and saturation needs sustained tripping past it
_SUSPECT_BIAS = gauge(
    "tpurx_route_suspect_bias",
    "Worst consecutive-timeout pressure across this rank's collective "
    "routes, 0-1 (0.5 = a route just crossed the suspect threshold)",
)


@dataclasses.dataclass
class RouteState:
    op: str
    axis: str = ""
    ewma_latency_ns: float = 0.0
    ok_count: int = 0
    timeout_count: int = 0
    consecutive_timeouts: int = 0
    degrade_count: int = 0
    last_action: str = ""
    # rung the next call should start at ("" = ladder top); set by verdict
    # consumption or by consecutive-timeout escalation
    start_rung: str = ""
    start_rung_reason: str = ""

    @property
    def suspect(self) -> bool:
        return self.consecutive_timeouts >= SUSPECT_AFTER or bool(self.start_rung)


class RouteHealth:
    """Registry of per-(op, axis) route states."""

    def __init__(self):
        self._lock = threading.Lock()
        self._routes: Dict[Tuple[str, str], RouteState] = {}

    def route(self, op: str, axis: str = "") -> RouteState:
        with self._lock:
            key = (op, axis)
            st = self._routes.get(key)
            if st is None:
                st = self._routes[key] = RouteState(op=op, axis=axis)
            return st

    def _bias_locked(self) -> float:
        worst = max(
            (st.consecutive_timeouts for st in self._routes.values()),
            default=0,
        )
        return min(1.0, worst / float(2 * SUSPECT_AFTER))

    def note_ok(self, op: str, axis: str, latency_ns: int) -> None:
        st = self.route(op, axis)
        with self._lock:
            st.ok_count += 1
            st.consecutive_timeouts = 0
            if st.ewma_latency_ns <= 0:
                st.ewma_latency_ns = float(latency_ns)
            else:
                st.ewma_latency_ns += _EWMA_ALPHA * (
                    latency_ns - st.ewma_latency_ns
                )
            bias = self._bias_locked()
        _SUSPECT_BIAS.set(bias)

    def note_timeout(self, op: str, axis: str) -> None:
        st = self.route(op, axis)
        with self._lock:
            st.timeout_count += 1
            st.consecutive_timeouts += 1
            bias = self._bias_locked()
        _SUSPECT_BIAS.set(bias)

    def note_degrade(self, op: str, axis: str, action: str) -> None:
        st = self.route(op, axis)
        with self._lock:
            st.degrade_count += 1
            st.last_action = action

    def note_recovered(self, op: str, axis: str, action: str) -> None:
        """A degrade rung completed the op: the route is serviceable via
        ``action`` — remember it as the starting rung so the next call does
        not re-walk the dead rungs above it."""
        st = self.route(op, axis)
        with self._lock:
            st.consecutive_timeouts = 0
            st.last_action = action
            if action not in ("", "retry"):
                st.start_rung = action
                st.start_rung_reason = "recovered via this rung"
            bias = self._bias_locked()
        _SUSPECT_BIAS.set(bias)

    def start_rung(self, op: str, axis: str = "") -> str:
        """Rung the ladder should start at for this route ('' = top)."""
        st = self.route(op, axis)
        with self._lock:
            if st.start_rung:
                return st.start_rung
            if st.consecutive_timeouts >= SUSPECT_AFTER:
                return "relayout"
            return ""

    def clear_route(self, op: str, axis: str = "") -> None:
        """Forget a route's bias (a re-init/relayout built a new topology)."""
        st = self.route(op, axis)
        with self._lock:
            st.start_rung = ""
            st.start_rung_reason = ""
            st.consecutive_timeouts = 0
            bias = self._bias_locked()
        _SUSPECT_BIAS.set(bias)

    def apply_verdict(self, verdict) -> None:
        """Consume a trace-analyzer :class:`DegradeVerdict` on the restart
        path: pre-arm the implicated op's route at the verdict's rung."""
        action = getattr(verdict, "action", "none")
        op = getattr(verdict, "op", "") or ""
        if action in ("none", "") or not op:
            return
        st = self.route(op, getattr(verdict, "axis", "") or "")
        with self._lock:
            st.start_rung = action if action != "retry" else ""
            st.start_rung_reason = getattr(verdict, "reason", "") or "verdict"
        log.warning(
            "degrade verdict armed: op=%s axis=%s start_rung=%s (%s)",
            op, st.axis, st.start_rung, st.start_rung_reason,
        )

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                f"{op}@{axis or '-'}": dataclasses.asdict(st)
                for (op, axis), st in self._routes.items()
            }


_health: Optional[RouteHealth] = None
_health_lock = threading.Lock()


def health() -> RouteHealth:
    global _health
    with _health_lock:
        if _health is None:
            _health = RouteHealth()
        return _health


def _reset_health_for_tests() -> None:
    global _health
    with _health_lock:
        _health = None
    _SUSPECT_BIAS.set(0.0)
