"""On-device collective helpers for the resiliency layer's tiny syncs.

The reference all-reduces timeout stats over NCCL/Gloo
(``fault_tolerance/timeouts_calc.py:74-91``).  The TPU fast path gathers each
process's host-side stats through one tiny device all-gather over ICI/DCN
(``multihost_utils.process_allgather`` — a (nproc, k) float32 array, one
collective, microseconds) and reduces on host.  It composes with the DCN
store path (used when ranks hold no devices or the mesh is down).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def device_max_reduce(values: List[float]) -> List[float]:
    """Element-wise max of each process's value vector, via one device
    all-gather.  Must be called by every process (collective)."""
    from jax.experimental import multihost_utils

    x = np.asarray(values, dtype=np.float32)
    gathered = multihost_utils.process_allgather(x)  # (nproc, k) or (k,)
    gathered = np.atleast_2d(gathered)
    return [float(v) for v in gathered.max(axis=0)]


def make_timeouts_reduce_fn():
    """Adapter for :meth:`TimeoutsCalc.synchronize_all`'s ``reduce_fn``:
    takes/returns the {stat_key: value} dict, reducing values on device.

    Keys must match across processes (guaranteed when ranks run the same
    section schedule; for divergent section sets use the store path)."""

    def reduce_fn(vals: Dict[str, float]) -> Dict[str, float]:
        keys = sorted(vals)
        merged = device_max_reduce([vals[k] for k in keys])
        return dict(zip(keys, merged))

    return reduce_fn
