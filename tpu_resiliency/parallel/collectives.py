"""Self-healing collectives: the resiliency layer's wrapped collective API.

Every resiliency-layer collective (the timeout-stats all-gather, the fused
quorum readback in ``ops/quorum.py``, ici replication's ppermute shifts,
``TimeoutsCalc.synchronize_all``'s device path) runs through
:class:`ResilientCollective`, which makes the op itself the resiliency
boundary (PAPERS.md: "An Efficient, Reliable and Observable Collective
Communication Library…", "Reliable and Resilient Collective Communication
Library for LLM Training and Serving"):

1. **deadline** — the op executes on a :class:`~.deadline.DeadlineLane`
   whose futex/event :class:`~tpu_resiliency.ops.quorum.StampTripwire`
   watches the budget; exceeding it raises a typed
   :class:`~.deadline.CollectiveTimeout` naming the op and implicated mesh
   axis instead of wedging the host thread;
2. **telemetry** — per-op latency keyed by the DispatchTail program
   identity (``record_dispatch`` stamps every wrapped op, so the at-abort
   fingerprint and the live histograms share one op vocabulary):
   ``tpurx_collective_latency_ns{op,axis}``,
   ``tpurx_collective_timeouts_total{op}``,
   ``tpurx_collective_degrades_total{op,action}``;
3. **degrade** — an ordered policy ladder (``parallel/degrade.py``):
   bounded retry → re-layout onto a fallback lane → targeted
   mesh-shrink through the abort ladder's
   :class:`~tpu_resiliency.inprocess.abort.DegradeToShrink` hook.  A single
   bad link costs one collective's deadline plus a local re-layout, not a
   pod-wide restart.

:func:`instrument_dispatch` / :func:`observe_latency_ns` are the single
instrumentation choke point — ``straggler.OpCollector.wrap`` routes its
dispatch stamps and completion latencies through the same two helpers, so
every instrumented op (collective or not) lands in one vocabulary.

See ``docs/collectives.md`` for the wrapper API and fault matrix.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..inprocess.fingerprint import record_dispatch
from ..telemetry import counter, flight, histogram
from ..utils import env
from ..utils.logging import get_logger
from ..utils.retry import RetryExhausted
from .deadline import CollectiveTimeout, DeadlineLane, shared_lane
from .degrade import (
    RELAYOUT,
    RETRY,
    SHRINK,
    DegradePolicy,
    default_relayout,
    trip_shrink,
)
from .health import health

log = get_logger("coll")

# -- telemetry (single declaration site for the collective plane) -----------

_LATENCY_NS = histogram(
    "tpurx_collective_latency_ns",
    "Dispatch-to-settle latency of instrumented collectives, keyed by the "
    "DispatchTail op identity",
    labels=("op", "axis"),
)
_TIMEOUTS = counter(
    "tpurx_collective_timeouts_total",
    "Wrapped collectives that exceeded their deadline budget",
    labels=("op",),
)
_DEGRADES = counter(
    "tpurx_collective_degrades_total",
    "Degrade-ladder rungs taken by wrapped collectives",
    labels=("op", "action"),
)

# flight-recorder events: trace.py pairs dispatch/settle into spans keyed
# on (op, axis)
EV_DISPATCH = flight.declare_event(
    "collective.dispatch", "op", "axis", "deadline_ms", "lane"
)
EV_SETTLE = flight.declare_event("collective.settle", "op", "axis", "status")


# -- instrumentation choke point --------------------------------------------


def instrument_dispatch(op: str) -> int:
    """Stamp ``op`` into the rank's dispatch tail (the at-abort fingerprint
    feed) and return the ns start stamp for :func:`observe_latency_ns` —
    the ONE dispatch-side instrumentation path (straggler's
    ``OpCollector.wrap`` routes through here too)."""
    record_dispatch(op)
    return time.monotonic_ns()


def observe_latency_ns(op: str, elapsed_ns: int, axis: str = "") -> None:
    """Completion-side half of the choke point: one latency histogram,
    op names shared with the fingerprint vocabulary."""
    _LATENCY_NS.labels(op, axis).observe(elapsed_ns)


# -- soak fault arming (link_degrade campaign) ------------------------------

_FAULT_CLASS = "coll_stall"


def _stall_armed() -> bool:
    """``TPURX_FAULT=coll_stall`` (+ rank filter): this rank's *primary*
    collective lane stalls past its deadline — a wedged/degraded link.
    Fallback lanes stay healthy, so the degrade ladder can prove the
    retry → re-layout path end to end (soak class ``link_degrade``)."""
    spec = env.FAULT.get() or ""
    if spec.split(":", 1)[0] != _FAULT_CLASS:
        return False
    ranks = env.FAULT_RANKS.get()
    if ranks:
        rank = env.RANK.get()
        return rank is not None and int(rank) in {
            int(r) for r in str(ranks).split(",") if r.strip()
        }
    return True


# -- the wrapper ------------------------------------------------------------


class ResilientCollective:
    """A deadlined, telemetered, degradable collective.

    ``fn`` is the primary lane (the real collective); ``fallback``, when
    given, is the re-layout lane (reduced/alternate mesh, or a host/store
    path) the *relayout* and *shrink* rungs switch to.  Without a fallback
    those rungs re-run the primary after the re-layout prep (cache drop /
    targeted shrink) — a re-trace against the surviving topology.

    ``deadline_ms``/``retries``/``policy`` default to the env knobs
    (``TPURX_COLL_DEADLINE_MS`` / ``TPURX_COLL_RETRIES`` /
    ``TPURX_COLL_DEGRADE``) read at call time, so a soak can re-arm a
    running process.  ``deadline_ms <= 0`` runs inline: no worker handoff,
    no deadline — the zero-overhead opt-out.
    """

    def __init__(
        self,
        op: str,
        fn: Callable[..., Any],
        *,
        axis: str = "",
        fallback: Optional[Callable[..., Any]] = None,
        deadline_ms: Optional[float] = None,
        retries: Optional[int] = None,
        policy: Optional[DegradePolicy] = None,
        lane: Optional[DeadlineLane] = None,
        relayout: Callable[[], str] = default_relayout,
    ):
        self.op = op
        self.fn = fn
        self.axis = axis
        self.fallback = fallback
        self._deadline_ms = deadline_ms
        self._retries = retries
        self._policy = policy
        self._lane = lane
        self.relayout = relayout

    # -- config reads (call-time so knobs re-arm live processes) -----------

    def budget_ms(self) -> float:
        if self._deadline_ms is not None:
            return self._deadline_ms
        return float(env.COLL_DEADLINE_MS.get())

    def policy(self) -> DegradePolicy:
        pol = self._policy or DegradePolicy.from_env()
        if self._retries is not None:
            pol = DegradePolicy(rungs=pol.rungs, retries=self._retries)
        return pol

    def lane(self) -> DeadlineLane:
        return self._lane if self._lane is not None else shared_lane()

    # -- attempt machinery -------------------------------------------------

    def _attempt(self, fn, args, kwargs, budget_ms: float, lane_kind: str):
        t0 = instrument_dispatch(self.op)
        flight.record(EV_DISPATCH, self.op, self.axis, budget_ms, lane_kind)
        stalled = lane_kind == "primary" and _stall_armed()

        def call():
            if stalled:
                # armed link fault: the primary lane wedges past budget
                time.sleep(budget_ms / 1e3 * 2 + 0.1)
            return fn(*args, **kwargs)

        try:
            out = self.lane().run(
                call, op=self.op, axis=self.axis, budget_ms=budget_ms
            )
        except CollectiveTimeout:
            flight.record(EV_SETTLE, self.op, self.axis, "timeout")
            raise
        elapsed = time.monotonic_ns() - t0
        flight.record(EV_SETTLE, self.op, self.axis, "ok")
        observe_latency_ns(self.op, elapsed, self.axis)
        health().note_ok(self.op, self.axis, elapsed)
        return out

    def _note_timeout(self) -> None:
        _TIMEOUTS.labels(self.op).inc()
        health().note_timeout(self.op, self.axis)

    def _degrade_lane(self):
        """(fn, lane_kind) the post-re-layout attempt runs on."""
        if self.fallback is not None:
            return self.fallback, "fallback"
        return self.fn, "primary_relaid"

    # -- the call ----------------------------------------------------------

    def __call__(self, *args, **kwargs):
        budget = self.budget_ms()
        if budget <= 0:
            t0 = instrument_dispatch(self.op)
            flight.record(EV_DISPATCH, self.op, self.axis, 0.0, "inline")
            out = self.fn(*args, **kwargs)
            flight.record(EV_SETTLE, self.op, self.axis, "ok")
            observe_latency_ns(self.op, time.monotonic_ns() - t0, self.axis)
            return out
        pol = self.policy()
        start = health().start_rung(self.op, self.axis)
        last: Optional[CollectiveTimeout] = None
        if not start:
            try:
                return self._attempt(self.fn, args, kwargs, budget, "primary")
            except CollectiveTimeout as exc:
                last = exc
                self._note_timeout()
            rungs = pol.rungs
        else:
            # health bias (consecutive trips, or a consumed at-abort degrade
            # verdict): the primary attempt is known-doomed — start the
            # ladder at the armed rung instead of burning its deadline
            log.warning(
                "collective %s@%s: starting at rung '%s' (route bias)",
                self.op, self.axis or "-", start,
            )
            rungs = pol.rungs_from(start)
        for rung in rungs:
            if rung == RETRY:
                r = pol.retrier(self.op)
                while True:
                    try:
                        r.backoff(last)
                    except RetryExhausted:
                        break
                    try:
                        out = self._attempt(
                            self.fn, args, kwargs, budget, "primary"
                        )
                        health().note_recovered(self.op, self.axis, RETRY)
                        return out
                    except CollectiveTimeout as exc:
                        last = exc
                        self._note_timeout()
            elif rung == RELAYOUT:
                _DEGRADES.labels(self.op, RELAYOUT).inc()
                health().note_degrade(self.op, self.axis, RELAYOUT)
                detail = self.relayout()
                fn2, kind = self._degrade_lane()
                log.warning(
                    "collective degrade: op=%s axis=%s action=relayout "
                    "lane=%s (%s)", self.op, self.axis or "-", kind, detail,
                )
                try:
                    out = self._attempt(fn2, args, kwargs, budget * 2, kind)
                    health().note_recovered(self.op, self.axis, RELAYOUT)
                    return out
                except CollectiveTimeout as exc:
                    last = exc
                    self._note_timeout()
            elif rung == SHRINK:
                _DEGRADES.labels(self.op, SHRINK).inc()
                health().note_degrade(self.op, self.axis, SHRINK)
                detail = trip_shrink(self.op, self.axis)
                fn2, kind = self._degrade_lane()
                log.warning(
                    "collective degrade: op=%s axis=%s action=shrink "
                    "lane=%s (%s)", self.op, self.axis or "-", kind, detail,
                )
                try:
                    out = self._attempt(fn2, args, kwargs, budget * 2, kind)
                    health().note_recovered(self.op, self.axis, SHRINK)
                    return out
                except CollectiveTimeout as exc:
                    last = exc
                    self._note_timeout()
        # degrade ladder exhausted: this CollectiveTimeout escapes to the
        # caller — drop the black box while the ring still shows the ladder
        flight.dump("collective_timeout")
        raise last if last is not None else CollectiveTimeout(
            self.op, self.axis, budget
        )


def wrap_collective(fn: Callable[..., Any], op: str, **kw) -> ResilientCollective:
    """Decorator-style construction: ``g = wrap_collective(f, "my_op",
    axis="data")``."""
    return ResilientCollective(op, fn, **kw)


# -- wrapped resiliency-layer collectives -----------------------------------


def _allgather_max(values: List[float]) -> List[float]:
    from jax.experimental import multihost_utils

    x = np.asarray(values, dtype=np.float32)
    gathered = multihost_utils.process_allgather(x)  # (nproc, k) or (k,)
    gathered = np.atleast_2d(gathered)
    return [float(v) for v in gathered.max(axis=0)]


_device_max: Optional[ResilientCollective] = None


def device_max_reduce(values: List[float]) -> List[float]:
    """Element-wise max of each process's value vector, via one device
    all-gather routed through the resilient wrapper.  Must be called by
    every process (collective)."""
    global _device_max
    # finish jax's (idempotent) import on the CALLER thread before the lane
    # dispatch: the deadline lane's worker — or an abandoned late worker
    # racing a fresh one after a trip — must never be jax's first importer
    # (concurrent first-import dies on a partially initialized module)
    from jax.experimental import multihost_utils  # noqa: F401

    if _device_max is None:
        _device_max = ResilientCollective(
            "device_max_reduce", _allgather_max, axis="processes"
        )
    return _device_max(values)


def make_timeouts_reduce_fn():
    """Adapter for :meth:`TimeoutsCalc.synchronize_all`'s ``reduce_fn``:
    takes/returns the {stat_key: value} dict, reducing values on device
    through the wrapped :func:`device_max_reduce` — the call is deadlined
    and degradable like every resiliency-layer collective (a wedged mesh
    raises :class:`CollectiveTimeout` / falls down the degrade ladder
    instead of hanging the sync; the caller's store path stays the
    mesh-free fallback).

    Keys must match across processes (guaranteed when ranks run the same
    section schedule; for divergent section sets use the store path)."""

    def reduce_fn(vals: Dict[str, float]) -> Dict[str, float]:
        keys = sorted(vals)
        merged = device_max_reduce([vals[k] for k in keys])
        return dict(zip(keys, merged))

    return reduce_fn


# -- sanctioned builders for raw collectives --------------------------------


def build_shift_permute(mesh, axis: str, shift: int):
    """The sanctioned ``lax.ppermute`` builder (lint TPURX014 bans raw
    ``lax.p*`` outside this module): a jitted shard_map'd shift of every
    row ``shift`` positions along ``axis``.  Returns ``(jitted, sharding)``
    — callers execute through a :class:`ResilientCollective` so the shift
    is deadlined and telemetered."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_size = mesh.shape[axis]
    perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]

    def body(x):
        import jax as _jax

        return _jax.lax.ppermute(x, axis, perm)

    from ..utils.jax_compat import shard_map as shard_map_compat

    smapped = shard_map_compat(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check=False
    )
    return jax.jit(smapped), NamedSharding(mesh, P(axis))


def _reset_for_tests() -> None:
    from .deadline import _reset_shared_lane_for_tests
    from .health import _reset_health_for_tests

    global _device_max
    _device_max = None
    _reset_shared_lane_for_tests()
    _reset_health_for_tests()
