"""Deadline enforcement for host-blocking collectives.

JAX exposes no collective-abort API and an in-flight XLA program cannot be
cancelled from Python (SURVEY.md §7(a)) — so a deadline here cannot *stop*
the op; it can only stop the op from wedging the **caller**.  The lane model
mirrors the abort ladder's abandoned-worker pattern (``inprocess/abort.py``):

- the wrapped op executes on a reusable worker thread owned by a
  :class:`DeadlineLane`;
- the caller's wait is watched by the repo's event-driven staleness
  machinery — a :class:`~tpu_resiliency.ops.quorum.StampTripwire` in event
  mode parks on the lane's beat event with the op's budget as the wait
  timeout (the same futex/event park as the liveness tripwire: no polling
  sleep, staleness observed at wake latency);
- on trip, the caller is released with a typed
  :class:`CollectiveTimeout` naming the op and the implicated mesh axis,
  and the stuck worker is **abandoned** (its eventual result, if any, is
  discarded; the monitor-kill backstop owns whatever it holds).  A fresh
  worker serves the next submission.

Clock contract: op stamps use the sanctioned ns helpers from
``ops/quorum.py`` (``now_stamp_ns``/``stamp_age_ns``/``clamp_future_ns``)
so deadline ages share the wrap-safe epoch of every other liveness stamp
in the repo (hygiene rule: no raw ``time.time()`` stamps outside quorum).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from ..ops.quorum import (
    StampTripwire,
    clamp_future_ns,
    now_stamp_ns,
    stamp_age_ns,
)
from ..telemetry import counter
from ..utils.logging import get_logger

log = get_logger("coll.deadline")

_ABANDONED = counter(
    "tpurx_collective_workers_abandoned_total",
    "Deadline-lane worker threads abandoned mid-op (op exceeded budget; "
    "the thread is still blocked inside the collective)",
)


class CollectiveTimeout(RuntimeError):
    """A wrapped collective exceeded its deadline budget.

    Typed so the degrade ladder (``parallel/degrade.py``) can catch exactly
    the deadline trip — not arbitrary op failures — and so logs name the op
    and the implicated mesh axis instead of a bare hang.
    """

    def __init__(self, op: str, axis: str, budget_ms: float,
                 age_ms: Optional[float] = None):
        age = f" (age {age_ms:.1f}ms)" if age_ms is not None else ""
        super().__init__(
            f"collective '{op}' exceeded its {budget_ms:.0f}ms deadline "
            f"on mesh axis '{axis or '?'}'{age}"
        )
        self.op = op
        self.axis = axis
        self.budget_ms = budget_ms
        self.age_ms = age_ms


class _Op:
    """One submitted op: fn + completion slot, first-finisher-wins."""

    __slots__ = ("fn", "op", "axis", "budget_ms", "done", "result",
                 "exc", "timed_out", "_lock")

    def __init__(self, fn: Callable[[], Any], op: str, axis: str,
                 budget_ms: float):
        self.fn = fn
        self.op = op
        self.axis = axis
        self.budget_ms = budget_ms
        self.done = threading.Event()
        self.result: Any = None
        self.exc: Optional[BaseException] = None
        self.timed_out = False
        self._lock = threading.Lock()

    def finish(self, *, result=None, exc=None, timed_out=False) -> bool:
        """Settle the op exactly once; returns False if already settled
        (a trip raced the completion — first wins, the loser's outcome is
        discarded)."""
        with self._lock:
            if self.done.is_set():
                return False
            self.result = result
            self.exc = exc
            self.timed_out = timed_out
            self.done.set()
            return True


class DeadlineLane:
    """Reusable deadlined-execution lane: one worker thread + one tripwire.

    One op runs at a time (callers serialize on the lane lock — collectives
    on one mesh are ordered anyway).  The persistent tripwire's budget
    function reads the in-flight op: ``inf`` while idle (chunked re-arm
    waits, the tripwire's suppressed mode), the op's budget while one is in
    flight.  The worker beats the tripwire event on every completion; a
    missing beat past budget IS the detection.

    Worst-case detection latency is ~2x budget when a submission pulse races
    an in-progress wait (the tripwire re-checks true op age on every wake,
    so a fresh op is never tripped early — lateness only, never spurious).
    """

    def __init__(self, name: str = "coll"):
        self.name = name
        self._lock = threading.Lock()          # one op at a time
        self._state = threading.Lock()         # protects _current/_worker
        self._current: Optional[_Op] = None
        self._start_ns = 0
        self._queue: "threading.Condition" = threading.Condition()
        self._pending: Optional[_Op] = None
        self._worker: Optional[threading.Thread] = None
        self._worker_gen = 0
        self.abandoned = 0
        self._beat = threading.Event()
        self._tripwire = StampTripwire(
            on_stale=self._on_stale,
            budget_ms_fn=self._budget_ms,
            event=self._beat,
            age_ns_fn=self._age_ns,
            name=f"tpurx-coll-deadline-{name}",
        ).start()

    # -- tripwire feeds ----------------------------------------------------

    def _budget_ms(self) -> float:
        with self._state:
            op = self._current
        return op.budget_ms if op is not None else float("inf")

    def _age_ns(self) -> int:
        return clamp_future_ns(stamp_age_ns(now_stamp_ns(), self._start_ns))

    def _on_stale(self, age_ms: float) -> None:
        with self._state:
            op = self._current
            if op is None:
                return
            self._current = None
            # the worker is still blocked inside op.fn: abandon it — the
            # next submit spawns a fresh one (abort-ladder pattern)
            self._worker = None
            self._worker_gen += 1
        self.abandoned += 1
        _ABANDONED.inc()
        log.warning(
            "deadline trip: op=%s axis=%s budget=%.0fms age=%.1fms "
            "(worker abandoned)", op.op, op.axis, op.budget_ms, age_ms,
        )
        op.finish(timed_out=True)

    # -- worker ------------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._state:
            if self._worker is not None and self._worker.is_alive():
                return
            gen = self._worker_gen
            self._worker = threading.Thread(
                target=self._worker_loop, args=(gen,),
                name=f"tpurx-coll-worker-{self.name}", daemon=True,
            )
            self._worker.start()

    def _worker_loop(self, gen: int) -> None:
        while True:
            with self._queue:
                while self._pending is None:
                    with self._state:
                        if gen != self._worker_gen:
                            return  # abandoned while idle (lane reset)
                    self._queue.wait(timeout=0.5)
                op, self._pending = self._pending, None
            try:
                result = op.fn()
                exc = None
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                result, exc = None, e
            with self._state:
                stale = gen != self._worker_gen
                if not stale and self._current is op:
                    self._current = None
            if stale:
                # this worker was abandoned mid-op: the caller already got
                # CollectiveTimeout; discard the late outcome and exit
                log.info("abandoned worker finished late op=%s", op.op)
                return
            op.finish(result=result, exc=exc)
            self._beat.set()  # wake the tripwire: fresh

    # -- public ------------------------------------------------------------

    def run(self, fn: Callable[[], Any], *, op: str, axis: str = "",
            budget_ms: float) -> Any:
        """Execute ``fn()`` under ``budget_ms``; raise
        :class:`CollectiveTimeout` if it does not settle in time.

        ``budget_ms <= 0`` runs inline (no deadline, no thread handoff) —
        the zero-overhead opt-out.
        """
        if budget_ms <= 0:
            return fn()
        submitted = _Op(fn, op, axis, budget_ms)
        with self._lock:
            self._ensure_worker()
            with self._state:
                self._start_ns = now_stamp_ns()
                self._current = submitted
            with self._queue:
                self._pending = submitted
                self._queue.notify()
            # pulse the beat so a tripwire parked in its idle/re-arm wait
            # re-reads the budget (now finite) for this op
            self._beat.set()
            # the tripwire is the deadline authority; the local timeout is
            # a generous fail-safe should the watcher thread itself die
            settled = submitted.done.wait(timeout=budget_ms / 1e3 * 2 + 5.0)
            if not settled:
                submitted.finish(timed_out=True)
                with self._state:
                    if self._current is submitted:
                        self._current = None
                        self._worker = None
                        self._worker_gen += 1
                self.abandoned += 1
                _ABANDONED.inc()
        if submitted.timed_out:
            raise CollectiveTimeout(op, axis, budget_ms,
                                    age_ms=self._age_ns() / 1e6)
        if submitted.exc is not None:
            raise submitted.exc
        return submitted.result

    def stop(self) -> None:
        self._tripwire.stop()
        with self._state:
            self._worker_gen += 1
            self._worker = None
        with self._queue:
            self._queue.notify_all()


_shared_lane: Optional[DeadlineLane] = None
_shared_lock = threading.Lock()


def shared_lane() -> DeadlineLane:
    """The process-wide default lane (resiliency-layer collectives are tiny
    and ordered; one lane serializes them exactly as the mesh would)."""
    global _shared_lane
    with _shared_lock:
        if _shared_lane is None:
            _shared_lane = DeadlineLane("shared")
        return _shared_lane


def _reset_shared_lane_for_tests() -> None:
    global _shared_lane
    with _shared_lock:
        if _shared_lane is not None:
            _shared_lane.stop()
        _shared_lane = None
