"""Ordered degrade policy for wrapped collectives.

The Chameleon argument (PAPERS.md) applied to the collective itself: when
an op trips its deadline, recovery is selected from the cheapest viable
tier — not jumped straight to a pod-wide restart.  The ladder, composed
via ``TPURX_COLL_DEGRADE`` (default ``retry,relayout,shrink``):

1. **retry** — bounded re-attempts of the primary lane through
   :class:`~tpu_resiliency.utils.retry.Retrier` (site ``coll_<op>``, full
   jitter; a transient link hiccup costs one backoff, nothing else);
2. **relayout** — drop compiled executables (the measured
   ``mesh_shrink_experiment`` re-init recipe's cache half) and re-run on
   the fallback lane when one is registered (reduced/alternate mesh or a
   host path), else re-trace the primary against the current topology;
3. **shrink** — a *targeted* :class:`ShrinkMeshStage` trip through the
   :func:`~tpu_resiliency.inprocess.abort.get_degrade_hook` installed by
   the in-process wrapper: the implicated rank's mesh is torn down for
   re-init at the surviving size — one rank's re-layout, not a pod-wide
   restart ladder.

A route's health bias (``parallel/health.py``) can start the ladder below
the top — e.g. a consumed at-abort verdict, or a route that already proved
its link dead — so known-bad rungs are not re-walked every call.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

from ..utils import env
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy, Retrier

log = get_logger("coll.degrade")

RETRY = "retry"
RELAYOUT = "relayout"
SHRINK = "shrink"
ACTIONS = (RETRY, RELAYOUT, SHRINK)

# retry rung cadence: deadline trips are already slow (a whole budget each),
# so backoffs stay short — the bound is what matters
_RETRY_RUNG_POLICY = RetryPolicy(base_delay=0.05, max_delay=1.0)


@dataclasses.dataclass(frozen=True)
class DegradePolicy:
    """Parsed ladder composition + retry budget (immutable, per-wrapper)."""

    rungs: Tuple[str, ...] = ACTIONS
    retries: int = 2

    @classmethod
    def from_env(cls) -> "DegradePolicy":
        spec = env.COLL_DEGRADE.get() or ""
        rungs = tuple(
            r for r in (s.strip() for s in spec.split(",")) if r
        )
        bad = [r for r in rungs if r not in ACTIONS]
        if bad:
            log.warning("TPURX_COLL_DEGRADE: unknown rung(s) %s ignored", bad)
            rungs = tuple(r for r in rungs if r in ACTIONS)
        return cls(rungs=rungs, retries=max(0, int(env.COLL_RETRIES.get())))

    def rungs_from(self, start: str) -> Tuple[str, ...]:
        """The ladder from ``start`` down ('' or unknown = full ladder)."""
        if start in self.rungs:
            return self.rungs[self.rungs.index(start):]
        return self.rungs

    def retrier(self, op: str) -> Retrier:
        return Retrier(
            f"coll_{op}",
            _RETRY_RUNG_POLICY.with_(max_attempts=self.retries + 1),
        )


def default_relayout() -> str:
    """The in-process half of the measured re-init recipe
    (``benchmarks/mesh_shrink_experiment.py``): drop compiled executables so
    the re-run re-traces against the current (possibly changed) topology.
    The full teardown — distributed client + backends — is the *shrink*
    rung's job via the abort ladder."""
    try:
        import jax

        jax.clear_caches()
        return "caches cleared"
    except Exception as exc:  # noqa: BLE001 — relayout is best-effort prep
        return f"clear_caches unavailable: {exc!r}"


def trip_shrink(op: str, axis: str, culprits: Tuple[int, ...] = ()) -> str:
    """Fire the targeted-shrink hook installed by the in-process wrapper
    (``inprocess/abort.py``); standalone processes (no wrapper) fall back
    to a one-rung ladder around a bare :class:`ShrinkMeshStage`."""
    from ..inprocess.abort import (
        AbortLadder,
        DegradeToShrink,
        ShrinkMeshStage,
        get_degrade_hook,
    )

    hook: Optional[Callable] = get_degrade_hook()
    if hook is None:
        hook = DegradeToShrink(AbortLadder(ShrinkMeshStage(), name="degrade"))
    return hook(op=op, axis=axis, culprits=tuple(culprits))
