"""Training-loop integrations (reference: ``ptl_resiliency/``).

The reference binds to PyTorch Lightning; JAX has no single dominant loop, so
the integration surface is a small callback protocol (``on_train_start`` /
``on_step_end`` / ``on_checkpoint`` / ``on_train_end``) that drops into any
custom loop, plus prebuilt callbacks mirroring the reference's:

- :class:`FaultToleranceCallback` — heartbeats + calculated timeouts
  (``fault_tolerance_callback.py:169``)
- :class:`FaultToleranceSectionsCallback` — section-based variant
- :class:`StragglerDetectionCallback` — detector lifecycle + report logging
- :class:`LocalCheckpointCallback` — hierarchical local/global save + resume
"""

from .callbacks import (
    Callback,
    CallbackRunner,
    FaultToleranceCallback,
    FaultToleranceSectionsCallback,
    LocalCheckpointCallback,
    StragglerDetectionCallback,
)

__all__ = [
    "Callback",
    "CallbackRunner",
    "FaultToleranceCallback",
    "FaultToleranceSectionsCallback",
    "StragglerDetectionCallback",
    "LocalCheckpointCallback",
]
