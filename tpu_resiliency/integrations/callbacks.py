"""Training-loop callbacks.

Reference analogs live in ``ptl_resiliency/``: heartbeat callback (``:169``),
sections callback, straggler callback, local-checkpoint callback — rebuilt on
a loop-agnostic protocol.  Use:

    runner = CallbackRunner([FaultToleranceCallback(), ...])
    runner.on_train_start(step=start_step)
    for step in range(start_step, total):
        ...
        runner.on_step_end(step=step)
    runner.on_train_end()
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..utils.logging import get_logger

log = get_logger("integrations")


class Callback:
    def on_train_start(self, **ctx) -> None: ...
    def on_step_start(self, **ctx) -> None: ...
    def on_step_end(self, **ctx) -> None: ...
    def on_checkpoint_start(self, **ctx) -> None: ...
    def on_checkpoint_end(self, **ctx) -> None: ...
    def on_train_end(self, **ctx) -> None: ...
    def on_exception(self, **ctx) -> None: ...


class CallbackRunner:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def _fire(self, hook: str, **ctx) -> None:
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(**ctx)
            except Exception:  # noqa: BLE001 - callbacks must not kill training
                log.exception("callback %s.%s failed", type(cb).__name__, hook)

    def __getattr__(self, name: str):
        if name.startswith("on_"):
            return lambda **ctx: self._fire(name, **ctx)
        raise AttributeError(name)


class _TrainingStateMachine:
    """Decides when calculated timeouts may be updated (reference
    ``fault_tolerance_callback.py:45-169``): only after a clean run of
    ``warmup_steps`` steps with no fault in between — otherwise a slow
    faulty epoch would inflate the learned timeouts."""

    def __init__(self, warmup_steps: int = 16):
        self.warmup_steps = warmup_steps
        self.clean_steps = 0
        self.seen_fault = False

    def on_step(self) -> None:
        self.clean_steps += 1

    def on_fault(self) -> None:
        self.seen_fault = True
        self.clean_steps = 0

    @property
    def can_update_timeouts(self) -> bool:
        return self.clean_steps >= self.warmup_steps


class FaultToleranceCallback(Callback):
    """Heartbeat on every step; push calculated timeouts after a clean warmup;
    persist them next to checkpoints so restarts keep learned budgets."""

    def __init__(
        self,
        client=None,
        state_path: Optional[str] = None,
        warmup_steps: int = 16,
        update_interval: int = 64,
    ):
        from ..fault_tolerance import RankMonitorClient

        self.client = client or RankMonitorClient()
        self.state_path = state_path or getattr(
            self.client.cfg, "state_dict_path", None
        )
        self.machine = _TrainingStateMachine(warmup_steps)
        self.update_interval = update_interval
        self._last_update_step = -1

    def on_train_start(self, **ctx) -> None:
        if not self.client.is_initialized:
            if self.state_path and os.path.exists(self.state_path):
                import json

                with open(self.state_path) as f:
                    self.client.load_state_dict(json.load(f))
            self.client.init_workload_monitoring()
        self.client.send_heartbeat()

    def on_step_end(self, step: int = 0, **ctx) -> None:
        self.client.send_heartbeat()
        self.machine.on_step()
        if (
            self.machine.can_update_timeouts
            and step - self._last_update_step >= self.update_interval
        ):
            self._last_update_step = step
            try:
                self.client.calculate_and_set_hb_timeouts()
                self._persist()
            except Exception:  # noqa: BLE001
                log.exception("timeout update failed")

    def on_exception(self, **ctx) -> None:
        self.machine.on_fault()

    def on_train_end(self, **ctx) -> None:
        self._persist()
        self.client.shutdown_workload_monitoring()

    def _persist(self) -> None:
        if not self.state_path:
            return
        import json

        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.client.state_dict(), f)
        os.replace(tmp, self.state_path)


class FaultToleranceSectionsCallback(Callback):
    """Section-based variant: ``setup`` / ``step`` / ``checkpointing``
    sections (reference ``fault_tolerance_sections_callback.py``)."""

    def __init__(self, client=None):
        from ..fault_tolerance import RankMonitorClient

        self.client = client or RankMonitorClient()
        self._in_setup = False

    def on_train_start(self, **ctx) -> None:
        if not self.client.is_initialized:
            self.client.init_workload_monitoring()
        self.client.start_section("setup")
        self._in_setup = True

    def on_step_start(self, **ctx) -> None:
        if self._in_setup:
            self.client.end_section("setup")
            self._in_setup = False
        self.client.start_section("step")

    def on_step_end(self, **ctx) -> None:
        self.client.end_section("step")

    def on_checkpoint_start(self, **ctx) -> None:
        self.client.start_section("checkpointing")

    def on_checkpoint_end(self, **ctx) -> None:
        self.client.end_section("checkpointing")

    def on_train_end(self, **ctx) -> None:
        if self._in_setup:
            self.client.end_section("setup")
        self.client.shutdown_workload_monitoring()


class StragglerDetectionCallback(Callback):
    """Detector lifecycle + report logging (reference
    ``straggler_det_callback.py``)."""

    def __init__(self, detector=None, relative_threshold: float = 0.7, on_straggler=None):
        from ..straggler import Detector

        self.detector = detector or Detector()
        self.relative_threshold = relative_threshold
        self.on_straggler = on_straggler
        self.last_report = None

    def on_train_start(self, **ctx) -> None:
        self.detector.initialize()

    def on_step_start(self, **ctx) -> None:
        self._section = self.detector.detection_section("step")
        self._section.__enter__()

    def on_step_end(self, **ctx) -> None:
        self._section.__exit__(None, None, None)
        report = self.detector.maybe_report()
        if report is not None:
            self.last_report = report
            verdicts = report.identify_stragglers(self.relative_threshold)
            for v in verdicts:
                if v.is_straggler:
                    log.warning(
                        "STRAGGLER: rank %s relative=%.3f individual=%s",
                        v.rank, v.relative_score, v.individual_score,
                    )
                    if self.on_straggler:
                        self.on_straggler(v)

    def on_train_end(self, **ctx) -> None:
        self.detector.shutdown()


class LocalCheckpointCallback(Callback):
    """Hierarchical checkpointing glue (reference
    ``local_checkpoint_callback.py`` + ``HierarchicalCheckpointIO``): save
    node-local every ``local_interval`` steps (fast, replicated), rely on the
    caller's global saves for durability; ``resume()`` prefers the freshest
    fully-covered local checkpoint over the global one."""

    def __init__(self, manager, get_state, local_interval: int = 50,
                 drain_timeout: float = 600.0):
        self.manager = manager
        self.get_state = get_state
        self.local_interval = local_interval
        self.drain_timeout = drain_timeout

    def on_step_end(self, step: int = 0, **ctx) -> None:
        if step > 0 and step % self.local_interval == 0:
            self.manager.save(self.get_state(), iteration=step, is_async=True)

    def on_train_end(self, **ctx) -> None:
        # bounded drain: a wedged background save raises here (naming the
        # save thread) instead of hanging train end forever
        self.manager.wait(timeout=self.drain_timeout)

    def resume(self, template, global_iteration: Optional[int] = None):
        """Returns (tree, iteration, source) — local wins if fresher."""
        local_it = self.manager.find_latest()
        if local_it is not None and (
            global_iteration is None or local_it > global_iteration
        ):
            tree, it = self.manager.load(template, iteration=local_it)
            return tree, it, "local"
        return None, global_iteration, "global"
