"""Always-on per-op straggler collection (the CUPTI-buffers analog, TPU-native).

The reference collects per-kernel durations continuously into native
circular buffers with <1% overhead (``cupti_src/CuptiProfiler.h:39-78``,
``BufferPool.cpp``), so per-op stats are available at every report interval
without a profiling pause.  On TPU the unit the runtime launches is the
compiled XLA *module* (one fused program per jitted step) and there is no
public per-kernel callback API outside the profiler, so the TPU-native
equivalent has three parts:

1. **Always-on dispatch feed** (:meth:`OpCollector.wrap`): every invocation
   of an instrumented jitted callable is timed dispatch→completion WITHOUT
   blocking the training thread — the output array is handed to a
   completion-watcher thread that blocks on readiness and pushes the
   duration into a native ring (the step path pays one enqueue, ~µs).
   Contrast with :class:`~tpu_resiliency.straggler.timers.DeviceTimer`,
   whose ``block_until_ready`` on the hot path serializes host and device.
2. **Native shared-memory rings** (:class:`OpRingArena`,
   ``native/op_ring.c``): constant-memory circular per-op buffers, lock-free
   single-writer, readable at ANY time — including by the rank-monitor
   process attaching from outside while the trainer is wedged (the CUPTI
   property of buffers outliving a hung launch).  Pure-Python fallback when
   no toolchain is present.
3. **Duty-cycled intra-module attribution** (:meth:`OpCollector.wrap` +
   ``profile_interval_s``): once per interval the next instrumented call
   runs under ``jax.profiler.trace``; the dump is parsed OFF-thread
   (``xla_profile.parse_trace_dir``) and per-op durations land in the same
   rings under ``xla:`` names.  Intra-module per-op visibility is
   inherently a profiler operation on TPU; amortized over the interval the
   cost is <<1%.

Lane-filter self-check (VERDICT r2 weak #6): the trace parser's lane
classification tracks the JAX trace format.  On every parsed capture with
events but zero matched ops, a loud error names the installed jax version;
a version pin check warns once when jax moves outside the tested range.
"""

from __future__ import annotations

import collections
import ctypes
import queue
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, Optional

from ..utils import env
from ..utils.logging import get_logger
from ..utils.native import load_native
from ..utils.shm import attach_shm, create_shm
from .timers import DurationStore, SectionStats

log = get_logger("straggler.collector")

_TESTED_JAX_PREFIXES = ("0.9", "0.10")
_version_checked = False


def _check_jax_version() -> None:
    global _version_checked
    if _version_checked or env.SKIP_JAX_LANE_CHECK.get():
        return
    _version_checked = True
    import jax

    if not any(jax.__version__.startswith(p) for p in _TESTED_JAX_PREFIXES):
        log.warning(
            "jax %s is outside the straggler lane filter's tested range %s — "
            "trace lane classification may silently miss ops; verify one "
            "capture and extend _TESTED_JAX_PREFIXES "
            "(TPURX_SKIP_JAX_LANE_CHECK=1 silences this)",
            jax.__version__, _TESTED_JAX_PREFIXES,
        )


class _Stats(ctypes.Structure):
    _fields_ = [
        ("count", ctypes.c_uint64),
        ("drops", ctypes.c_uint64),
        ("window", ctypes.c_uint64),
        ("total", ctypes.c_double),
        ("mean", ctypes.c_double),
        ("median", ctypes.c_double),
        ("min", ctypes.c_double),
        ("max", ctypes.c_double),
        ("stddev", ctypes.c_double),
    ]


def _load_ring_lib():
    lib = load_native(
        "libtpurx-opring.so", "op_ring.c", extra_args=("-lm",),
        required_symbols=(
            "tpurx_ring_arena_size", "tpurx_ring_init", "tpurx_ring_intern",
            "tpurx_ring_push", "tpurx_ring_add_drop", "tpurx_ring_n_ops",
            "tpurx_ring_name", "tpurx_ring_stats",
        ),
    )
    if lib is None:
        return None
    lib.tpurx_ring_arena_size.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
    lib.tpurx_ring_arena_size.restype = ctypes.c_size_t
    lib.tpurx_ring_init.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
    ]
    lib.tpurx_ring_init.restype = ctypes.c_int
    lib.tpurx_ring_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpurx_ring_intern.restype = ctypes.c_int
    lib.tpurx_ring_push.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_float,
    ]
    lib.tpurx_ring_push.restype = None
    lib.tpurx_ring_add_drop.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tpurx_ring_add_drop.restype = None
    lib.tpurx_ring_n_ops.argtypes = [ctypes.c_void_p]
    lib.tpurx_ring_n_ops.restype = ctypes.c_uint64
    lib.tpurx_ring_name.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.tpurx_ring_name.restype = ctypes.c_int
    lib.tpurx_ring_stats.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(_Stats),
    ]
    lib.tpurx_ring_stats.restype = ctypes.c_int
    return lib


class OpRingArena:
    """Native circular per-op duration buffers in shared memory.

    Single writer (the collector's watcher thread); any number of readers,
    in-process or attached from another process by shm name.  Falls back to
    bounded Python deques when the native library can't be built — same API,
    same bounded memory, no cross-process readability.
    """

    def __init__(self, max_ops: int = 256, capacity: int = 1024,
                 _attach_name: Optional[str] = None):
        self.max_ops = max_ops
        self.capacity = capacity
        self._lib = _load_ring_lib()
        # intern races the duty-cycle parse thread against the training
        # thread; the C arena is single-threaded by contract, so serialize
        # here (pushes stay lock-free: single writer per slot)
        self._intern_lock = threading.Lock()
        self._idx: Dict[str, int] = {}
        self._shm = None
        self._fallback: Optional[Dict[str, collections.deque]] = None
        self._fallback_drops: Dict[str, int] = {}
        self._fallback_names: Dict[int, str] = {}  # idx -> name (O(1) push)
        self._closed = False
        self.overflow_drops = 0  # samples for ops beyond max_ops
        if self._lib is None:
            if _attach_name is not None:
                # an attach caller NAMED a real arena; silently handing back
                # an empty fallback would read as "no ops recorded"
                raise RuntimeError(
                    f"cannot attach arena {_attach_name}: native ring "
                    "library unavailable on this host"
                )
            self._fallback = {}
            self.shm_name = None
            return
        if _attach_name is None:
            size = self._lib.tpurx_ring_arena_size(max_ops, capacity)
            self._shm = create_shm(size)
            self._base = ctypes.addressof(
                ctypes.c_char.from_buffer(self._shm.buf)
            )
            self._lib.tpurx_ring_init(self._base, max_ops, capacity)
            self._owner = True
        else:
            self._shm = attach_shm(_attach_name)
            self._base = ctypes.addressof(
                ctypes.c_char.from_buffer(self._shm.buf)
            )
            self._owner = False
        self.shm_name = self._shm.name

    MAGIC = b"1GNIRUPT"  # little-endian u64 0x54505552494e4731 ("TPURING1")

    @classmethod
    def attach(cls, shm_name: str) -> "OpRingArena":
        """Attach read-side from another process (rank monitor post-mortem)."""
        return cls(_attach_name=shm_name)

    @classmethod
    def looks_like_arena(cls, shm_name: str) -> bool:
        """Cheap magic check without constructing an arena — used to pick
        the ring segment out of a process's other shm mappings."""
        try:
            shm = attach_shm(shm_name)
        except (OSError, ValueError):
            return False
        try:
            return bytes(shm.buf[:8]) == cls.MAGIC
        finally:
            try:
                shm.close()
            except BufferError:
                pass

    @property
    def native(self) -> bool:
        return self._lib is not None

    def intern(self, name: str) -> int:
        idx = self._idx.get(name)
        if idx is not None:
            return idx
        if self._closed:
            return -1
        with self._intern_lock:
            idx = self._idx.get(name)
            if idx is not None:
                return idx
            if self._fallback is not None:
                if len(self._fallback) >= self.max_ops:
                    self._idx[name] = -1
                    return -1  # same bounded-by-design contract as native
                idx = len(self._fallback)
                self._fallback[name] = collections.deque(maxlen=self.capacity)
                self._fallback_drops[name] = 0
                self._fallback_names[idx] = name
            else:
                idx = self._lib.tpurx_ring_intern(
                    self._base, name.encode()[: 63]
                )
            if idx < 0:
                # arena full: cache the verdict so later pushes for this
                # name don't rescan all slots in C per sample
                self._idx[name] = -1
                return -1
            self._idx[name] = idx
            return idx

    def push(self, idx_or_name, duration_s: float) -> None:
        if self._closed:
            return
        if isinstance(idx_or_name, str):
            idx_or_name = self.intern(idx_or_name)
        if idx_or_name is None or idx_or_name < 0:
            self.overflow_drops += 1  # arena full: visible, not silent
            return
        if self._fallback is not None:
            name = self._fallback_names.get(idx_or_name)
            if name is not None:
                self._fallback[name].append(duration_s)
            return
        self._lib.tpurx_ring_push(
            self._base, idx_or_name, ctypes.c_float(duration_s)
        )

    def add_drop(self, idx: int) -> None:
        if self._closed or idx is None or idx < 0:
            return
        if self._fallback is not None:
            name = self._fallback_names.get(idx)
            if name is not None:
                self._fallback_drops[name] += 1
            return
        self._lib.tpurx_ring_add_drop(self._base, idx)

    def stats(self) -> Dict[str, SectionStats]:
        """Per-op stats over each ring's current window — non-quiescing:
        the writer keeps pushing while this reads."""
        if self._closed:
            return {}
        if self._fallback is not None:
            return {
                name: SectionStats.from_samples(name, list(buf))
                for name, buf in self._fallback.items()
            }
        out: Dict[str, SectionStats] = {}
        n = int(self._lib.tpurx_ring_n_ops(self._base))
        buf = ctypes.create_string_buffer(64)
        st = _Stats()
        for i in range(n):
            if self._lib.tpurx_ring_name(self._base, i, buf, 64) != 0:
                continue
            if self._lib.tpurx_ring_stats(self._base, i, ctypes.byref(st)) != 0:
                continue
            name = buf.value.decode(errors="replace")
            out[name] = SectionStats(
                name=name, count=int(st.window), total=st.total, avg=st.mean,
                median=st.median, min=st.min, max=st.max, stddev=st.stddev,
            )
        return out

    def drops(self) -> Dict[str, int]:
        if self._closed:
            return {}
        out_extra = (
            {"__overflow__": self.overflow_drops} if self.overflow_drops else {}
        )
        if self._fallback is not None:
            return {**dict(self._fallback_drops), **out_extra}
        out = {}
        n = int(self._lib.tpurx_ring_n_ops(self._base))
        buf = ctypes.create_string_buffer(64)
        st = _Stats()
        for i in range(n):
            if (self._lib.tpurx_ring_name(self._base, i, buf, 64) == 0
                    and self._lib.tpurx_ring_stats(
                        self._base, i, ctypes.byref(st)) == 0):
                out[buf.value.decode(errors="replace")] = int(st.drops)
        return out

    def close(self) -> None:
        self._closed = True
        if self._shm is not None:
            # ctypes from_buffer pins the mmap — drop our pointer first
            self._base = None
            try:
                self._shm.close()
            except BufferError:
                pass  # pinned by an in-flight reader; janitor reaps later
            if getattr(self, "_owner", False):
                from ..utils.shm import unlink_shm

                unlink_shm(self._shm)
            self._shm = None


class CompletionWatcher:
    """Off-thread dispatch→completion timing.

    The training thread enqueues ``(op_idx, t0, output_leaf)`` and moves on;
    this thread blocks on array readiness and pushes ``t_ready - t0`` into
    the arena.  Bounded queue: when dispatch outruns completion checking the
    sample is DROPPED and counted (never backpressure the step).  Holding
    the leaf briefly delays its buffer reuse; the bound caps that too.
    """

    def __init__(self, arena: OpRingArena, maxsize: int = 256):
        self.arena = arena
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # queued + currently-being-fetched samples; queue emptiness alone
        # would declare a flush done while the last fetch is still in flight
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    def start(self) -> "CompletionWatcher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="tpurx-op-watcher", daemon=True
            )
            self._thread.start()
        return self

    def submit(self, op_idx: int, t0: float, leaf, label: str = "") -> None:
        with self._inflight_lock:
            self._inflight += 1
        try:
            self._q.put_nowait((op_idx, t0, leaf, label))
        except queue.Full:
            with self._inflight_lock:
                self._inflight -= 1
            self.arena.add_drop(op_idx)

    def pending(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def _loop(self) -> None:
        import jax

        from ..parallel.collectives import observe_latency_ns

        while not self._stop.is_set():
            try:
                op_idx, t0, leaf, label = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                jax.block_until_ready(leaf)
                elapsed = time.perf_counter() - t0
                self.arena.push(op_idx, elapsed)
                if label:
                    # completion half of the collective-plane choke point:
                    # the same latency family wrapped collectives feed, op
                    # names shared with the dispatch-tail vocabulary
                    observe_latency_ns(label, int(elapsed * 1e9))
            except Exception:  # noqa: BLE001 — a failed fetch ends the step, not us
                self.arena.add_drop(op_idx)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

    def stop(self) -> bool:
        """Returns True when the thread actually exited — the caller must
        NOT unmap the arena under a still-running feeder."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
            if t.is_alive():
                return False
            self._thread = None
        return True


class OpCollector:
    """Always-on collector façade: wrap callables once, read stats any time.

    ``profile_interval_s > 0`` adds the duty-cycled intra-module capture:
    once per interval, ONE call runs under the XLA profiler and its per-op
    durations land in the same rings under ``xla:`` names, parsed off-thread.
    """

    def __init__(
        self,
        arena: Optional[OpRingArena] = None,
        profile_interval_s: float = 0.0,
        top_k_ops: int = 64,
    ):
        _check_jax_version()
        self.arena = arena or OpRingArena()
        self.watcher = CompletionWatcher(self.arena).start()
        self.profile_interval_s = profile_interval_s
        self.top_k_ops = top_k_ops
        self._last_profile_t = time.monotonic()
        self._profile_lock = threading.Lock()
        self._parse_pool: Optional[threading.Thread] = None
        self.lane_filter_misses = 0
        self._installed_store: Optional[DurationStore] = None

    # -- instrumentation ---------------------------------------------------

    def wrap(self, fn: Callable, name: Optional[str] = None) -> Callable:
        """Non-blocking always-on timing of a jitted callable."""
        import jax

        label = name or getattr(fn, "__name__", repr(fn))
        op_idx = self.arena.intern(label)

        from ..parallel.collectives import instrument_dispatch

        def collected(*args, **kwargs):
            # the collective-plane instrumentation choke point: name +
            # dispatch stamp into the rank's dispatch tail (µs; read
            # post-mortem when wedged) — one vocabulary for the at-abort
            # fingerprint AND the live latency histograms
            instrument_dispatch(label)
            profiling = self._profile_due()
            if profiling:
                return self._profiled_call(fn, label, args, kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            leaf = _first_array_leaf(out)
            if leaf is not None:
                self.watcher.submit(op_idx, t0, leaf, label=label)
            return out

        collected.__name__ = f"op_collected[{label}]"
        collected.__wrapped__ = fn
        _ = jax  # imported for side effect parity with DeviceTimer.wrap
        return collected

    def _profile_due(self) -> bool:
        if self.profile_interval_s <= 0:
            return False
        now = time.monotonic()
        if now - self._last_profile_t < self.profile_interval_s:
            return False
        # one winner per interval across threads
        if not self._profile_lock.acquire(blocking=False):
            return False
        try:
            if now - self._last_profile_t < self.profile_interval_s:
                return False
            self._last_profile_t = now
            return True
        finally:
            self._profile_lock.release()

    def _profiled_call(self, fn, label, args, kwargs):
        import jax

        trace_dir = tempfile.mkdtemp(prefix="tpurx-opcoll-")
        try:
            with jax.profiler.trace(trace_dir):
                out = fn(*args, **kwargs)
                jax.block_until_ready(out)
        except Exception:
            shutil.rmtree(trace_dir, ignore_errors=True)
            raise
        t = threading.Thread(
            target=self._parse_trace, args=(trace_dir,),
            name="tpurx-op-parse", daemon=True,
        )
        t.start()
        self._parse_pool = t
        return out

    def _parse_trace(self, trace_dir: str) -> None:
        from .xla_profile import parse_trace_dir

        try:
            per_op = parse_trace_dir(trace_dir)
            if not per_op:
                self.lane_filter_misses += 1
                import jax

                log.error(
                    "duty-cycle capture parsed ZERO op events (jax %s) — the "
                    "trace lane filter no longer matches this JAX's trace "
                    "format; intra-module attribution is blind until "
                    "xla_profile lane lists are updated",
                    jax.__version__,
                )
                return
            ranked = sorted(
                per_op.items(), key=lambda kv: -sum(kv[1])
            )[: self.top_k_ops]
            for op_name, durs in ranked:
                idx = self.arena.intern("xla:" + op_name)
                for d in durs:
                    self.arena.push(idx, d)
        finally:
            shutil.rmtree(trace_dir, ignore_errors=True)

    # -- reading -----------------------------------------------------------

    def stats(self) -> Dict[str, SectionStats]:
        return self.arena.stats()

    def drops(self) -> Dict[str, int]:
        return self.arena.drops()

    def flush(self, timeout: float = 2.0) -> None:
        """Wait for queued completions to land (tests / report fences)."""
        deadline = time.monotonic() + timeout
        while self.watcher.pending() > 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        t = self._parse_pool
        if t is not None:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def close(self) -> None:
        self.flush(timeout=0.5)  # drain while the watcher is still alive
        stopped = self.watcher.stop()
        parse_t = self._parse_pool
        parsing = parse_t is not None and parse_t.is_alive()
        if stopped and not parsing:
            self.arena.close()
        else:
            # a wedged fetch (the exact hung-device scenario this module
            # exists for) or an in-flight trace parse may still push:
            # unmapping now would SIGSEGV the trainer.  Leak the segment —
            # the shm janitor reaps it; a leak beats a crash.
            log.warning(
                "op collector closing with a live feeder thread — leaving "
                "the ring arena mapped (janitor reclaims the segment)"
            )


def _first_array_leaf(tree):
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready") or hasattr(leaf, "is_ready"):
            return leaf
    return None
