"""Name interning (reference ``name_mapper.py:22``): section/callable names
map to stable small ids so future on-device gather paths can ship ids, not
strings. The store path sends names once per round; the mapper also guards
against unbounded name cardinality (a bug in naming sections per-step would
otherwise grow memory forever)."""

from __future__ import annotations

from typing import Dict

from ..utils.logging import get_logger

log = get_logger("straggler.names")


class NameMapper:
    def __init__(self, max_names: int = 4096):
        self.max_names = max_names
        self._ids: Dict[str, int] = {}
        self._warned = False

    def intern(self, name: str) -> int:
        idx = self._ids.get(name)
        if idx is None:
            if len(self._ids) >= self.max_names:
                if not self._warned:
                    log.warning(
                        "more than %s distinct section names — are names "
                        "per-step unique by mistake?", self.max_names,
                    )
                    self._warned = True
                return -1
            idx = len(self._ids)
            self._ids[name] = idx
        return idx

    def name_of(self, idx: int) -> str:
        for name, i in self._ids.items():
            if i == idx:
                return name
        raise KeyError(idx)

    def __len__(self) -> int:
        return len(self._ids)
