"""Timing backends: CPU sections and device-bound callables.

Reference analogs: ``straggler.py:288-348`` (``detection_section`` CPU timing
+ CUPTI capture toggle) and the CUPTI per-kernel circular buffers
(``CircularBuffer.h``).  Durations live in bounded deques — memory stays
constant over arbitrarily long runs.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Deque, Dict, List, Optional


@dataclasses.dataclass
class SectionStats:
    name: str
    count: int
    total: float
    avg: float
    median: float
    min: float
    max: float
    stddev: float

    @classmethod
    def from_samples(cls, name: str, samples: List[float]) -> "SectionStats":
        n = len(samples)
        if n == 0:
            return cls(name, 0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0)
        s = sorted(samples)
        total = sum(s)
        avg = total / n
        median = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
        var = sum((x - avg) ** 2 for x in s) / n
        return cls(name, n, total, avg, median, s[0], s[-1], math.sqrt(var))

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "SectionStats":
        return cls(**d)


class DurationStore:
    """Bounded per-name duration samples (CircularBuffer analog)."""

    def __init__(self, maxlen: int = 1024):
        self.maxlen = maxlen
        self._samples: Dict[str, Deque[float]] = {}

    def record(self, name: str, duration: float) -> None:
        buf = self._samples.get(name)
        if buf is None:
            buf = self._samples[name] = collections.deque(maxlen=self.maxlen)
        buf.append(duration)

    def names(self) -> List[str]:
        return sorted(self._samples)

    def stats(self) -> Dict[str, SectionStats]:
        return {
            name: SectionStats.from_samples(name, list(buf))
            for name, buf in self._samples.items()
        }

    def reset(self) -> None:
        self._samples.clear()


class DeviceTimer:
    """Times a callable to device completion.

    XLA dispatch is async: wall time around a jitted call measures the host,
    not the chip.  ``block_until_ready`` on the outputs closes the gap — the
    recorded duration is (queue + device execution), the same quantity the
    reference derives from CUPTI kernel records at per-kernel granularity.
    """

    def __init__(self, store: DurationStore):
        self.store = store
        self.enabled = True

    def wrap(self, fn, name: Optional[str] = None):
        import jax

        label = name or getattr(fn, "__name__", repr(fn))

        def timed(*args, **kwargs):
            if not self.enabled:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            self.store.record(label, time.perf_counter() - t0)
            return out

        timed.__name__ = f"straggler_timed[{label}]"
        timed.__wrapped__ = fn
        return timed
