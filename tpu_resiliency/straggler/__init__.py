"""Straggler detection (reference: ``attribution/straggler/``).

Per-rank performance monitoring: time CPU sections and device-bound jitted
callables, synchronize reports across ranks on a fixed cadence, score each
rank relative to the fastest peer and to its own history, and flag
stragglers.

TPU re-design: the reference's CUPTI C++ kernel tracer becomes a
**device-section timer** — wrapped jitted callables are timed to completion
(``block_until_ready``) so the measurement is device time, not dispatch time
(XLA's async dispatch makes raw wall timing meaningless).  The scoring and
reporting semantics match ``reporting.py:219-253``.
"""

from .detector import Detector
from .reporting import Report, StragglerVerdict
from .timers import SectionStats

__all__ = ["Detector", "Report", "StragglerVerdict", "SectionStats"]
