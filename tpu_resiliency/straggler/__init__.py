"""Straggler detection (reference: ``attribution/straggler/``).

Per-rank performance monitoring: time CPU sections and device-bound jitted
callables, synchronize reports across ranks on a fixed cadence, score each
rank relative to the fastest peer and to its own history, and flag
stragglers.

TPU re-design: the reference's CUPTI C++ kernel tracer becomes an
**always-on op collector** (``collector.py``) — wrapped jitted callables are
timed dispatch→completion off-thread into native shared-memory ring buffers
(``native/op_ring.c``, the CUPTI circular-buffer analog: constant memory,
<1% hot-path cost, readable by the rank monitor while the trainer is
wedged), with duty-cycled XLA-profiler captures for intra-module per-op
attribution.  The scoring and reporting semantics match
``reporting.py:219-253``.
"""

from .collector import CompletionWatcher, OpCollector, OpRingArena
from .detector import Detector
from .reporting import Report, StragglerVerdict
from .timers import SectionStats

__all__ = [
    "CompletionWatcher", "Detector", "OpCollector", "OpRingArena", "Report",
    "SectionStats", "StragglerVerdict",
]
