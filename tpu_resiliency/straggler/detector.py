"""Straggler detector façade (reference ``straggler/straggler.py:86-368``).

Usage:

    det = Detector(store=..., rank=r, world_size=w, scores_to_compute=...)
    det.initialize()
    step_fn = det.wrap_callables({"train_step": step_fn})["train_step"]
    for batch in data:
        with det.detection_section("data"):
            batch = next(it)
        loss = step_fn(...)
        report = det.maybe_report()      # None until the cadence fires
        if report is not None and det.rank == 0:
            for v in report.identify_stragglers():
                ...

Cross-rank gathering rides the KV store (one payload write per rank per
round + reads by rank 0 — the reference gathers over NCCL/Gloo,
``dist_utils.py:85``).  ``gather_on_rank0=False`` gives every rank the full
report (all ranks read all payloads).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Optional

from ..store.barrier import barrier
from ..telemetry import counter, gauge
from ..utils.logging import get_logger
from ..utils.profiling import ProfilingEvent, record_event
from .interval_tracker import ReportIntervalTracker
from .reporting import Report
from .timers import DeviceTimer, DurationStore
from .name_mapper import NameMapper

log = get_logger("straggler")

_REPORT_ROUNDS = counter(
    "tpurx_straggler_report_rounds_total", "Straggler reporting rounds completed"
)
_INDIVIDUAL_SCORE = gauge(
    "tpurx_straggler_individual_score",
    "This rank's current-vs-own-best score (1.0 = at historical best)",
)


class Detector:
    def __init__(
        self,
        store=None,
        rank: int = 0,
        world_size: int = 1,
        report_interval: int = 16,
        time_interval_s: Optional[float] = None,
        gather_on_rank0: bool = True,
        history_maxlen: int = 1024,
        always_on: bool = True,
        profile_interval_s: float = 0.0,
    ):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.gather_on_rank0 = gather_on_rank0
        self.sections = DurationStore(maxlen=history_maxlen)
        self.device = DurationStore(maxlen=history_maxlen)
        self.device_timer = DeviceTimer(self.device)
        self.tracker = ReportIntervalTracker(report_interval, time_interval_s)
        self.names = NameMapper()
        self._round = 0
        # per-name best historical median (for individual scores)
        self._best_medians: Dict[str, float] = {}
        self._initialized = False
        self._xla_collector = None  # built on first profiled_step()
        # always-on collector: non-blocking completion timing into native
        # shm rings (+ optional duty-cycled per-op profiler captures)
        self.collector = None
        if always_on:
            import os

            from .collector import OpCollector

            self.collector = OpCollector(
                profile_interval_s=profile_interval_s,
                arena=None,
            )
            # publish the arena name so a RankMonitorClient constructed later
            # in this process forwards it on INIT — the monitor can then read
            # this rank's op stats post-mortem while it hangs
            if self.collector.arena.shm_name:
                os.environ["TPURX_OPRING_SHM"] = self.collector.arena.shm_name

    def initialize(self) -> None:
        self._initialized = True

    def shutdown(self) -> None:
        self._initialized = False
        if self.collector is not None:
            self.collector.close()
            self.collector = None

    # -- instrumentation ---------------------------------------------------

    @contextlib.contextmanager
    def detection_section(self, name: str):
        """Time a CPU section (reference ``detection_section``)."""
        self.names.intern(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.sections.record(name, time.perf_counter() - t0)
            self._tick()

    def wrap_callables(self, callables: Dict[str, Callable]) -> Dict[str, Callable]:
        """Wrap jitted callables so their device time is captured
        (reference monkey-patch profiling ``straggler.py:368``).

        With the always-on collector the wrap is NON-blocking (completion is
        observed off-thread into the native rings); the blocking DeviceTimer
        remains the fallback."""
        out = {}
        for name, fn in callables.items():
            self.names.intern(name)
            if self.collector is not None:
                out[name] = self.collector.wrap(fn, name)
            else:
                out[name] = self.device_timer.wrap(fn, name)
        return out

    @contextlib.contextmanager
    def profiled_step(self):
        """Sampled per-op capture: profile the enclosed step with the XLA
        profiler and record op durations into the device stats (the CUPTI
        per-kernel analog).  Costs ~tens of ms — call every Nth step, not
        every step."""
        from .xla_profile import XlaProfileCollector

        if self._xla_collector is None:
            self._xla_collector = XlaProfileCollector(self.device)
        with self._xla_collector.capture():
            yield

    def _tick(self) -> None:
        # accumulate: a due report must survive further ticks until consumed
        if self.tracker.tick():
            self._report_due = True

    # -- reporting ---------------------------------------------------------

    def maybe_report(self, timeout: float = 60.0) -> Optional[Report]:
        if not getattr(self, "_report_due", False):
            return None
        self._report_due = False
        return self.generate_report(timeout=timeout)

    def generate_report(self, timeout: float = 60.0) -> Optional[Report]:
        """Collective: every rank publishes local stats; rank 0 (or all, with
        gather_on_rank0=False) assembles the report."""
        record_event(ProfilingEvent.STRAGGLER_DETECTED, kind="report_round", round=self._round)
        round_idx = self._round
        self._round += 1
        section_stats = self.sections.stats()
        device_stats = self.device.stats()
        if self.collector is not None:
            # in-flight completions land before the snapshot; ring stats are
            # readable without pausing collection (CUPTI-buffer property)
            self.collector.flush(timeout=1.0)
            device_stats = {**device_stats, **self.collector.stats()}
        # update own history
        for name, st in {**section_stats, **device_stats}.items():
            if st.median > 0:
                best = self._best_medians.get(name)
                if best is None or st.median < best:
                    self._best_medians[name] = st.median

        if self.store is None or self.world_size == 1:
            _REPORT_ROUNDS.inc()
            return Report(
                round_idx,
                {self.rank: section_stats},
                {self.rank: device_stats},
            )

        payload = Report.rank_payload(section_stats, device_stats)
        key = f"straggler/round/{round_idx}/rank/{self.rank}"
        self.store.set(key, payload)
        barrier(
            self.store, f"straggler/round/{round_idx}/gather",
            self.world_size, timeout=timeout,
        )
        report = None
        if not self.gather_on_rank0 or self.rank == 0:
            # ONE round trip for all ranks' payloads (the barrier above
            # guarantees presence) — at 256 ranks this is the difference
            # between 256 RTTs and 1 on the gather path
            keys = [
                f"straggler/round/{round_idx}/rank/{r}"
                for r in range(self.world_size)
            ]
            raws = self.store.multi_get(keys)
            if raws is None:
                raise RuntimeError(
                    f"straggler round {round_idx}: payload vanished after "
                    "the gather barrier"
                )
            payloads = {r: raw.decode() for r, raw in enumerate(raws)}
            report = Report.from_payloads(round_idx, payloads)
        if not self.gather_on_rank0:
            # everyone reads: fence before cleanup so no reader races a delete
            barrier(
                self.store, f"straggler/round/{round_idx}/read",
                self.world_size, timeout=timeout,
            )
        if self.rank == 0:
            # a multi-day run must not grow the store unboundedly: drop this
            # round's payloads and barrier keys once consumed
            for k in self.store.list_keys(f"straggler/round/{round_idx}/"):
                self.store.delete(k)
            for k in self.store.list_keys(f"barrier/straggler/round/{round_idx}/"):
                self.store.delete(k)
        _REPORT_ROUNDS.inc()
        return report

    def individual_score(self) -> Optional[float]:
        """This rank's current-vs-best score (device stats preferred)."""
        device = self.device.stats()
        if self.collector is not None:
            device = {**device, **self.collector.stats()}
        stats = device or self.sections.stats()
        score = Report.individual_scores(stats, self._best_medians)
        if score is not None:
            _INDIVIDUAL_SCORE.set(score)
        return score

    def reset(self) -> None:
        self.sections.reset()
        self.device.reset()
