"""Straggler detector façade (reference ``straggler/straggler.py:86-368``).

Usage:

    det = Detector(store=..., rank=r, world_size=w, scores_to_compute=...)
    det.initialize()
    step_fn = det.wrap_callables({"train_step": step_fn})["train_step"]
    for batch in data:
        with det.detection_section("data"):
            batch = next(it)
        loss = step_fn(...)
        report = det.maybe_report()      # None until the cadence fires
        if report is not None and det.rank == 0:
            for v in report.identify_stragglers():
                ...

Cross-rank gathering rides the KV store's reduction tree (``store/tree.py``
— the reference gathers over NCCL/Gloo, ``dist_utils.py:85``): payloads
merge rank → host → job, so rank 0's inbound payload count is O(fanout) per
round.  ``gather_on_rank0=False`` broadcasts the merged report back so
every rank gets it.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Callable, Dict, Optional

from ..store.tree import combine_json_merge, tree_gather
from ..telemetry import counter, gauge
from ..utils.logging import get_logger
from ..utils.profiling import ProfilingEvent, record_event
from .interval_tracker import ReportIntervalTracker
from .reporting import Report
from .timers import DeviceTimer, DurationStore
from .name_mapper import NameMapper

log = get_logger("straggler")

_REPORT_ROUNDS = counter(
    "tpurx_straggler_report_rounds_total", "Straggler reporting rounds completed"
)
_INDIVIDUAL_SCORE = gauge(
    "tpurx_straggler_individual_score",
    "This rank's current-vs-own-best score (1.0 = at historical best)",
)


class Detector:
    def __init__(
        self,
        store=None,
        rank: int = 0,
        world_size: int = 1,
        report_interval: int = 16,
        time_interval_s: Optional[float] = None,
        gather_on_rank0: bool = True,
        history_maxlen: int = 1024,
        always_on: bool = True,
        profile_interval_s: float = 0.0,
    ):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.gather_on_rank0 = gather_on_rank0
        self.sections = DurationStore(maxlen=history_maxlen)
        self.device = DurationStore(maxlen=history_maxlen)
        self.device_timer = DeviceTimer(self.device)
        self.tracker = ReportIntervalTracker(report_interval, time_interval_s)
        self.names = NameMapper()
        self._round = 0
        # per-name best historical median (for individual scores)
        self._best_medians: Dict[str, float] = {}
        self._initialized = False
        self._xla_collector = None  # built on first profiled_step()
        # always-on collector: non-blocking completion timing into native
        # shm rings (+ optional duty-cycled per-op profiler captures)
        self.collector = None
        if always_on:
            import os

            from .collector import OpCollector

            self.collector = OpCollector(
                profile_interval_s=profile_interval_s,
                arena=None,
            )
            # publish the arena name so a RankMonitorClient constructed later
            # in this process forwards it on INIT — the monitor can then read
            # this rank's op stats post-mortem while it hangs
            if self.collector.arena.shm_name:
                os.environ["TPURX_OPRING_SHM"] = self.collector.arena.shm_name

    def initialize(self) -> None:
        self._initialized = True

    def shutdown(self) -> None:
        self._initialized = False
        if self.collector is not None:
            self.collector.close()
            self.collector = None

    # -- instrumentation ---------------------------------------------------

    @contextlib.contextmanager
    def detection_section(self, name: str):
        """Time a CPU section (reference ``detection_section``)."""
        self.names.intern(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.sections.record(name, time.perf_counter() - t0)
            self._tick()

    def wrap_callables(self, callables: Dict[str, Callable]) -> Dict[str, Callable]:
        """Wrap jitted callables so their device time is captured
        (reference monkey-patch profiling ``straggler.py:368``).

        With the always-on collector the wrap is NON-blocking (completion is
        observed off-thread into the native rings); the blocking DeviceTimer
        remains the fallback."""
        out = {}
        for name, fn in callables.items():
            self.names.intern(name)
            if self.collector is not None:
                out[name] = self.collector.wrap(fn, name)
            else:
                out[name] = self.device_timer.wrap(fn, name)
        return out

    @contextlib.contextmanager
    def profiled_step(self):
        """Sampled per-op capture: profile the enclosed step with the XLA
        profiler and record op durations into the device stats (the CUPTI
        per-kernel analog).  Costs ~tens of ms — call every Nth step, not
        every step."""
        from .xla_profile import XlaProfileCollector

        if self._xla_collector is None:
            self._xla_collector = XlaProfileCollector(self.device)
        with self._xla_collector.capture():
            yield

    def _tick(self) -> None:
        # accumulate: a due report must survive further ticks until consumed
        if self.tracker.tick():
            self._report_due = True

    # -- reporting ---------------------------------------------------------

    def maybe_report(self, timeout: float = 60.0) -> Optional[Report]:
        if not getattr(self, "_report_due", False):
            return None
        self._report_due = False
        return self.generate_report(timeout=timeout)

    def generate_report(self, timeout: float = 60.0) -> Optional[Report]:
        """Collective: every rank publishes local stats; rank 0 (or all, with
        gather_on_rank0=False) assembles the report."""
        record_event(ProfilingEvent.STRAGGLER_DETECTED, kind="report_round", round=self._round)
        round_idx = self._round
        self._round += 1
        section_stats = self.sections.stats()
        device_stats = self.device.stats()
        if self.collector is not None:
            # in-flight completions land before the snapshot; ring stats are
            # readable without pausing collection (CUPTI-buffer property)
            self.collector.flush(timeout=1.0)
            device_stats = {**device_stats, **self.collector.stats()}
        # update own history
        for name, st in {**section_stats, **device_stats}.items():
            if st.median > 0:
                best = self._best_medians.get(name)
                if best is None or st.median < best:
                    self._best_medians[name] = st.median

        if self.store is None or self.world_size == 1:
            _REPORT_ROUNDS.inc()
            return Report(
                round_idx,
                {self.rank: section_stats},
                {self.rank: device_stats},
            )

        # Hierarchical gather (rank → host → job): every rank's payload rides
        # the reduction tree, so rank 0 consumes O(fanout) inbound payloads
        # per round instead of the flat gather's O(N).  Subtree keys are
        # deleted by their consuming parent; rank 0 GCs two-rounds-stale
        # prefixes (covers the broadcast result key and crashed rounds).
        payload = json.dumps(
            {self.rank: Report.rank_payload(section_stats, device_stats)}
        ).encode()
        merged = tree_gather(
            self.store,
            self.rank,
            self.world_size,
            prefix=f"straggler/round/{round_idx}",
            payload=payload,
            combine=combine_json_merge,
            timeout=timeout,
            broadcast=not self.gather_on_rank0,
            site="straggler",
            gc_prefix=(
                f"straggler/round/{round_idx - 2}/" if round_idx >= 2 else None
            ),
        )
        report = None
        if merged is not None:
            payloads = {int(r): p for r, p in json.loads(merged).items()}
            report = Report.from_payloads(round_idx, payloads)
        _REPORT_ROUNDS.inc()
        return report

    def individual_score(self) -> Optional[float]:
        """This rank's current-vs-best score (device stats preferred)."""
        device = self.device.stats()
        if self.collector is not None:
            device = {**device, **self.collector.stats()}
        stats = device or self.sections.stats()
        score = Report.individual_scores(stats, self._best_medians)
        if score is not None:
            _INDIVIDUAL_SCORE.set(score)
        return score

    def reset(self) -> None:
        self.sections.reset()
        self.device.reset()
