"""Report cadence synchronization (reference ``interval_tracker.py:24``).

Ranks must generate reports at the same logical point or cross-rank scores
compare different workloads.  The tracker counts local section completions
and, on the first report, aligns the interval so every rank reports every
``interval`` completions starting from a shared origin.
"""

from __future__ import annotations


class ReportIntervalTracker:
    def __init__(self, interval: int = 16, time_interval_s: float | None = None):
        import time as _time

        self.interval = interval
        self.time_interval_s = time_interval_s
        self.count = 0
        self._last_report_t = _time.monotonic()

    def tick(self) -> bool:
        """Count one section completion; True when a report is due."""
        import time as _time

        self.count += 1
        if self.count % self.interval == 0:
            self._last_report_t = _time.monotonic()
            return True
        if (
            self.time_interval_s is not None
            and _time.monotonic() - self._last_report_t >= self.time_interval_s
        ):
            self._last_report_t = _time.monotonic()
            return True
        return False
