"""Straggler scoring and reports.

Scoring semantics follow ``attribution/straggler/reporting.py:84-253``:

- **relative scores**: for each timed name, a rank's score is
  ``best_median / rank_median`` (1.0 = fastest rank, lower = slower); the
  per-rank summary score weights names by their share of total time, so a
  slow-but-rare section cannot dominate.
- **individual scores**: ``best_historical_median / current_median`` per
  rank — catches a rank degrading against itself even when the whole job
  slows together (relative scores cannot see fleet-wide degradation).
- ``identify_stragglers``: ranks under the threshold on either axis.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from ..telemetry import counter, gauge
from .timers import SectionStats

_VERDICTS = counter(
    "tpurx_straggler_verdicts_total",
    "Per-rank verdicts produced by identify_stragglers",
    labels=("verdict",),
)
_FLAGGED = gauge(
    "tpurx_straggler_flagged_ranks", "Ranks flagged straggler in the last round"
)
# the per-rank performance score the RankRiskModel fuses: published by
# whichever rank held the report round, so SnapshotFeed sees the whole
# gang's straggler axis in one snapshot (1.0 = nominal, lower = slower)
_SCORE = gauge(
    "tpurx_straggler_score",
    "Worst of a rank's relative and individual performance scores from "
    "the last straggler report round (1.0 = nominal, lower = slower)",
    labels=("rank",),
)


@dataclasses.dataclass
class StragglerVerdict:
    rank: int
    relative_score: float
    individual_score: Optional[float]
    is_straggler: bool
    detail: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Report:
    """All-rank stats for one reporting round."""

    round_idx: int
    # {rank: {name: SectionStats}}
    section_stats: Dict[int, Dict[str, SectionStats]]
    device_stats: Dict[int, Dict[str, SectionStats]]

    # -- serialization (store gather) -------------------------------------

    @staticmethod
    def rank_payload(
        sections: Dict[str, SectionStats], device: Dict[str, SectionStats]
    ) -> str:
        return json.dumps(
            {
                "sections": {k: v.to_dict() for k, v in sections.items()},
                "device": {k: v.to_dict() for k, v in device.items()},
            }
        )

    @classmethod
    def from_payloads(cls, round_idx: int, payloads: Dict[int, str]) -> "Report":
        section_stats, device_stats = {}, {}
        for rank, raw in payloads.items():
            d = json.loads(raw)
            section_stats[rank] = {
                k: SectionStats.from_dict(v) for k, v in d["sections"].items()
            }
            device_stats[rank] = {
                k: SectionStats.from_dict(v) for k, v in d["device"].items()
            }
        return cls(round_idx=round_idx, section_stats=section_stats, device_stats=device_stats)

    # -- scoring -----------------------------------------------------------

    @staticmethod
    def _relative_scores(
        per_rank: Dict[int, Dict[str, SectionStats]]
    ) -> Dict[int, float]:
        ranks = sorted(per_rank)
        names = sorted({n for stats in per_rank.values() for n in stats})
        if not names:
            return {r: 1.0 for r in ranks}
        best_median = {
            n: min(
                (per_rank[r][n].median for r in ranks if n in per_rank[r] and per_rank[r][n].median > 0),
                default=0.0,
            )
            for n in names
        }
        out: Dict[int, float] = {}
        for r in ranks:
            weighted, weight_sum = 0.0, 0.0
            for n in names:
                st = per_rank[r].get(n)
                if st is None or st.median <= 0 or best_median[n] <= 0:
                    continue
                weight = st.total
                weighted += (best_median[n] / st.median) * weight
                weight_sum += weight
            out[r] = weighted / weight_sum if weight_sum else 1.0
        return out

    def relative_device_scores(self) -> Dict[int, float]:
        return self._relative_scores(self.device_stats)

    def relative_section_scores(self) -> Dict[int, float]:
        return self._relative_scores(self.section_stats)

    @staticmethod
    def individual_scores(
        current: Dict[str, SectionStats], best_history: Dict[str, float]
    ) -> Optional[float]:
        """current-vs-own-best for one rank; None with no history."""
        weighted, weight_sum = 0.0, 0.0
        for name, st in current.items():
            best = best_history.get(name)
            if best is None or st.median <= 0:
                continue
            weighted += (best / st.median) * st.total
            weight_sum += st.total
        if not weight_sum:
            return None
        return weighted / weight_sum

    def op_diff(self, rank: int, top_k: int = 10) -> List[Dict]:
        """Per-op slowdown of ``rank`` vs the fastest rank — pinpoints WHICH
        op drags a flagged straggler (the per-kernel CUPTI diff capability).
        Entries: {name, rank_median, best_median, slowdown, total} sorted by
        time lost (slowdown-weighted total)."""
        mine = self.device_stats.get(rank) or self.section_stats.get(rank) or {}
        per_rank = self.device_stats if self.device_stats.get(rank) else self.section_stats
        out = []
        for name, st in mine.items():
            if st.median <= 0:
                continue
            best = min(
                (
                    per_rank[r][name].median
                    for r in per_rank
                    if name in per_rank[r] and per_rank[r][name].median > 0
                ),
                default=st.median,
            )
            slowdown = st.median / best if best > 0 else 1.0
            out.append(
                {
                    "name": name,
                    "rank_median": st.median,
                    "best_median": best,
                    "slowdown": slowdown,
                    "total": st.total,
                    "time_lost": max(0.0, (st.median - best) * st.count),
                }
            )
        out.sort(key=lambda d: -d["time_lost"])
        return out[:top_k]

    def identify_stragglers(
        self,
        relative_threshold: float = 0.7,
        individual_threshold: float = 0.7,
        individual: Optional[Dict[int, Optional[float]]] = None,
    ) -> List[StragglerVerdict]:
        rel_dev = self.relative_device_scores()
        rel_sec = self.relative_section_scores()
        verdicts = []
        for rank in sorted(set(rel_dev) | set(rel_sec)):
            # device timing is the primary signal when present
            rel = rel_dev.get(rank) if self.device_stats.get(rank) else None
            if rel is None:
                rel = rel_sec.get(rank, 1.0)
            ind = (individual or {}).get(rank)
            is_straggler = rel < relative_threshold or (
                ind is not None and ind < individual_threshold
            )
            verdicts.append(
                StragglerVerdict(
                    rank=rank,
                    relative_score=rel,
                    individual_score=ind,
                    is_straggler=is_straggler,
                    detail={"relative_section": rel_sec.get(rank, 1.0)},
                )
            )
        flagged = sum(1 for v in verdicts if v.is_straggler)
        _VERDICTS.labels("straggler").inc(flagged)
        _VERDICTS.labels("nominal").inc(len(verdicts) - flagged)
        _FLAGGED.set(flagged)
        for v in verdicts:
            score = v.relative_score
            if v.individual_score is not None:
                score = min(score, v.individual_score)
            _SCORE.labels(str(v.rank)).set(score)
        return verdicts
