"""Operator tool: read a (possibly wedged) trainer's op rings from the shell.

The always-on collector's arena lives in named shared memory precisely so it
outlives a hung training process (``collector.py``); this CLI attaches
read-side and renders per-op stats — the on-call engineer's "what was that
rank doing" view without touching the trainer.

    tpurx-opring <shm_name>              # e.g. psm_85212c3b
    tpurx-opring <shm_name> --watch 2    # refresh every 2s
    tpurx-opring --from-pid <pid>        # resolve via TPURX_OPRING_SHM env

The shm name is logged by the Detector at startup and published in the
trainer's environment as ``TPURX_OPRING_SHM`` (forwarded to the rank
monitor on INIT for post-mortem capture).
"""

from __future__ import annotations

import argparse
import sys
import time


def _resolve_from_pid(pid: int) -> str:
    """Find the trainer's arena among its mapped shm segments.

    /proc/<pid>/environ only reflects the execve-time environment (the
    Detector publishes TPURX_OPRING_SHM at runtime, invisible there), but
    the arena is MAPPED — scan /proc/<pid>/maps for /dev/shm entries and
    magic-check each."""
    from .collector import OpRingArena

    candidates = []
    try:
        with open(f"/proc/{pid}/maps") as f:
            for line in f:
                if "/dev/shm/" in line:
                    name = line.rsplit("/dev/shm/", 1)[1].split()[0]
                    name = name.split(" (deleted)")[0]
                    if name not in candidates:
                        candidates.append(name)
    except OSError as exc:
        raise SystemExit(f"cannot read /proc/{pid}/maps: {exc}")
    for name in candidates:
        if OpRingArena.looks_like_arena(name):
            return name
    raise SystemExit(
        f"pid {pid} maps no op-ring arena (shm segments seen: "
        f"{candidates or 'none'})"
    )


def render(shm_name: str) -> str:
    from .collector import OpRingArena

    arena = OpRingArena.attach(shm_name)  # raises if the native lib is absent
    try:
        stats = arena.stats()
        drops = arena.drops()
    finally:
        arena.close()
    if not stats:
        return f"arena {shm_name}: no ops recorded"
    rows = sorted(stats.values(), key=lambda s: -s.total)
    total_all = sum(s.total for s in rows) or 1e-12
    width = 28
    lines = [
        f"arena {shm_name}: {len(rows)} op(s)",
        f"{'op':<40} {'count':>7} {'median':>10} {'p~max':>10} "
        f"{'total':>9}  share",
    ]
    for s in rows:
        share = s.total / total_all
        bar = "#" * max(1, int(share * width))
        name = s.name if len(s.name) <= 40 else s.name[:37] + "..."
        lines.append(
            f"{name:<40} {s.count:>7} {s.median * 1e3:>8.2f}ms "
            f"{s.max * 1e3:>8.2f}ms {s.total:>8.2f}s  {bar} {share:>5.1%}"
        )
    dropped = {k: v for k, v in drops.items() if v}
    if dropped:
        lines.append(f"drops: {dropped}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpurx-opring", description=__doc__)
    p.add_argument("shm_name", nargs="?", help="arena shared-memory name")
    p.add_argument("--from-pid", type=int, default=None,
                   help="resolve the arena name from a trainer pid's env")
    p.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                   help="refresh continuously")
    args = p.parse_args(argv)
    name = args.shm_name
    if args.from_pid is not None:
        name = _resolve_from_pid(args.from_pid)
    if not name:
        p.error("need a shm name or --from-pid")
    try:
        while True:
            print(render(name), flush=True)
            if not args.watch:
                return 0
            time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        return 0
    except FileNotFoundError:
        print(f"no such arena: {name} (trainer exited and unlinked it?)",
              file=sys.stderr)
        return 1
    except RuntimeError as exc:
        print(str(exc), file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
