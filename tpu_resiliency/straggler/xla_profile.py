"""XLA-profile timer backend: per-op device durations from JAX traces.

The reference's CUPTI extension records per-kernel durations on every
detection section (``cupti_src/``); the XLA analog captures a JAX profiler
trace and aggregates the device-lane op events.  The emitted Chrome-trace
JSON is parsed with the stdlib (the xplane protobuf bindings in this image
are version-broken, and a hard dependency on them would be fragile anyway).

Profiling a step costs more than the reference's always-on CUPTI buffers
(trace start/stop ≈ tens of ms), so the collector is designed for **sampled**
capture — wrap one step every N report rounds:

    collector = XlaProfileCollector(detector.device)
    with collector.capture():
        step_fn(...)   # one profiled step
    # per-op durations now in the detector's device DurationStore ("xla:...")

Op-name durations feed the same relative/individual scoring as section and
callable timings — per-op granularity pinpoints WHICH op is slow on a
straggling rank (the CUPTI per-kernel capability).
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import shutil
import tempfile
from typing import Dict, List

from ..utils.logging import get_logger
from .timers import DurationStore

log = get_logger("straggler.xla")


# Runtime bookkeeping spans sharing the op lanes: not op time.  "end: <op>"
# markers would double-count ops; executor/listener spans cover whole
# executions and would dilute per-op weighting; "XLA Modules"/"Steps" lane
# aggregates likewise.
_NON_OP_PREFIXES = ("end: ", "$")
_NON_OP_SUBSTRINGS = (
    "ThunkExecutor", "ThreadpoolListener", "ExecuteThunks", "BufferAllocations",
)
_NON_OP_LANE_SUBSTRINGS = ("python", "Steps", "XLA Modules", "tf_Compile", "Framework")


def _is_op_event(name: str, lane: str) -> bool:
    if any(s in lane for s in _NON_OP_LANE_SUBSTRINGS):
        return False
    if name.startswith(_NON_OP_PREFIXES):
        return False
    if any(s in name for s in _NON_OP_SUBSTRINGS):
        return False
    return True


def parse_trace_dir(trace_dir: str) -> Dict[str, List[float]]:
    """Aggregate op durations (seconds) from a profiler dump directory.

    Takes complete ('X') events from the op lanes — on TPU the device
    "XLA Ops" lanes; on CPU the PjRt client execution threads — keyed by op
    name, with runtime bookkeeping spans filtered (see ``_is_op_event``)."""
    out: Dict[str, List[float]] = {}
    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ):
        try:
            with gzip.open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            log.warning("unparseable trace file %s: %s", path, exc)
            continue
        events = data.get("traceEvents", [])
        lanes: Dict[tuple, str] = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                lanes[(e.get("pid"), e.get("tid"))] = e.get("args", {}).get("name", "")
        for e in events:
            if e.get("ph") != "X" or not e.get("dur"):
                continue
            lane = lanes.get((e.get("pid"), e.get("tid")), "")
            name = e.get("name", "?")
            if not _is_op_event(name, lane):
                continue
            out.setdefault(name, []).append(float(e["dur"]) / 1e6)  # µs → s
    return out


class XlaProfileCollector:
    def __init__(self, store: DurationStore, prefix: str = "xla:", top_k: int = 64):
        self.store = store
        self.prefix = prefix
        self.top_k = top_k
        self.last_capture: Dict[str, List[float]] = {}

    @contextlib.contextmanager
    def capture(self):
        """Profile the enclosed step(s); record per-op durations on exit."""
        import jax

        trace_dir = tempfile.mkdtemp(prefix="tpurx-xlaprof-")
        try:
            with jax.profiler.trace(trace_dir):
                yield
            per_op = parse_trace_dir(trace_dir)
            # keep the top_k ops by total time: straggler scores weight by
            # total anyway, and unbounded op-name cardinality would bloat
            # every report
            ranked = sorted(
                per_op.items(), key=lambda kv: -sum(kv[1])
            )[: self.top_k]
            self.last_capture = dict(ranked)
            for name, durs in ranked:
                for d in durs:
                    self.store.record(self.prefix + name, d)
        finally:
            shutil.rmtree(trace_dir, ignore_errors=True)
