"""Runtime sanitizer witness files, read back into the static analysis.

``tpu_resiliency/utils/sanitize.py`` (opt-in via ``TPURX_SANITIZE=1``)
records the actual cross-thread lock-acquisition DAG as JSONL: one ``edge``
record per distinct (held-lock, acquired-lock) pair, keyed by each lock's
CREATION site — which is exactly the declaration site the static lock table
indexes, so the two views compare 1:1.  ``tpurx-lint --witness <file>``
feeds the observed DAG to TPURX011: static cycles whose every edge was
observed at runtime are promoted to CONFIRMED; cycles whose locks were all
exercised but only ever in one consistent order are PRUNED as false
positives; everything else stays PLAUSIBLE.
"""

from __future__ import annotations

import json
import os


class Witness:
    """Parsed witness: observed acquisition edges + exercised lock sites."""

    def __init__(self):
        self.edges: set = set()     # (from_site, to_site), repo-relative
        self.sites: set = set()
        self.cycles: list = []      # [[site, ...], ...]
        self.records = 0

    @classmethod
    def load(cls, paths, root: str) -> "Witness":
        """Load one or more JSONL witness files; sites are normalized to
        repo-relative (absolute paths under `root` are relativized)."""
        w = cls()
        root = os.path.abspath(root)
        if isinstance(paths, str):
            paths = [paths]
        for path in paths:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    w._ingest(rec, root)
        return w

    def _ingest(self, rec: dict, root: str) -> None:
        self.records += 1
        event = rec.get("event")
        if event == "edge":
            a = _norm_site(rec.get("frm", {}).get("site", ""), root)
            b = _norm_site(rec.get("to", {}).get("site", ""), root)
            if a and b:
                self.edges.add((a, b))
                self.sites.update((a, b))
        elif event == "cycle":
            chain = [_norm_site(s, root) for s in rec.get("chain", [])]
            self.cycles.append([s for s in chain if s])
            self.sites.update(s for s in chain if s)


def _norm_site(site: str, root: str) -> str:
    if not site:
        return ""
    path, _, line = site.rpartition(":")
    if os.path.isabs(path):
        try:
            rel = os.path.relpath(path, root)
        except ValueError:
            return site
        if not rel.startswith(".."):
            path = rel.replace(os.sep, "/")
    return f"{path}:{line}"
