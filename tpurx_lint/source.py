"""Per-file parse product: source text, AST (parsed exactly once), parent
links, and tpurx suppression directives.

Suppression syntax (reason REQUIRED — an inline waiver without a recorded
why is how grandfathered rot accumulates):

    x = ev.wait()  # tpurx: disable=TPURX005 -- bounded by caller's SIGALRM

    # tpurx: disable=TPURX005 -- bounded by caller's SIGALRM
    x = ev.wait()

    # tpurx: disable-file=TPURX001 -- argparse CLI, stdout IS the interface

``disable=`` on a line suppresses matching findings on that line; a comment
alone on its line also covers the next non-blank code line.  ``disable-file=``
covers the whole file.  Several rules may be listed comma-separated.  A
directive missing its ``-- reason`` (or naming a malformed rule id) is itself
reported as TPURX900.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

from .findings import Finding

SUPPRESSION_META_RULE = "TPURX900"

_DIRECTIVE_RE = re.compile(
    r"#\s*tpurx:\s*(?P<kind>disable(?:-file)?)\s*=\s*(?P<rules>[A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$"
)
_RULE_ID_RE = re.compile(r"^TPURX\d{3}$")


@dataclass
class Suppression:
    rules: frozenset
    line: int              # line the directive appears on
    reason: str
    file_scope: bool = False


@dataclass
class ParsedFile:
    """One source file, parsed once, shared by every rule."""

    path: str                   # absolute
    rel: str                    # repo-relative, posix
    text: str
    tree: ast.AST
    lines: list = field(default_factory=list)
    suppressions: list = field(default_factory=list)
    directive_findings: list = field(default_factory=list)
    _parents: dict = field(default_factory=dict)
    _line_suppress: dict = field(default_factory=dict)   # line -> set(rule ids)
    _file_suppress: set = field(default_factory=set)

    @classmethod
    def parse(cls, path: str, rel: str, text: str) -> "ParsedFile":
        tree = ast.parse(text, filename=rel)
        pf = cls(path=path, rel=rel, text=text, tree=tree,
                 lines=text.splitlines())
        pf._link_parents()
        pf._collect_directives()
        return pf

    # -- AST helpers -------------------------------------------------------

    def _link_parents(self) -> None:
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST):
        return self._parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule=rule, path=self.rel, line=line, message=message,
                       symbol=self.source_line(line))

    # -- suppression directives -------------------------------------------

    def _collect_directives(self) -> None:
        code_lines = set()
        try:
            toks = list(tokenize.generate_tokens(io.StringIO(self.text).readline))
        except tokenize.TokenError:
            toks = []
        comments = []
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                comments.append(tok)
            elif tok.type not in (
                tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                tokenize.DEDENT, tokenize.ENCODING, tokenize.ENDMARKER,
            ):
                for ln in range(tok.start[0], tok.end[0] + 1):
                    code_lines.add(ln)

        for tok in comments:
            m = _DIRECTIVE_RE.search(tok.string)
            if m is None:
                if re.search(r"#\s*tpurx:", tok.string):
                    self.directive_findings.append(self.finding(
                        SUPPRESSION_META_RULE, tok.start[0],
                        f"malformed tpurx directive {tok.string.strip()!r} "
                        f"(expected '# tpurx: disable=<RULE,...> -- <reason>')",
                    ))
                continue
            rules = frozenset(
                r.strip() for r in m.group("rules").split(",") if r.strip()
            )
            reason = (m.group("reason") or "").strip()
            line = tok.start[0]
            bad = [r for r in rules if not _RULE_ID_RE.match(r)]
            if bad:
                self.directive_findings.append(self.finding(
                    SUPPRESSION_META_RULE, line,
                    f"suppression names malformed rule id(s) {sorted(bad)} "
                    f"(expected TPURXnnn)",
                ))
                continue
            if not reason:
                self.directive_findings.append(self.finding(
                    SUPPRESSION_META_RULE, line,
                    f"suppression for {sorted(rules)} has no reason — append "
                    f"'-- <why this is safe>' (reasons are required)",
                ))
                continue
            file_scope = m.group("kind") == "disable-file"
            self.suppressions.append(
                Suppression(rules=rules, line=line, reason=reason,
                            file_scope=file_scope))
            if file_scope:
                self._file_suppress |= rules
            else:
                covered = {line}
                if line not in code_lines:
                    # comment on its own line: cover the next code line
                    nxt = line + 1
                    limit = len(self.lines)
                    while nxt <= limit and nxt not in code_lines:
                        nxt += 1
                    if nxt <= limit:
                        covered.add(nxt)
                for ln in covered:
                    self._line_suppress.setdefault(ln, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_suppress:
            return True
        return rule in self._line_suppress.get(line, set())
