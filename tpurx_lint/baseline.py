"""Checked-in baseline of grandfathered findings.

The baseline exists so a new rule can land with the gate green while its
pre-existing offenders are burned down deliberately.  Every entry carries a
one-line ``justification`` written at review time — an empty justification
fails the gate, which keeps ``--write-baseline`` output from being committed
unreviewed.

Entries match on (rule, path, symbol) — symbol is the stripped source line —
not on line numbers, so edits elsewhere in a file don't invalidate the
baseline, while any edit to the offending line itself surfaces the finding
again for fresh review.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .findings import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass
class BaselineEntry:
    rule: str
    path: str
    symbol: str
    justification: str = ""

    def key(self):
        return (self.rule, self.path, self.symbol)


@dataclass
class Baseline:
    entries: list = field(default_factory=list)
    path: str = ""

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(entries=[], path=path)
        with open(path) as f:
            data = json.load(f)
        entries = [
            BaselineEntry(
                rule=e["rule"], path=e["path"], symbol=e["symbol"],
                justification=e.get("justification", ""),
            )
            for e in data.get("entries", [])
        ]
        return cls(entries=entries, path=path)

    def save(self, path: str = "") -> None:
        path = path or self.path
        data = {
            "comment": (
                "Grandfathered tpurx-lint findings. Every entry needs a "
                "one-line justification reviewed by a human; new code must "
                "not be added here — fix it or suppress inline with a reason."
            ),
            "entries": [
                {
                    "rule": e.rule,
                    "path": e.path,
                    "symbol": e.symbol,
                    "justification": e.justification,
                }
                for e in sorted(self.entries, key=BaselineEntry.key)
            ],
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")

    def _index(self):
        idx = set()
        for e in self.entries:
            idx.add(e.key())
        return idx

    def split(self, findings):
        """Partition findings into (new, baselined)."""
        idx = self._index()
        new, old = [], []
        for f in findings:
            if (f.rule, f.path, f.symbol) in idx:
                old.append(f)
            else:
                new.append(f)
        return new, old

    def unjustified(self):
        return [e for e in self.entries if not e.justification.strip()]

    def stale(self, findings):
        """Entries no longer matched by any finding (burned down or drifted)."""
        live = {(f.rule, f.path, f.symbol) for f in findings}
        return [e for e in self.entries if e.key() not in live]

    @classmethod
    def from_findings(cls, findings, path: str,
                      justifications: dict | None = None) -> "Baseline":
        justifications = justifications or {}
        seen = {}
        for f in findings:
            key = (f.rule, f.path, f.symbol)
            if key not in seen:
                seen[key] = BaselineEntry(
                    rule=f.rule, path=f.path, symbol=f.symbol,
                    justification=justifications.get(key, ""),
                )
        return cls(entries=list(seen.values()), path=path)
