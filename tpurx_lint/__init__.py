"""tpurx-lint: pluggable resiliency static analysis for the tpu-resiliency repo.

The invariants that kill resiliency systems — an unbounded blocking wait in a
recovery path, a hand-rolled retry loop bypassing the shared jitter/deadline
policy, a non-daemon thread wedging abort teardown, a swallowed exception in a
fault handler — are machine-enforceable.  This package is the single home for
those checks: a single-parse-per-file rule engine with stable rule IDs
(TPURX001…), inline ``# tpurx: disable=<RULE> -- <reason>`` suppressions
(reason required), a checked-in baseline for grandfathered findings, text/JSON
output, and a ``python -m tpurx_lint`` CLI.

See ``docs/lint.md`` for the rule catalog and the suppression/baseline policy.
"""

from .findings import Finding
from .engine import LintResult, Project, run_lint
from .registry import all_rules, get_rule

__version__ = "1.0"

__all__ = [
    "Finding",
    "LintResult",
    "Project",
    "run_lint",
    "all_rules",
    "get_rule",
    "__version__",
]
