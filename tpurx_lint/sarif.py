"""SARIF 2.1.0 output for CI annotation pipelines.

One run, one driver (``tpurx-lint``), stable rule IDs as SARIF
``reportingDescriptor``s, one ``result`` per finding with a region and a
content-keyed partial fingerprint (same (rule, path, stripped-line) key the
baseline uses, so fingerprints survive line drift exactly like baseline
entries do).  Baselined findings are emitted with ``suppressions`` so SARIF
viewers show them as reviewed rather than hiding them.
"""

from __future__ import annotations

import hashlib

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _fingerprint(f) -> str:
    key = f"{f.rule}|{f.path}|{f.symbol}".encode()
    return hashlib.sha256(key).hexdigest()[:32]


def _result(f, level: str, suppressed: bool = False) -> dict:
    out = {
        "ruleId": f.rule,
        "level": level,
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, f.line)},
            },
        }],
        "partialFingerprints": {"tpurxContentKey/v1": _fingerprint(f)},
    }
    if suppressed:
        out["suppressions"] = [{
            "kind": "external",
            "justification": "grandfathered in tpurx_lint/baseline.json",
        }]
    return out


def render(result, rules, root: str) -> dict:
    """SARIF log dict for a ``LintResult`` (json.dumps it yourself)."""
    driver_rules = []
    for r in rules:
        driver_rules.append({
            "id": r.rule_id,
            "name": r.name,
            "shortDescription": {"text": r.name},
            "fullDescription": {"text": " ".join(r.rationale.split())},
            "defaultConfiguration": {"level": "error"},
        })
    for meta_id, text in (("TPURX900", "malformed or reasonless suppression "
                                       "directive"),
                          ("TPURX999", "unparseable file")):
        driver_rules.append({
            "id": meta_id,
            "name": meta_id.lower(),
            "shortDescription": {"text": text},
            "defaultConfiguration": {"level": "error"},
        })

    results = [_result(f, "error") for f in result.findings]
    results += [_result(f, "error") for f in result.parse_errors]
    results += [_result(f, "note", suppressed=True)
                for f in result.baselined]

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "tpurx-lint",
                "informationUri": "https://example.invalid/tpu-resiliency/docs/lint.md",
                "rules": driver_rules,
            }},
            "originalUriBaseIds": {
                "SRCROOT": {"uri": f"file://{root.rstrip('/')}/"},
            },
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }
