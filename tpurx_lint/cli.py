"""``python -m tpurx_lint`` / ``tpurx-lint`` command line.

Exit codes: 0 clean (baselined findings allowed), 1 findings (or baseline
hygiene failures: unjustified or stale entries), 2 bad invocation.
"""

from __future__ import annotations

import argparse
import json
import sys

from .baseline import Baseline, DEFAULT_BASELINE
from .engine import run_lint
from .registry import all_rules


def _print(*parts):
    # stdout IS the interface of this CLI
    sys.stdout.write(" ".join(str(p) for p in parts) + "\n")


def _jobs_arg(val: str):
    if val == "auto":
        return "auto"
    try:
        return int(val)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--jobs expects an integer or 'auto', got {val!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurx-lint",
        description="Resiliency static analysis for the tpu-resiliency repo.",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: tpu_resiliency tests "
                         "benchmarks tpurx_lint)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths (default: cwd)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--jobs", type=_jobs_arg, default="auto", metavar="N",
                    help="parallel per-file lint processes ('auto' = cpu "
                         "count, 1 = serial; whole-program tier always runs "
                         "once in the parent)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "(justifications must then be filled in by hand)")
    ap.add_argument("--witness", action="append", metavar="FILE",
                    help="runtime lock-order sanitizer witness JSONL "
                         "(TPURX_SANITIZE=1 output; repeatable) — promotes "
                         "static TPURX011 cycles to CONFIRMED or prunes "
                         "false positives")
    ap.add_argument("--rule", action="append", dest="rules", metavar="TPURXnnn",
                    help="run only the given rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also list findings matched by the baseline")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            _print(f"{rule.rule_id}  {rule.name}")
            _print(f"    scope: {', '.join(rule.scope)}"
                   + (f"  (exempt: {', '.join(rule.exclude)})" if rule.exclude else ""))
            _print(f"    {rule.rationale.strip()}")
        return 0

    result = run_lint(
        paths=args.paths or None,
        root=args.root,
        baseline_path=args.baseline,
        use_baseline=not args.no_baseline,
        rule_ids=args.rules,
        jobs=args.jobs,
        witness_path=args.witness,
    )

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        old = Baseline.load(path)
        carried = {e.key(): e.justification for e in old.entries}
        bl = Baseline.from_findings(result.findings + result.baselined, path)
        for e in bl.entries:
            e.justification = carried.get(e.key(), "")
        bl.save(path)
        _print(f"wrote {len(bl.entries)} entries to {path} "
               f"(fill in any empty justifications before committing)")
        return 0

    if args.format == "sarif":
        from .sarif import render
        import os
        root = os.path.abspath(args.root or os.getcwd())
        _print(json.dumps(render(result, all_rules(), root), indent=2))
    elif args.format == "json":
        _print(json.dumps({
            "findings": [f.to_dict() for f in result.findings],
            "baselined": [f.to_dict() for f in result.baselined],
            "parse_errors": [f.to_dict() for f in result.parse_errors],
            "witness_pruned": [f.to_dict() for f in result.witness_pruned],
            "stale_baseline": [
                {"rule": e.rule, "path": e.path, "symbol": e.symbol}
                for e in result.stale_baseline
            ],
            "unjustified_baseline": [
                {"rule": e.rule, "path": e.path, "symbol": e.symbol}
                for e in result.unjustified_baseline
            ],
            "ok": result.ok and not result.stale_baseline
                  and not result.unjustified_baseline,
        }, indent=2))
    else:
        for f in result.parse_errors:
            _print(f"{f.location()}: {f.rule} {f.message}")
        for f in result.findings:
            _print(f"{f.location()}: {f.rule} {f.message}")
        if args.show_baselined:
            for f in result.baselined:
                _print(f"{f.location()}: {f.rule} [baselined] {f.message}")
        for f in result.witness_pruned:
            _print(f"{f.location()}: {f.rule} [pruned by witness] {f.message}")
        for e in result.unjustified_baseline:
            _print(f"{e.path}: baseline entry for {e.rule} has no "
                   f"justification ({e.symbol!r})")
        for e in result.stale_baseline:
            _print(f"{e.path}: stale baseline entry for {e.rule} "
                   f"({e.symbol!r}) — offending line is gone; remove it")
        n = len(result.findings)
        b = len(result.baselined)
        _print(f"{n} finding(s), {b} baselined, "
               f"{len(result.parse_errors)} parse error(s)"
               + (f", {len(result.witness_pruned)} pruned by witness"
                  if result.witness_pruned else ""))

    failed = (not result.ok or result.stale_baseline
              or result.unjustified_baseline)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
