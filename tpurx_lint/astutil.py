"""Shared AST predicates used by several rules."""

from __future__ import annotations

import ast


def attr_chain(node) -> str:
    """Dotted-name text of a Name/Attribute chain ('' when not a plain chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def call_name(call: ast.Call) -> str:
    """Dotted name of the called object ('' when dynamic)."""
    return attr_chain(call.func)


def keyword(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def is_none_constant(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def has_finite_timeout(call: ast.Call, kwarg: str = "timeout",
                       positional_ok: bool = True) -> bool:
    """True when the call carries a timeout that is not literally None.

    Any non-None expression counts — the linter can't evaluate it, and the
    point of the rule is that SOMEONE chose a bound, not what the bound is.
    """
    kw = keyword(call, kwarg)
    if kw is not None:
        return not is_none_constant(kw)
    if positional_ok and call.args:
        return not is_none_constant(call.args[0])
    return False


def contains_call_to(node, names: set) -> ast.Call | None:
    """First descendant Call whose dotted name is in `names`."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and call_name(sub) in names:
            return sub
    return None


def enclosing_function(pf, node):
    """Nearest FunctionDef/AsyncFunctionDef ancestor (or None)."""
    for anc in pf.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def enclosing_class(pf, node):
    for anc in pf.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def class_base_names(cls: ast.ClassDef) -> set:
    out = set()
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.add(b.id)
        elif isinstance(b, ast.Attribute):
            out.add(b.attr)
    return out


def body_is_swallow(handler: ast.ExceptHandler) -> bool:
    """Handler body is only `pass` / `...` / a docstring constant."""
    return all(
        isinstance(s, ast.Pass)
        or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        for s in handler.body
    )
