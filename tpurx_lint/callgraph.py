"""Whole-program symbol table + module-qualified call graph.

Built once per lint run from the already-parsed ``Project`` (no re-parsing)
and shared by every interprocedural rule: TPURX011 (lock-order), TPURX012
(deadline propagation), TPURX013 (store-key lifecycle).  The graph is
deliberately conservative — it resolves only what it can prove:

- bare-name calls to same-module functions and ``from x import f`` imports;
- ``self.m()`` to methods of the same class and its repo-resolvable bases;
- ``mod.f()`` through ``import mod`` / ``import pkg.mod as alias``;
- ``ClassName.m()`` and ``ClassName(...).m()``;
- ``self.attr.m()`` / ``var.m()`` where the attribute/local was assigned from
  a repo-class constructor (one level of flow-insensitive type inference).

Anything dynamic resolves to nothing: the rules built on top over-report
nothing from edges that do not exist, and the runtime sanitizer witness
(``tpurx-lint --witness``) closes the gap from the other side.

Qualified names are dotted: ``pkg.mod.func`` and ``pkg.mod.Class.method``.
Lock declarations (``self.x = threading.Lock()``, module-level ``X =
threading.Condition()``) are indexed here too, because lock identity and the
call graph must agree on ownership.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .astutil import attr_chain, call_name

_LOCK_KINDS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}


def module_name(rel: str) -> str:
    """Repo-relative posix path -> dotted module name."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


@dataclass
class LockDecl:
    """One lock/condition declaration site: the unit of lock identity.

    Granularity is (owner, attr) — every instance of a class shares one
    identity, which is exactly what the runtime witness keys on (creation
    site), so static and runtime views compare 1:1.
    """

    owner: str              # class qname or module name
    attr: str               # attribute / module-level name
    kind: str               # Lock | RLock | Condition
    rel: str
    line: int
    wraps: str | None = None   # attr of the lock a Condition was built over

    @property
    def lock_id(self) -> str:
        return f"{self.owner}.{self.attr}"

    @property
    def site(self) -> str:
        return f"{self.rel}:{self.line}"

    @property
    def reentrant(self) -> bool:
        # Condition() wraps an RLock by default; Condition(lock) aliases
        # `lock` and is resolved to it before edges are built.
        return self.kind in ("RLock", "Condition")


@dataclass
class FunctionInfo:
    qname: str
    node: ast.AST           # FunctionDef / AsyncFunctionDef
    pf: object              # ParsedFile
    module: str
    cls: str | None = None  # owning class qname

    # deadline-ish parameter names, in signature order
    deadline_params: list = field(default_factory=list)


@dataclass
class ClassInfo:
    qname: str
    node: ast.ClassDef
    pf: object
    module: str
    bases: list = field(default_factory=list)       # resolvable base qnames
    methods: dict = field(default_factory=dict)     # name -> FunctionInfo
    attr_types: dict = field(default_factory=dict)  # attr -> class qname
    locks: dict = field(default_factory=dict)       # attr -> LockDecl
    param_attrs: dict = field(default_factory=dict)  # __init__ param -> attr


_DEADLINE_HINTS = ("timeout", "deadline")


def is_deadline_param(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in _DEADLINE_HINTS)


def _param_names(node) -> list:
    args = node.args
    names = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    names += [a.arg for a in args.kwonlyargs]
    return names


class CallGraph:
    """Symbol table + call edges for one ``Project``."""

    def __init__(self):
        self.modules: dict = {}      # module name -> ParsedFile
        self.functions: dict = {}    # qname -> FunctionInfo
        self.classes: dict = {}      # qname -> ClassInfo
        self.imports: dict = {}      # module -> {local name -> qualified}
        self.locks: dict = {}        # lock_id -> LockDecl
        self.locks_by_site: dict = {}  # "rel:line" -> LockDecl
        self._callee_cache: dict = {}
        self._local_type_cache: dict = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, project) -> "CallGraph":
        cg = cls()
        for pf in project.files:
            cg.modules[module_name(pf.rel)] = pf
        for mod, pf in cg.modules.items():
            cg._index_module(mod, pf)
        for mod, pf in cg.modules.items():
            cg._collect_imports(mod, pf)
        for ci in list(cg.classes.values()):
            cg._infer_class(ci)
        # constructor-param propagation: `self.x = param` in __init__ picks up
        # the type of what call sites actually pass (back-references like
        # Worker(self) are how cross-module lock cycles arise); two passes so
        # one level of chaining resolves
        for _ in range(2):
            cg._propagate_ctor_params()
        return cg

    def _index_module(self, mod: str, pf) -> None:
        for node in pf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, pf, node, cls=None)
            elif isinstance(node, ast.ClassDef):
                cq = f"{mod}.{node.name}"
                ci = ClassInfo(qname=cq, node=node, pf=pf, module=mod)
                self.classes[cq] = ci
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fi = self._add_function(mod, pf, sub, cls=cq)
                        ci.methods[sub.name] = fi
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    decl = LockDecl(owner=mod, attr=node.targets[0].id,
                                    kind=kind, rel=pf.rel, line=node.lineno)
                    self._add_lock(decl)

    def _add_function(self, mod, pf, node, cls):
        qname = f"{cls}.{node.name}" if cls else f"{mod}.{node.name}"
        fi = FunctionInfo(qname=qname, node=node, pf=pf, module=mod, cls=cls)
        fi.deadline_params = [
            p for p in _param_names(node)
            if p not in ("self", "cls") and is_deadline_param(p)
        ]
        self.functions[qname] = fi
        return fi

    def _add_lock(self, decl: LockDecl) -> None:
        self.locks[decl.lock_id] = decl
        self.locks_by_site[decl.site] = decl

    def _collect_imports(self, mod: str, pf) -> None:
        table: dict = {}
        pkg_parts = mod.split(".")[:-1]
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    table[local] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    src = ".".join(base + ([node.module] if node.module else []))
                else:
                    src = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    table[local] = f"{src}.{alias.name}" if src else alias.name
        self.imports[mod] = table

    def _infer_class(self, ci: ClassInfo) -> None:
        # resolvable bases
        for b in ci.node.bases:
            bq = self._resolve_symbol(ci.module, b)
            if bq in self.classes:
                ci.bases.append(bq)
        # attribute types + lock declarations from `self.x = ...` anywhere
        for node in ast.walk(ci.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name) and t.value.id == "self"):
                continue
            kind = _lock_ctor_kind(node.value)
            if kind:
                wraps = None
                if kind == "Condition" and isinstance(node.value, ast.Call) \
                        and node.value.args:
                    arg = node.value.args[0]
                    chain = attr_chain(arg)
                    if chain.startswith("self."):
                        wraps = chain[5:]
                decl = LockDecl(owner=ci.qname, attr=t.attr, kind=kind,
                                rel=ci.pf.rel, line=node.lineno, wraps=wraps)
                ci.locks[t.attr] = decl
                self._add_lock(decl)
                continue
            if isinstance(node.value, ast.Call):
                cq = self._resolve_symbol(ci.module, node.value.func)
                if cq in self.classes:
                    ci.attr_types[t.attr] = cq
        # `self.x = <param>` inside __init__: remember which param lands in
        # which attribute, so call-site types can be propagated in
        init = ci.methods.get("__init__")
        if init is not None:
            params = set(_param_names(init.node)) - {"self"}
            for node in ast.walk(init.node):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                        and isinstance(node.value, ast.Name)
                        and node.value.id in params):
                    ci.param_attrs[node.value.id] = node.targets[0].attr

    def _propagate_ctor_params(self) -> None:
        for fi in list(self.functions.values()):
            local_types = self._local_types(fi)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                cq = self._resolve_symbol(fi.module, node.func)
                ci = self.classes.get(cq)
                if ci is None or not ci.param_attrs:
                    continue
                init = self.lookup_method(cq, "__init__")
                if init is None:
                    continue
                names = [a.arg for a in init.node.args.args]
                if names and names[0] == "self":
                    names = names[1:]
                bindings = list(zip(names, node.args)) + [
                    (kw.arg, kw.value) for kw in node.keywords if kw.arg]
                for pname, expr in bindings:
                    attr = ci.param_attrs.get(pname)
                    if attr is None or attr in ci.attr_types:
                        continue
                    ptype = self._expr_type(fi, expr, local_types)
                    if ptype:
                        ci.attr_types[attr] = ptype

    def _expr_type(self, fi, expr, local_types) -> str:
        """Class qname of an expression, where provable."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi.cls:
                return fi.cls
            return local_types.get(expr.id, "")
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and fi.cls):
            ci = self.classes.get(fi.cls)
            return (ci.attr_types.get(expr.attr, "") if ci else "")
        if isinstance(expr, ast.Call):
            cq = self._resolve_symbol(fi.module, expr.func)
            return cq if cq in self.classes else ""
        return ""

    # -- resolution --------------------------------------------------------

    def _resolve_symbol(self, mod: str, node) -> str:
        """Qualified name of a Name/Attribute expression in module `mod`."""
        chain = attr_chain(node)
        if not chain or chain.startswith("?"):
            return ""
        head, _, rest = chain.partition(".")
        table = self.imports.get(mod, {})
        if head in table:
            base = table[head]
        elif f"{mod}.{head}" in self.classes or f"{mod}.{head}" in self.functions:
            base = f"{mod}.{head}"
        elif head in self.modules:
            base = head
        else:
            return ""
        return f"{base}.{rest}" if rest else base

    def class_of(self, qname: str):
        return self.classes.get(qname)

    def lookup_method(self, class_qname: str, name: str,
                      _seen=None) -> FunctionInfo | None:
        """Method resolution through repo-resolvable bases."""
        seen = _seen or set()
        if class_qname in seen:
            return None
        seen.add(class_qname)
        ci = self.classes.get(class_qname)
        if ci is None:
            return None
        if name in ci.methods:
            return ci.methods[name]
        for b in ci.bases:
            hit = self.lookup_method(b, name, seen)
            if hit is not None:
                return hit
        return None

    def lookup_lock(self, class_qname: str, attr: str,
                    _seen=None) -> LockDecl | None:
        """Lock attr through bases, resolving Condition(lock) aliasing."""
        seen = _seen or set()
        if class_qname in seen:
            return None
        seen.add(class_qname)
        ci = self.classes.get(class_qname)
        if ci is None:
            return None
        decl = ci.locks.get(attr)
        if decl is not None:
            if decl.wraps and decl.wraps != attr:
                aliased = self.lookup_lock(decl.owner, decl.wraps)
                if aliased is not None:
                    return aliased
            return decl
        for b in ci.bases:
            hit = self.lookup_lock(b, attr, seen)
            if hit is not None:
                return hit
        return None

    def _local_types(self, fi: FunctionInfo) -> dict:
        """var name -> class qname for `v = ClassName(...)` in the body."""
        cached = self._local_type_cache.get(fi.qname)
        if cached is not None:
            return cached
        out = {}
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                cq = self._resolve_symbol(fi.module, node.value.func)
                if cq in self.classes:
                    out[node.targets[0].id] = cq
        self._local_type_cache[fi.qname] = out
        return out

    def resolve_call(self, fi: FunctionInfo, call: ast.Call,
                     local_types: dict | None = None):
        """FunctionInfo of the repo function `call` invokes (or None).

        Returns (callee, via_self): via_self is True when the call provably
        targets the SAME instance (``self.m()``) — lock identity follows.
        """
        func = call.func
        if isinstance(func, ast.Name):
            target = self._resolve_symbol(fi.module, func)
            if target in self.functions:
                return self.functions[target], False
            if target in self.classes:      # ClassName(...) -> __init__
                hit = self.lookup_method(target, "__init__")
                return hit, False
            return None, False
        if not isinstance(func, ast.Attribute):
            return None, False

        recv, meth = func.value, func.attr
        # self.m() -> same class (and bases)
        if isinstance(recv, ast.Name) and recv.id == "self" and fi.cls:
            hit = self.lookup_method(fi.cls, meth)
            if hit is not None:
                return hit, True
            return None, False
        # self.attr.m() via inferred attribute type
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and fi.cls):
            ci = self.classes.get(fi.cls)
            cq = ci.attr_types.get(recv.attr) if ci else None
            if cq:
                hit = self.lookup_method(cq, meth)
                if hit is not None:
                    return hit, False
            return None, False
        # var.m() via local constructor assignment
        if isinstance(recv, ast.Name):
            if local_types is None:
                local_types = self._local_types(fi)
            cq = local_types.get(recv.id)
            if cq:
                hit = self.lookup_method(cq, meth)
                if hit is not None:
                    return hit, False
        # mod.f() / pkg.mod.f() / ClassName.m() / ClassName(...).m()
        if isinstance(recv, ast.Call):
            cq = self._resolve_symbol(fi.module, recv.func)
            if cq in self.classes:
                hit = self.lookup_method(cq, meth)
                if hit is not None:
                    return hit, False
            return None, False
        target = self._resolve_symbol(fi.module, func)
        if target in self.functions:
            return self.functions[target], False
        if target in self.classes:
            hit = self.lookup_method(target, "__init__")
            return hit, False
        return None, False

    def callees(self, qname: str) -> list:
        """[(callee_qname, line, via_self)] for every resolvable call."""
        cached = self._callee_cache.get(qname)
        if cached is not None:
            return cached
        fi = self.functions.get(qname)
        out = []
        if fi is not None:
            local_types = self._local_types(fi)
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    callee, via_self = self.resolve_call(fi, node, local_types)
                    if callee is not None and callee.qname != qname:
                        out.append((callee.qname, node.lineno, via_self))
        self._callee_cache[qname] = out
        return out

    def closure(self, qname: str, max_depth: int = 12) -> set:
        """Every function qname reachable from `qname` (inclusive)."""
        seen = {qname}
        frontier = [(qname, 0)]
        while frontier:
            cur, d = frontier.pop()
            if d >= max_depth:
                continue
            for callee, _line, _vs in self.callees(cur):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append((callee, d + 1))
        return seen


def _lock_ctor_kind(value) -> str | None:
    """'Lock'/'RLock'/'Condition' when `value` constructs one, else None."""
    if not isinstance(value, ast.Call):
        return None
    return _LOCK_KINDS.get(call_name(value))
