"""Engine: discover files, parse each exactly once, run every rule, apply
suppressions, split against the baseline."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from .baseline import Baseline, DEFAULT_BASELINE
from .findings import Finding
from .registry import all_rules
from .source import ParsedFile

_SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules", ".venv"}


@dataclass
class Project:
    """Everything the rules may look at: parsed files plus the repo root
    (finalize passes read non-Python artifacts like docs through it)."""

    root: str
    files: list = field(default_factory=list)   # list[ParsedFile]

    def file(self, rel: str):
        for pf in self.files:
            if pf.rel == rel:
                return pf
        return None

    def read_text(self, rel: str) -> str | None:
        path = os.path.join(self.root, rel)
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return None


@dataclass
class LintResult:
    findings: list = field(default_factory=list)       # non-baselined
    baselined: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)   # list[Finding] TPURX999
    stale_baseline: list = field(default_factory=list)
    unjustified_baseline: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def all_raw(self):
        return self.findings + self.baselined


def discover(paths, root: str):
    """Yield (abs, rel) for every .py file under the given paths."""
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        ap = os.path.abspath(ap)
        if os.path.isfile(ap):
            if ap.endswith(".py") and ap not in seen:
                seen.add(ap)
                yield ap, os.path.relpath(ap, root).replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                f = os.path.join(dirpath, fn)
                if f in seen:
                    continue
                seen.add(f)
                yield f, os.path.relpath(f, root).replace(os.sep, "/")


def parse_project(paths, root: str) -> tuple:
    project = Project(root=os.path.abspath(root))
    errors = []
    for path, rel in discover(paths, root):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            project.files.append(ParsedFile.parse(path, rel, text))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(Finding(
                rule="TPURX999", path=rel,
                line=getattr(e, "lineno", None) or 1,
                message=f"unparseable: {e}"))
    return project, errors


def run_lint(paths=None, root=None, baseline_path=None,
             use_baseline: bool = True, rule_ids=None) -> LintResult:
    """Run every (or the selected) rule over `paths` relative to `root`.

    Suppression directives are applied first (their misuse surfaces as
    TPURX900), then the baseline splits what's left into new vs grandfathered.
    """
    root = os.path.abspath(root or os.getcwd())
    paths = list(paths) if paths else ["tpu_resiliency", "tests", "benchmarks"]
    project, parse_errors = parse_project(paths, root)

    rules = all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        rules = [r for r in rules if r.rule_id in wanted]

    raw = []
    for pf in project.files:
        raw.extend(pf.directive_findings)
        for rule in rules:
            if not rule.applies_to(pf.rel):
                continue
            raw.extend(rule.check_file(pf))
    for rule in rules:
        raw.extend(rule.finalize(project))

    kept = []
    for f in raw:
        pf = project.file(f.path)
        if (pf is not None and f.rule != "TPURX900"
                and pf.is_suppressed(f.rule, f.line)):
            continue
        kept.append(f)
    kept.sort(key=Finding.sort_key)

    result = LintResult(parse_errors=parse_errors)
    if use_baseline:
        bl = Baseline.load(baseline_path or DEFAULT_BASELINE)
        result.findings, result.baselined = bl.split(kept)
        # stale/justification audits only make sense over a full-rule run
        if not rule_ids:
            result.stale_baseline = bl.stale(kept)
            result.unjustified_baseline = bl.unjustified()
    else:
        result.findings = kept
    return result
