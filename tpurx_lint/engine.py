"""Engine: discover files, parse each exactly once, run every rule, apply
suppressions, split against the baseline.

Two tiers run over the shared parse products:

- per-file rules (``check_file``) — embarrassingly parallel; ``jobs`` fans
  them out across processes (each worker re-parses only its own slice; the
  parent's parse is reused for everything else);
- the whole-program tier (``finalize``) — runs once in the parent over the
  full ``Project``, with the module-qualified call graph built exactly once
  (``Project.callgraph()``) and shared by every interprocedural rule.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from .baseline import Baseline, DEFAULT_BASELINE
from .findings import Finding
from .registry import all_rules
from .source import ParsedFile

_SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules", ".venv"}

DEFAULT_PATHS = ["tpu_resiliency", "tests", "benchmarks", "tpurx_lint"]


@dataclass
class Project:
    """Everything the rules may look at: parsed files plus the repo root
    (finalize passes read non-Python artifacts like docs through it)."""

    root: str
    files: list = field(default_factory=list)   # list[ParsedFile]
    witness: object = None                      # Witness or None
    witness_pruned: list = field(default_factory=list)
    _cg: object = None

    def file(self, rel: str):
        for pf in self.files:
            if pf.rel == rel:
                return pf
        return None

    def callgraph(self):
        """The whole-program call graph, built once and cached."""
        if self._cg is None:
            from .callgraph import CallGraph
            self._cg = CallGraph.build(self)
        return self._cg

    def read_text(self, rel: str) -> str | None:
        path = os.path.join(self.root, rel)
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return None


@dataclass
class LintResult:
    findings: list = field(default_factory=list)       # non-baselined
    baselined: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)   # list[Finding] TPURX999
    stale_baseline: list = field(default_factory=list)
    unjustified_baseline: list = field(default_factory=list)
    witness_pruned: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def all_raw(self):
        return self.findings + self.baselined


def discover(paths, root: str):
    """Yield (abs, rel) for every .py file under the given paths."""
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        ap = os.path.abspath(ap)
        if os.path.isfile(ap):
            if ap.endswith(".py") and ap not in seen:
                seen.add(ap)
                yield ap, os.path.relpath(ap, root).replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                f = os.path.join(dirpath, fn)
                if f in seen:
                    continue
                seen.add(f)
                yield f, os.path.relpath(f, root).replace(os.sep, "/")


def parse_project(paths, root: str) -> tuple:
    project = Project(root=os.path.abspath(root))
    errors = []
    for path, rel in discover(paths, root):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            project.files.append(ParsedFile.parse(path, rel, text))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(Finding(
                rule="TPURX999", path=rel,
                line=getattr(e, "lineno", None) or 1,
                message=f"unparseable: {e}"))
    return project, errors


def resolve_jobs(jobs) -> int:
    """Normalize the jobs knob: None/1 -> serial; 'auto'/0 -> cpu count."""
    if jobs in ("auto", 0):
        return max(1, os.cpu_count() or 1)
    if jobs is None:
        return 1
    return max(1, int(jobs))


def _worker_check_files(args):
    """Pool worker: re-parse a slice of files, run per-file rules only.

    Receives (rel, text) pairs — texts were already read by the parent, so
    workers never touch the filesystem; directive findings and suppression
    application stay in the parent (which has its own parse of everything).
    """
    batch, rule_ids = args
    rules = all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        rules = [r for r in rules if r.rule_id in wanted]
    out = []
    for rel, text in batch:
        try:
            pf = ParsedFile.parse(rel, rel, text)
        except (SyntaxError, ValueError):
            continue   # parent already reported TPURX999
        for rule in rules:
            if rule.applies_to(rel):
                out.extend(rule.check_file(pf))
    return out


def _run_per_file_parallel(project, rules, rule_ids, jobs: int) -> list:
    import multiprocessing

    batches = [[] for _ in range(jobs)]
    for i, pf in enumerate(project.files):
        batches[i % jobs].append((pf.rel, pf.text))
    ctx = multiprocessing.get_context("fork")
    with ctx.Pool(processes=jobs) as pool:
        parts = pool.map(
            _worker_check_files,
            [(batch, rule_ids) for batch in batches if batch])
    raw = []
    for part in parts:
        raw.extend(part)
    return raw


def run_lint(paths=None, root=None, baseline_path=None,
             use_baseline: bool = True, rule_ids=None,
             jobs=None, witness_path=None) -> LintResult:
    """Run every (or the selected) rule over `paths` relative to `root`.

    Suppression directives are applied first (their misuse surfaces as
    TPURX900), then the baseline splits what's left into new vs
    grandfathered.  ``jobs`` fans the per-file tier across processes
    ('auto'/0 = cpu count); the whole-program tier always runs once in the
    parent.  ``witness_path`` feeds a runtime sanitizer witness (or a list
    of them) to the lock-order rule for confirm/prune verdicts.
    """
    root = os.path.abspath(root or os.getcwd())
    paths = list(paths) if paths else list(DEFAULT_PATHS)
    project, parse_errors = parse_project(paths, root)

    if witness_path:
        from .witness import Witness
        project.witness = Witness.load(witness_path, root)

    rules = all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        rules = [r for r in rules if r.rule_id in wanted]

    njobs = resolve_jobs(jobs)
    raw = []
    for pf in project.files:
        raw.extend(pf.directive_findings)
    if njobs > 1 and len(project.files) > 1:
        raw.extend(_run_per_file_parallel(project, rules, rule_ids, njobs))
    else:
        for pf in project.files:
            for rule in rules:
                if rule.applies_to(pf.rel):
                    raw.extend(rule.check_file(pf))
    for rule in rules:
        raw.extend(rule.finalize(project))

    kept = []
    for f in raw:
        pf = project.file(f.path)
        if (pf is not None and f.rule != "TPURX900"
                and pf.is_suppressed(f.rule, f.line)):
            continue
        kept.append(f)
    kept.sort(key=Finding.sort_key)

    result = LintResult(parse_errors=parse_errors,
                        witness_pruned=list(project.witness_pruned))
    if use_baseline:
        bl = Baseline.load(baseline_path or DEFAULT_BASELINE)
        result.findings, result.baselined = bl.split(kept)
        # stale/justification audits only make sense over a full-rule run,
        # and staleness only for files this run actually re-checked or that
        # are gone entirely (a partial-path run must not condemn entries it
        # never looked at)
        if not rule_ids:
            parsed = {pf.rel for pf in project.files}
            result.stale_baseline = [
                e for e in bl.stale(kept)
                if e.path in parsed
                or not os.path.exists(os.path.join(root, e.path))]
            result.unjustified_baseline = bl.unjustified()
    else:
        result.findings = kept
    return result
