"""Rule registry: stable IDs, one instance per rule, discovery for the CLI.

A rule is a class with:

- ``rule_id``: stable ``TPURXnnn`` identifier (never reused, never renumbered)
- ``name``: short kebab-case handle shown in reports
- ``rationale``: one-paragraph why (surfaces in ``--list-rules`` and docs)
- ``scope``: tuple of repo-relative path prefixes the rule examines
- ``exclude``: exact repo-relative paths exempt from the rule (the sanctioned
  home of the pattern, e.g. ``utils/retry.py`` for the retry-loop ban)
- ``check_file(pf)``: yield ``Finding``s for one ``ParsedFile``
- ``finalize(project)``: optional cross-file pass after every file is parsed
"""

from __future__ import annotations

_RULES: dict = {}


class Rule:
    rule_id = ""
    name = ""
    rationale = ""
    scope: tuple = ("tpu_resiliency/",)
    exclude: tuple = ()

    def applies_to(self, rel: str) -> bool:
        if rel in self.exclude:
            return False
        return any(rel.startswith(p) for p in self.scope)

    def check_file(self, pf):
        return ()

    def finalize(self, project):
        return ()


def register(cls):
    """Class decorator: instantiate and index by rule id."""
    inst = cls()
    if not inst.rule_id or inst.rule_id in _RULES:
        raise ValueError(f"bad or duplicate rule id: {inst.rule_id!r}")
    _RULES[inst.rule_id] = inst
    return cls


def all_rules():
    _load()
    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(rule_id: str):
    _load()
    return _RULES[rule_id]


def _load():
    if not _RULES:
        from . import rules  # noqa: F401  (imports register every rule)
