"""Finding record shared by every rule, the engine, and the reporters."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``symbol`` is the stripped source line the finding anchors to — baseline
    matching keys on (rule, path, symbol) rather than the line number so that
    unrelated edits above a grandfathered site don't invalidate the baseline.
    """

    rule: str
    path: str           # repo-relative, posix separators
    line: int           # 1-indexed
    message: str
    symbol: str = ""    # stripped source line content at `line`

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "symbol": self.symbol,
        }

    @staticmethod
    def sort_key(f: "Finding"):
        return (f.path, f.line, f.rule)
