"""TPURX010: every TPURX_* knob is declared once, typed, defaulted, and
documented — reads go through the utils/env.py registry.

54 knobs accreted over seven PRs, each read site re-deciding its own default
and parse ("!= '0'" here, "== '1'" there).  The registry gives each knob one
name, one type, one default, one doc line; this rule bans literal TPURX_*
environment reads everywhere else and cross-checks the registry against
docs/configuration.md.
"""

from __future__ import annotations

import ast

from ..astutil import attr_chain, call_name
from ..findings import Finding
from ..registry import Rule, register

ENV_MODULE = "tpu_resiliency/utils/env.py"
DOC_PATH = "docs/configuration.md"


def _module_string_consts(tree) -> dict:
    """Module-level NAME = "literal" bindings (the ENV_FOO = "TPURX_FOO"
    idiom) so reads through the constant are still attributed to the knob."""
    out = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _tpurx_literal_in(expr, consts) -> str:
    """First string (constant or resolved module constant) starting with
    TPURX_ inside the key expression."""
    for sub in ast.walk(expr):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and sub.value.startswith("TPURX_")):
            return sub.value
        if isinstance(sub, ast.Name):
            val = consts.get(sub.id, "")
            if val.startswith("TPURX_"):
                return val
    return ""


def _env_read_key(node: ast.AST, consts) -> str:
    """TPURX key literal when `node` reads the environment, else ''."""
    if isinstance(node, ast.Call):
        dotted = call_name(node)
        if dotted in ("os.getenv", "os.environ.get") and node.args:
            return _tpurx_literal_in(node.args[0], consts)
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if attr_chain(node.value) == "os.environ":
            return _tpurx_literal_in(node.slice, consts)
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)):
        if attr_chain(node.comparators[0]) == "os.environ":
            return _tpurx_literal_in(node.left, consts)
    return ""


def declared_knob_names(env_pf) -> list:
    """(name, lineno) for every Knob("NAME", ...) literal in env.py."""
    out = []
    for node in ast.walk(env_pf.tree):
        if (isinstance(node, ast.Call)
                and call_name(node).split(".")[-1] == "Knob"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.args[0].value, node.lineno))
    return out


@register
class EnvRegistryRule(Rule):
    rule_id = "TPURX010"
    name = "env-registry"
    rationale = (
        "All TPURX_* environment reads route through the typed registry in "
        "utils/env.py (one declared name/type/default/doc per knob); every "
        "declared knob must be cataloged in docs/configuration.md."
    )
    scope = ("tpu_resiliency/", "benchmarks/")
    exclude = (ENV_MODULE,)

    def check_file(self, pf):
        consts = _module_string_consts(pf.tree)
        for node in ast.walk(pf.tree):
            key = _env_read_key(node, consts)
            if key:
                yield pf.finding(
                    self.rule_id, node,
                    f"raw environment read of {key!r} — declare the knob in "
                    f"utils/env.py and read it through the registry",
                )

    def finalize(self, project):
        env_pf = project.file(ENV_MODULE)
        if env_pf is None:
            return
        declared = declared_knob_names(env_pf)
        seen = {}
        for name, lineno in declared:
            if name in seen:
                yield env_pf.finding(
                    self.rule_id, lineno,
                    f"knob {name} declared more than once (first at line "
                    f"{seen[name]})",
                )
            else:
                seen[name] = lineno
        doc = project.read_text(DOC_PATH)
        if doc is None:
            yield Finding(
                rule=self.rule_id, path=DOC_PATH, line=1,
                message=f"{DOC_PATH} is missing — regenerate it with "
                        f"'python -m tpu_resiliency.utils.env --write'",
            )
            return
        for name, lineno in declared:
            if name not in doc:
                yield env_pf.finding(
                    self.rule_id, lineno,
                    f"knob {name} is not documented in {DOC_PATH} — "
                    f"regenerate it with 'python -m tpu_resiliency.utils.env "
                    f"--write'",
                )
