"""TPURX010: every TPURX_* knob is declared once, typed, defaulted, and
documented — reads go through the utils/env.py registry, and runtime
WRITES go through the policy actuator.

54 knobs accreted over seven PRs, each read site re-deciding its own default
and parse ("!= '0'" here, "== '1'" there).  The registry gives each knob one
name, one type, one default, one doc line; this rule bans literal TPURX_*
environment reads everywhere else and cross-checks the registry against
docs/configuration.md.

The write ban exists because the adaptive policy engine
(tpu_resiliency/policy/) is the single sanctioned author of runtime knob
changes: it goes through ``env.set_runtime_override`` so every change is
typed, journaled, and visible to ``Knob.raw()`` without racing child
process environments.  A stray ``os.environ["TPURX_..."] = ...`` anywhere
else silently fights the controller (the override layer shadows it) and
never reaches the decision journal.  Identity republication — the
launcher stamping ``TPURX_RANK``/``TPURX_WORLD_SIZE`` after a mesh
shrink, the straggler detector publishing its shm name — is exempt via
``WRITE_EXEMPT``: those are facts children must inherit through the real
environment, not resiliency knobs, and ``finalize`` cross-checks that
every exempt key really is identity-group or publisher-documented
("set by ...") in the registry.
"""

from __future__ import annotations

import ast

from ..astutil import attr_chain, call_name
from ..findings import Finding
from ..registry import Rule, register

ENV_MODULE = "tpu_resiliency/utils/env.py"
DOC_PATH = "docs/configuration.md"
POLICY_PREFIX = "tpu_resiliency/policy/"

# Keys legitimately written to the REAL environment outside policy/: rank
# identity republished by the launcher for child inheritance, and
# publisher-owned plumbing whose registry doc declares its writer
# ("set by the ...").  finalize() verifies each entry still qualifies.
WRITE_EXEMPT = (
    "TPURX_RANK",
    "TPURX_LOCAL_RANK",
    "TPURX_WORLD_SIZE",
    "TPURX_OPRING_SHM",
)


def _module_string_consts(tree) -> dict:
    """Module-level NAME = "literal" bindings (the ENV_FOO = "TPURX_FOO"
    idiom) so reads through the constant are still attributed to the knob."""
    out = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _tpurx_literal_in(expr, consts) -> str:
    """First string (constant or resolved module constant) starting with
    TPURX_ inside the key expression."""
    for sub in ast.walk(expr):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and sub.value.startswith("TPURX_")):
            return sub.value
        if isinstance(sub, ast.Name):
            val = consts.get(sub.id, "")
            if val.startswith("TPURX_"):
                return val
    return ""


def _env_read_key(node: ast.AST, consts) -> str:
    """TPURX key literal when `node` reads the environment, else ''."""
    if isinstance(node, ast.Call):
        dotted = call_name(node)
        if dotted in ("os.getenv", "os.environ.get") and node.args:
            return _tpurx_literal_in(node.args[0], consts)
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if attr_chain(node.value) == "os.environ":
            return _tpurx_literal_in(node.slice, consts)
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)):
        if attr_chain(node.comparators[0]) == "os.environ":
            return _tpurx_literal_in(node.left, consts)
    return ""


def _env_write_key(node: ast.AST, consts) -> str:
    """TPURX key literal when `node` MUTATES the environment, else ''."""
    if isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, (ast.Store, ast.Del)):
        if attr_chain(node.value) == "os.environ":
            return _tpurx_literal_in(node.slice, consts)
    if isinstance(node, ast.Call):
        dotted = call_name(node)
        if dotted in ("os.environ.pop", "os.environ.setdefault",
                      "os.putenv") and node.args:
            return _tpurx_literal_in(node.args[0], consts)
        if dotted == "os.environ.update":
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                key = _tpurx_literal_in(arg, consts)
                if key:
                    return key
            for kw in node.keywords:
                if kw.arg and kw.arg.startswith("TPURX_"):
                    return kw.arg
    return ""


def declared_knob_names(env_pf) -> list:
    """(name, lineno) for every Knob("NAME", ...) literal in env.py."""
    out = []
    for node in ast.walk(env_pf.tree):
        if (isinstance(node, ast.Call)
                and call_name(node).split(".")[-1] == "Knob"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.args[0].value, node.lineno))
    return out


def declared_knob_meta(env_pf) -> dict:
    """name -> (doc, group) for every Knob("NAME", ...) literal in env.py
    (doc is the 4th positional arg, group the keyword; '' when absent)."""
    out = {}
    for node in ast.walk(env_pf.tree):
        if (isinstance(node, ast.Call)
                and call_name(node).split(".")[-1] == "Knob"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            doc = ""
            if len(node.args) > 3 and isinstance(node.args[3], ast.Constant) \
                    and isinstance(node.args[3].value, str):
                doc = node.args[3].value
            group = ""
            for kw in node.keywords:
                if kw.arg == "group" and isinstance(kw.value, ast.Constant):
                    group = str(kw.value.value)
            out[node.args[0].value] = (doc, group)
    return out


@register
class EnvRegistryRule(Rule):
    rule_id = "TPURX010"
    name = "env-registry"
    rationale = (
        "All TPURX_* environment reads route through the typed registry in "
        "utils/env.py (one declared name/type/default/doc per knob); every "
        "declared knob must be cataloged in docs/configuration.md; runtime "
        "TPURX_* writes are the policy actuator's monopoly "
        "(env.set_runtime_override) — direct os.environ mutation outside "
        "tpu_resiliency/policy/ is banned except for launcher identity "
        "republication (WRITE_EXEMPT)."
    )
    scope = ("tpu_resiliency/", "benchmarks/")
    exclude = (ENV_MODULE,)

    def check_file(self, pf):
        consts = _module_string_consts(pf.tree)
        in_policy = pf.rel.startswith(POLICY_PREFIX)
        for node in ast.walk(pf.tree):
            key = _env_read_key(node, consts)
            if key:
                yield pf.finding(
                    self.rule_id, node,
                    f"raw environment read of {key!r} — declare the knob in "
                    f"utils/env.py and read it through the registry",
                )
                continue
            key = _env_write_key(node, consts)
            if key and not in_policy and key not in WRITE_EXEMPT:
                yield pf.finding(
                    self.rule_id, node,
                    f"direct os.environ write of {key!r} — runtime knob "
                    f"changes go through env.set_runtime_override (the "
                    f"policy actuator in tpu_resiliency/policy/ is the "
                    f"sanctioned writer)",
                )

    def finalize(self, project):
        env_pf = project.file(ENV_MODULE)
        if env_pf is None:
            return
        # keep the write-exemption list honest: an exempt key must still be
        # identity-group or carry a publisher doc ("set by the ...") — a
        # repurposed knob loses its exemption here, not silently
        meta = declared_knob_meta(env_pf)
        for key in WRITE_EXEMPT:
            if key not in meta:
                continue  # minimal fixtures need not declare every key
            doc, group = meta[key]
            if group != "identity" and "set by" not in doc:
                yield env_pf.finding(
                    self.rule_id, 1,
                    f"WRITE_EXEMPT key {key} is neither identity-group nor "
                    f"publisher-documented ('set by ...') — it no longer "
                    f"qualifies for direct os.environ writes",
                )
        declared = declared_knob_names(env_pf)
        seen = {}
        for name, lineno in declared:
            if name in seen:
                yield env_pf.finding(
                    self.rule_id, lineno,
                    f"knob {name} declared more than once (first at line "
                    f"{seen[name]})",
                )
            else:
                seen[name] = lineno
        doc = project.read_text(DOC_PATH)
        if doc is None:
            yield Finding(
                rule=self.rule_id, path=DOC_PATH, line=1,
                message=f"{DOC_PATH} is missing — regenerate it with "
                        f"'python -m tpu_resiliency.utils.env --write'",
            )
            return
        for name, lineno in declared:
            if name not in doc:
                yield env_pf.finding(
                    self.rule_id, lineno,
                    f"knob {name} is not documented in {DOC_PATH} — "
                    f"regenerate it with 'python -m tpu_resiliency.utils.env "
                    f"--write'",
                )
