"""TPURX004: cross-rank gather rounds route through the reduction tree."""

from __future__ import annotations

import ast

from ..registry import Rule, register

_STORE_READ_ATTRS = {"multi_get", "get", "try_get"}


def _range_references_world_size(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Name) and call.func.id == "range"):
        return False
    for arg in call.args:
        for node in ast.walk(arg):
            if isinstance(node, ast.Name) and node.id == "world_size":
                return True
            if isinstance(node, ast.Attribute) and node.attr == "world_size":
                return True
    return False


@register
class FlatGatherRule(Rule):
    rule_id = "TPURX004"
    name = "flat-gather"
    rationale = (
        "A direct all-ranks-to-one gather (one store key per rank of the "
        "world) makes rank 0 and the owning shard an O(N) hotspot — route "
        "the round through store/tree.py's tree_gather so rank-0 inbound "
        "stays O(fanout)."
    )
    scope = ("tpu_resiliency/",)
    exclude = (
        # the sanctioned reduction-tree helper itself
        "tpu_resiliency/store/tree.py",
        # post-mortem reads of possibly-dead ranks: no collective possible
        "tpu_resiliency/attribution/trace_analyzer.py",
        # single-process emulation moving BULK blob bytes, not control metadata
        "tpu_resiliency/checkpointing/local/ici_replication.py",
    )

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            # shape 1: multi_get(<comprehension over range(world_size)>)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "multi_get"
            ):
                for arg in node.args:
                    comps = [
                        c
                        for sub in ast.walk(arg)
                        if isinstance(sub, (ast.ListComp, ast.GeneratorExp,
                                            ast.SetComp))
                        for c in sub.generators
                    ]
                    if any(
                        isinstance(c.iter, ast.Call)
                        and _range_references_world_size(c.iter)
                        for c in comps
                    ):
                        yield pf.finding(
                            self.rule_id, node,
                            "multi_get over range(world_size) — flat gather; "
                            "route the round through tree_gather",
                        )
            # shape 2: store reads inside `for r in range(world_size):`
            if (
                isinstance(node, ast.For)
                and isinstance(node.iter, ast.Call)
                and _range_references_world_size(node.iter)
            ):
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _STORE_READ_ATTRS
                        and isinstance(sub.func.value, (ast.Name, ast.Attribute))
                        and "store" in ast.dump(sub.func.value).lower()
                    ):
                        yield pf.finding(
                            self.rule_id, sub,
                            f"store .{sub.func.attr} inside a "
                            f"range(world_size) loop — flat gather; route the "
                            f"round through tree_gather",
                        )
