"""TPURX001: no bare print() in library modules."""

from __future__ import annotations

import ast

from ..registry import Rule, register

# argparse mains whose stdout IS the interface
CLI_ALLOWLIST = (
    "tpu_resiliency/straggler/inspect.py",
    "tpu_resiliency/utils/shm_janitor.py",
    "tpu_resiliency/health/device.py",
    "tpu_resiliency/fault_tolerance/per_cycle_logs.py",
    "tpu_resiliency/telemetry/trace.py",
)


@register
class BarePrintRule(Rule):
    rule_id = "TPURX001"
    name = "bare-print"
    rationale = (
        "A bare print() in a library module bypasses rank prefixes, the log "
        "funnel, and level control — use utils.logging.get_logger, or mark a "
        "genuine argparse CLI with a file-level suppression."
    )
    scope = ("tpu_resiliency/", "tpurx_lint/")
    exclude = CLI_ALLOWLIST

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                yield pf.finding(
                    self.rule_id, node,
                    "bare print() in a library module (use "
                    "utils.logging.get_logger, or suppress file-wide for a "
                    "CLI entry point)",
                )
