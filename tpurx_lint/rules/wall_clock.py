"""TPURX003: liveness stamps derive only from ops/quorum.py clock helpers."""

from __future__ import annotations

import ast

from ..registry import Rule, register

_STAMP_TOKENS = ("stamp", "beat", "timestamp", "heartbeat")


def _target_names(node) -> list:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
    return out


def _calls_wall_clock(expr) -> bool:
    for sub in ast.walk(expr):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in ("time", "time_ns")
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "time"
        ):
            return True
    return False


@register
class WallClockStampRule(Rule):
    rule_id = "TPURX003"
    name = "raw-wall-clock-stamp"
    rationale = (
        "Liveness stamps must derive from ops/quorum.py's clock helpers "
        "(now_stamp_ns / wall_time_s): a raw time.time()-derived stamp "
        "re-decides the epoch/fold/clock-domain contract locally and breaks "
        "the wrap-safe age math every detector shares."
    )
    scope = ("tpu_resiliency/",)
    exclude = ("tpu_resiliency/ops/quorum.py",)

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            names = []
            for t in targets:
                names.extend(_target_names(t))
            if not any(
                tok in name.lower() for name in names for tok in _STAMP_TOKENS
            ):
                continue
            if node.value is not None and _calls_wall_clock(node.value):
                yield pf.finding(
                    self.rule_id, node,
                    "raw time.time()-derived stamp (use quorum.now_stamp_ns / "
                    "quorum.wall_time_s so the epoch and clock-domain "
                    "contract has one home)",
                )
