"""TPURX016: durations are measured on the monotonic clock, never wall time."""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from ..registry import Rule, register

# wall-clock call forms: time.time(), time.time_ns(), datetime.now(),
# datetime.utcnow(), datetime.datetime.now(), ...
_TIME_ATTRS = {"time", "time_ns"}
_DATETIME_ATTRS = {"now", "utcnow"}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_wall_clock_call(node) -> bool:
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
        return False
    attr = node.func.attr
    base = node.func.value
    if attr in _TIME_ATTRS:
        return isinstance(base, ast.Name) and base.id == "time"
    if attr in _DATETIME_ATTRS:
        if isinstance(base, ast.Name):
            return base.id == "datetime"
        return isinstance(base, ast.Attribute) and base.attr == "datetime"
    return False


def _shallow_walk(scope) -> Iterator[ast.AST]:
    """Every node of ``scope`` excluding nested function/lambda bodies —
    each nested scope gets its own pass, so a name bound from a wall clock
    in one function never taints a same-named monotonic stamp in another."""
    body = scope.body if not isinstance(scope, ast.Lambda) else [scope.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _SCOPE_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _wall_names(scope) -> Set[str]:
    """Names bound directly in ``scope`` from a bare wall-clock call."""
    out: Set[str] = set()
    for node in _shallow_walk(scope):
        if isinstance(node, ast.Assign) and _is_wall_clock_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif (
            isinstance(node, ast.AnnAssign)
            and node.value is not None
            and _is_wall_clock_call(node.value)
            and isinstance(node.target, ast.Name)
        ):
            out.add(node.target.id)
    return out


def _operand_is_wall(node, wall_names: Set[str]) -> bool:
    return _is_wall_clock_call(node) or (
        isinstance(node, ast.Name) and node.id in wall_names
    )


@register
class WallClockDurationRule(Rule):
    rule_id = "TPURX016"
    name = "wall-clock-duration"
    rationale = (
        "A duration computed as the difference of time.time() / datetime.now() "
        "readings jumps with NTP steps, leap smearing and manual clock sets — "
        "on a fleet under clock calibration that can turn a deadline check or "
        "a phase measurement negative or wildly long.  Durations inside "
        "tpu_resiliency/ subtract monotonic readings (time.monotonic[_ns], "
        "telemetry.clock.mono_ns); wall clocks are for labeling, not "
        "measuring.  Sites that legitimately subtract wall stamps (cross-"
        "process marker ages, where monotonic clocks are incomparable) carry "
        "an inline suppression naming why."
    )
    scope = ("tpu_resiliency/",)
    # marker ages compare time.time() stamps ACROSS processes — monotonic
    # readings of different processes are incomparable, wall time is the
    # only shared clock there; smonsvc ages external artifacts (file mtimes,
    # cycle stamps written by watched jobs), all wall-domain by nature
    exclude = (
        "tpu_resiliency/attribution/trace_analyzer.py",
        "tpu_resiliency/services/smonsvc.py",
    )

    def check_file(self, pf) -> Iterator:
        scopes = [pf.tree] + [
            n for n in ast.walk(pf.tree) if isinstance(n, _SCOPE_NODES)
        ]
        for scope in scopes:
            wall = _wall_names(scope)
            for node in _shallow_walk(scope):
                if not (
                    isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                ):
                    continue
                if _operand_is_wall(node.left, wall) or _operand_is_wall(
                    node.right, wall
                ):
                    yield pf.finding(
                        self.rule_id, node,
                        "duration measured by subtracting wall-clock readings "
                        "(time.time/datetime.now) — use time.monotonic_ns() / "
                        "telemetry.clock.mono_ns so NTP steps cannot skew it",
                    )
