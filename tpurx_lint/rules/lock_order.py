"""TPURX011: whole-program lock-order deadlock detection.

Collects every lock acquisition (``with self._lock:``, ``with COND:``,
``x.acquire()``) across the repo, propagates "acquired while holding" facts
through the module-qualified call graph, builds the lock-order graph over
(owner, attr) lock identities, and reports:

- **cycles** — two call paths that take the same pair of locks in opposite
  orders; a scheduler interleaving away from deadlock.  Reported PLAUSIBLE
  (per-instance aliasing cannot be proven statically); a runtime sanitizer
  witness (``tpurx-lint --witness``) promotes them to CONFIRMED or prunes
  them when the observed order is consistent.
- **self-deadlocks** — a non-reentrant ``Lock`` provably re-acquired on the
  same instance (``self.X`` held, closure of self-calls re-acquires
  ``self.X``).  These are definite: the acquire parks forever.

RLock/Condition re-acquisition is reentrant and never reported.  Lock
identity is per (class, attr): all instances share one node, matching the
runtime witness's creation-site granularity.
"""

from __future__ import annotations

import ast

from ..astutil import attr_chain
from ..callgraph import LockDecl
from ..registry import Rule, register


def _resolve_lock_expr(cg, fi, expr):
    """(LockDecl, via_self) for a lock-typed expression, else (None, False)."""
    chain = attr_chain(expr)
    if not chain:
        return None, False
    parts = chain.split(".")
    if parts[0] == "self" and fi.cls:
        if len(parts) == 2:
            decl = cg.lookup_lock(fi.cls, parts[1])
            return decl, True
        if len(parts) == 3:
            ci = cg.class_of(fi.cls)
            cq = ci.attr_types.get(parts[1]) if ci else None
            if cq:
                return cg.lookup_lock(cq, parts[2]), False
        return None, False
    if len(parts) == 1:
        return cg.locks.get(f"{fi.module}.{parts[0]}"), False
    if len(parts) == 2:
        # module-level lock through an import, or var.attr via local type
        target = cg._resolve_symbol(fi.module, expr)
        if target in cg.locks:
            return cg.locks[target], False
        local_types = cg._local_types(fi)
        cq = local_types.get(parts[0])
        if cq:
            return cg.lookup_lock(cq, parts[1]), False
    return None, False


def _acquire_sites(cg, fi):
    """[(LockDecl, line, via_self, body_nodes)] for every acquisition in fi.

    ``body_nodes`` is the subtree held under the acquisition (With body) or
    () for a bare ``.acquire()`` call (held region unknown — still a target
    for incoming edges, never a source).
    """
    out = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for item in node.items:
                decl, via_self = _resolve_lock_expr(cg, fi, item.context_expr)
                if decl is not None:
                    out.append((decl, node.lineno, via_self, node.body))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "acquire"):
            decl, via_self = _resolve_lock_expr(cg, fi, node.func.value)
            if decl is not None:
                out.append((decl, node.lineno, via_self, ()))
    return out


@register
class LockOrderRule(Rule):
    rule_id = "TPURX011"
    name = "lock-order"
    rationale = (
        "Two threads taking the same pair of locks in opposite orders across "
        "the call graph is the abort-ladder deadlock class; every acquisition "
        "order is collected interprocedurally, cycles in the lock-order graph "
        "are reported with both witness paths, and a runtime sanitizer "
        "witness can confirm or prune them."
    )
    scope = ("tpu_resiliency/",)

    def finalize(self, project):
        cg = project.callgraph()
        self._closure_cache = {}
        self._param_acq_cache = {}
        self._definite_seen = set()
        self._cg = cg

        edges = {}          # (a_id, b_id) -> (path_text, anchor_pf, line)
        definite = []       # self-deadlock findings

        for qname, fi in cg.functions.items():
            if not self.applies_to(fi.pf.rel):
                continue
            for held, hline, via_self, body in _acquire_sites(cg, fi):
                if not body:
                    continue
                self._edges_under(project, fi, held, hline, via_self, body,
                                  edges, definite)

        yield from definite
        yield from self._cycle_findings(project, edges)

    # -- edge collection ---------------------------------------------------

    def _edges_under(self, project, fi, held, hline, held_self, body,
                     edges, definite):
        cg = self._cg
        hold_site = f"{fi.pf.rel}:{hline}"
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.With):
                    for item in node.items:
                        decl, via_self = _resolve_lock_expr(cg, fi,
                                                            item.context_expr)
                        if decl is None:
                            continue
                        self._record(project, fi, held, hold_site, hline,
                                     held_self, decl, via_self,
                                     f"{fi.pf.rel}:{node.lineno} "
                                     f"(acquire {decl.lock_id})",
                                     edges, definite)
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "acquire"):
                    decl, via_self = _resolve_lock_expr(cg, fi, node.func.value)
                    if decl is not None:
                        self._record(project, fi, held, hold_site, hline,
                                     held_self, decl, via_self,
                                     f"{fi.pf.rel}:{node.lineno} "
                                     f"(acquire {decl.lock_id})",
                                     edges, definite)
                elif isinstance(node, ast.Call):
                    callee, call_self = cg.resolve_call(fi, node)
                    if callee is None:
                        continue
                    # lock handed through a helper: a lock-typed argument the
                    # callee acquires by parameter name counts as acquired here
                    for pname, expr in self._call_bindings(callee, node):
                        if pname not in self._param_acquires(callee.qname):
                            continue
                        decl, via_self = _resolve_lock_expr(cg, fi, expr)
                        if decl is None:
                            continue
                        pline = self._param_acquires(callee.qname)[pname]
                        self._record(project, fi, held, hold_site, hline,
                                     held_self, decl, via_self,
                                     f"{fi.pf.rel}:{node.lineno} (hands "
                                     f"{decl.lock_id} to {callee.qname}) -> "
                                     f"{callee.pf.rel}:{pline} "
                                     f"(acquire {decl.lock_id})",
                                     edges, definite)
                    for lock_id, (decl, path, via_all) in \
                            self._acq_closure(callee.qname).items():
                        step = (f"{fi.pf.rel}:{node.lineno} "
                                f"(calls {callee.qname})")
                        self._record(project, fi, held, hold_site, hline,
                                     held_self, decl,
                                     call_self and via_all,
                                     " -> ".join([step] + path),
                                     edges, definite)

    def _record(self, project, fi, held, hold_site, hline, held_self,
                acq, acq_self, acq_path, edges, definite):
        if acq.lock_id == held.lock_id:
            if held.reentrant:
                return
            if held_self and acq_self:
                dedup = (fi.pf.rel, hline, held.lock_id)
                if dedup in self._definite_seen:
                    return
                self._definite_seen.add(dedup)
                definite.append(fi.pf.finding(
                    self.rule_id, hline,
                    f"self-deadlock: non-reentrant Lock {held.lock_id} "
                    f"(declared {held.site}) is re-acquired on the same "
                    f"instance while held here — via {acq_path}; the second "
                    f"acquire parks forever (use RLock or drop the lock "
                    f"before the call)",
                ))
            return
        key = (held.lock_id, acq.lock_id)
        if key not in edges:
            path = f"{hold_site} (acquire {held.lock_id}) -> {acq_path}"
            edges[key] = (path, fi.pf, hline)

    @staticmethod
    def _call_bindings(callee, call: ast.Call):
        """(param_name, arg_expr) pairs at this call site."""
        args = callee.node.args
        names = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        out = list(zip(names, call.args))
        out += [(kw.arg, kw.value) for kw in call.keywords if kw.arg]
        return out

    def _param_acquires(self, qname: str) -> dict:
        """param name -> line, for params the function acquires directly."""
        cached = self._param_acq_cache.get(qname)
        if cached is not None:
            return cached
        out = {}
        fi = self._cg.functions.get(qname)
        if fi is not None:
            args = fi.node.args
            params = {a.arg for a in args.posonlyargs} | \
                     {a.arg for a in args.args} | \
                     {a.arg for a in args.kwonlyargs}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Name) and ce.id in params:
                            out.setdefault(ce.id, node.lineno)
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "acquire"
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id in params):
                    out.setdefault(node.func.value.id, node.lineno)
        self._param_acq_cache[qname] = out
        return out

    def _acq_closure(self, qname, _depth=0):
        """lock_id -> (decl, path steps, via_self_all) acquired in closure."""
        cached = self._closure_cache.get(qname)
        if cached is not None:
            return cached
        self._closure_cache[qname] = {}   # recursion guard
        cg = self._cg
        fi = cg.functions.get(qname)
        out = {}
        if fi is None or _depth > 10:
            self._closure_cache[qname] = out
            return out
        for decl, line, via_self, _body in _acquire_sites(cg, fi):
            if decl.lock_id not in out:
                out[decl.lock_id] = (
                    decl,
                    [f"{fi.pf.rel}:{line} (acquire {decl.lock_id})"],
                    via_self)
        for callee, line, call_self in cg.callees(qname):
            for lock_id, (decl, path, via_all) in \
                    self._acq_closure(callee, _depth + 1).items():
                if lock_id not in out:
                    step = f"{fi.pf.rel}:{line} (calls {callee})"
                    out[lock_id] = (decl, [step] + path,
                                    call_self and via_all)
        self._closure_cache[qname] = out
        return out

    # -- cycle detection + witness verdicts --------------------------------

    def _cycle_findings(self, project, edges):
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        cycles = []
        seen = set()
        for (a, b) in sorted(edges):
            if (b, a) in edges:
                canon = tuple(sorted((a, b)))
                if canon not in seen:
                    seen.add(canon)
                    cycles.append([a, b])
        for cyc in self._long_cycles(adj, edges):
            canon = tuple(sorted(cyc))
            if canon not in seen:
                seen.add(canon)
                cycles.append(cyc)

        witness = getattr(project, "witness", None)
        pruned = []
        for cyc in cycles:
            ring = " -> ".join(cyc + [cyc[0]])
            paths = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                paths.append(f"[{a} then {b}] {edges[(a, b)][0]}")
            verdict = "PLAUSIBLE"
            if witness is not None:
                verdict = self._witness_verdict(witness, cyc)
            _path0, pf0, line0 = edges[(cyc[0], cyc[1])]
            msg = (f"[{verdict}] potential lock-order deadlock: {ring}; "
                   + "; ".join(paths))
            f = pf0.finding(self.rule_id, line0, msg)
            if verdict == "PRUNED":
                pruned.append(f)
            else:
                yield f
        if pruned:
            existing = getattr(project, "witness_pruned", [])
            project.witness_pruned = existing + pruned

    def _long_cycles(self, adj, edges):
        """One representative simple cycle (len >= 3) per discovered loop."""
        out = []
        for start in sorted(adj):
            stack = [(start, [start])]
            found = None
            visited = set()
            while stack and found is None:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start and len(path) >= 3:
                        found = list(path)
                        break
                    if nxt in visited or nxt in path or len(path) > 6:
                        continue
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
            if found:
                out.append(found)
        return out

    def _witness_verdict(self, witness, cyc) -> str:
        """CONFIRMED: every edge observed at runtime.  PRUNED: the locks were
        all exercised and some edge was only ever observed in the reverse
        (consistent) order.  Otherwise PLAUSIBLE."""
        cg = self._cg
        sites = [cg.locks[l].site if l in cg.locks else None for l in cyc]
        if any(s is None for s in sites):
            return "PLAUSIBLE"
        edges = [(sites[i], sites[(i + 1) % len(sites)])
                 for i in range(len(sites))]
        if all(e in witness.edges for e in edges):
            return "CONFIRMED"
        if all(s in witness.sites for s in sites):
            for (a, b) in edges:
                if (a, b) not in witness.edges and (b, a) in witness.edges:
                    return "PRUNED"
        return "PLAUSIBLE"
