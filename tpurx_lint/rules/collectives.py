"""TPURX014: resiliency-layer collectives route through the wrapper."""

from __future__ import annotations

import ast

from ..registry import Rule, register

# jax.lax cross-device collective primitives (the p*/all_* family)
_COLLECTIVE_LAX = {
    "psum",
    "pmax",
    "pmin",
    "pmean",
    "ppermute",
    "pshuffle",
    "psum_scatter",
    "pbroadcast",
    "all_gather",
    "all_to_all",
}


def _is_lax_base(base: ast.expr) -> bool:
    if isinstance(base, ast.Name):
        return base.id in ("lax", "_lax")
    if isinstance(base, ast.Attribute):
        return base.attr == "lax"
    return False


@register
class RawCollectiveRule(Rule):
    rule_id = "TPURX014"
    name = "raw-collective"
    rationale = (
        "A raw multihost_utils.process_allgather / lax.p* collective has no "
        "deadline, no per-op telemetry, and no degrade path — a wedged link "
        "parks the host thread until the pod-wide restart ladder fires.  "
        "Resiliency-layer collectives go through "
        "parallel.collectives.ResilientCollective (or the sanctioned "
        "builders in that module), which deadlines the op, records "
        "tpurx_collective_* telemetry under the DispatchTail op "
        "vocabulary, and degrades retry -> re-layout -> targeted shrink "
        "instead of wedging."
    )
    scope = ("tpu_resiliency/",)
    exclude = (
        # the sanctioned home: the wrapper API + raw-collective builders
        "tpu_resiliency/parallel/collectives.py",
        # the jitted detection lane: the fused quorum reduce is ITSELF the
        # deadline mechanism (a stale pmax IS the signal), and its host
        # readback already rides the wrapper (FusedStepQuorum)
        "tpu_resiliency/ops/quorum.py",
    )

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            attr = node.func.attr
            if attr == "process_allgather":
                yield pf.finding(
                    self.rule_id, node,
                    "raw multihost_utils.process_allgather — route the "
                    "collective through parallel.collectives "
                    "(ResilientCollective)",
                )
            elif attr in _COLLECTIVE_LAX and _is_lax_base(node.func.value):
                yield pf.finding(
                    self.rule_id, node,
                    f"raw lax.{attr} collective outside parallel/ — route "
                    "it through parallel.collectives (ResilientCollective "
                    "or a sanctioned builder)",
                )
