"""TPURX012: interprocedural deadline propagation.

TPURX005 is syntactic — it catches the wait with no bound.  This rule is the
dataflow upgrade: a function that ACCEPTS a ``timeout``/``deadline``
parameter made a promise to its caller, and every way of breaking that
promise inside its body is a finding:

1. **dead deadline** — the parameter is never read: the bound dies at the
   API boundary (``def join(self, timeout): ... self._cv.wait()``).
2. **dropped at a wait** — the body performs an unbounded blocking call even
   though a deadline is in scope (fires together with TPURX005: here the
   unbounding is a broken contract, not just a missing bound).
3. **dropped at a call** — the body calls a repo function that itself
   accepts a deadline parameter and whose closure blocks, without passing
   any bound: three calls deep is where dropped deadlines hide.

Abstract bodies (``raise NotImplementedError`` / ``...`` / docstring-only)
are exempt — the contract is the override's to keep.
"""

from __future__ import annotations

import ast

from ..blocking import unbounded_blocking_calls
from ..callgraph import is_deadline_param
from ..registry import Rule, register


def _is_abstract_body(node) -> bool:
    body = node.body
    stmts = [s for s in body
             if not (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))]
    if not stmts:
        return True
    if len(stmts) == 1:
        s = stmts[0]
        if isinstance(s, ast.Pass):
            return True
        if isinstance(s, ast.Raise):
            exc = s.exc
            name = ""
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name):
                name = exc.id
            return name == "NotImplementedError"
    return False


def _param_reads(node, params: set) -> set:
    """Deadline params that are actually read somewhere in the body."""
    read = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id in params:
            read.add(sub.id)
    return read


def _call_has_bound(call: ast.Call, callee_fi) -> bool:
    """True when the call site passes SOME deadline argument to the callee."""
    for kw in call.keywords:
        if kw.arg is None:       # **kwargs — assume threaded
            return True
        if is_deadline_param(kw.arg):
            return True
    # positional reach: does any positional land on a deadline param?
    args = callee_fi.node.args
    names = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    for i, _a in enumerate(call.args):
        if i < len(names) and is_deadline_param(names[i]):
            return True
        if isinstance(_a, ast.Starred):
            return True
    return False


@register
class DeadlinePropagationRule(Rule):
    rule_id = "TPURX012"
    name = "deadline-propagation"
    rationale = (
        "A function accepting timeout/deadline must thread it into every "
        "blocking callee reachable in its body — an accepted-then-dropped "
        "deadline is a caller-visible bound that silently never applies."
    )
    scope = ("tpu_resiliency/",)

    def finalize(self, project):
        cg = project.callgraph()
        self._blocks_cache = {}
        self._cg = cg
        for qname, fi in cg.functions.items():
            if not self.applies_to(fi.pf.rel):
                continue
            if not fi.deadline_params or _is_abstract_body(fi.node):
                continue
            params = set(fi.deadline_params)
            read = _param_reads(fi.node, params)

            for p in fi.deadline_params:
                if p not in read:
                    yield fi.pf.finding(
                        self.rule_id, fi.node.lineno,
                        f"{qname}() accepts deadline parameter '{p}' but "
                        f"never reads it — the caller's bound dies at this "
                        f"boundary (thread it into the blocking calls below, "
                        f"or drop the parameter)",
                    )

            for node, desc in unbounded_blocking_calls(fi.pf, fi.node):
                yield fi.pf.finding(
                    self.rule_id, node,
                    f"{qname}() accepts a deadline "
                    f"({', '.join(sorted(params))}) but this blocking call "
                    f"drops it: {desc}",
                )

            local_types = cg._local_types(fi)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee, _vs = cg.resolve_call(fi, node, local_types)
                if callee is None or not callee.deadline_params:
                    continue
                if callee.qname == qname:
                    continue
                if _call_has_bound(node, callee):
                    continue
                if not self._closure_blocks(callee.qname):
                    continue
                yield fi.pf.finding(
                    self.rule_id, node,
                    f"{qname}() holds a deadline "
                    f"({', '.join(sorted(params))}) but calls "
                    f"{callee.qname}() — which accepts "
                    f"'{callee.deadline_params[0]}' and blocks — without "
                    f"passing any bound: the deadline stops propagating here",
                )

    def _closure_blocks(self, qname: str, _depth=0) -> bool:
        """Does the callee's call-graph closure contain any blocking call
        (bounded or not)?  Suppressed wait sites are honored — a wait the
        author marked load-bearing does not make every caller fire."""
        cached = self._blocks_cache.get(qname)
        if cached is not None:
            return cached
        self._blocks_cache[qname] = False     # recursion guard
        cg = self._cg
        fi = cg.functions.get(qname)
        result = False
        if fi is not None and _depth <= 6:
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("wait", "join", "communicate",
                                               "result", "wait_stale",
                                               "watch_stale")):
                    if fi.pf.is_suppressed("TPURX005", node.lineno) \
                            or fi.pf.is_suppressed("TPURX012", node.lineno):
                        continue
                    result = True
                    break
            if not result:
                for callee, _line, _vs in cg.callees(qname):
                    if self._closure_blocks(callee, _depth + 1):
                        result = True
                        break
        self._blocks_cache[qname] = result
        return result
