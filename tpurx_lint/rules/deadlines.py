"""TPURX005: every blocking wait in the library carries a finite timeout.

The failure mode this kills: a recovery path parks on an Event/Condition/
process that the fault it is recovering FROM prevents from ever firing — the
silent-hang class the reliable-CCL literature attributes most lost pods to.
A deliberate forever-wait is fine, but it must say so in a suppression
reason so the next reader knows the unbounding is load-bearing.
"""

from __future__ import annotations

from ..blocking import unbounded_blocking_calls
from ..registry import Rule, register


@register
class DeadlineDisciplineRule(Rule):
    rule_id = "TPURX005"
    name = "deadline-discipline"
    rationale = (
        "Every blocking store/event/condition/process/socket/join wait in "
        "the library must carry a finite timeout (or an explicit suppression "
        "with a reason) — an unbounded wait in a recovery path is a silent "
        "hang when the peer is the thing that failed."
    )
    scope = ("tpu_resiliency/", "tpurx_lint/")

    def check_file(self, pf):
        for node, desc in unbounded_blocking_calls(pf):
            yield pf.finding(self.rule_id, node, desc)
