"""Importing this package registers every rule (via the @register decorator)."""

from . import (  # noqa: F401
    prints,
    raw_reads,
    wall_clock,
    flat_gather,
    deadlines,
    abort_path,
    retry_loops,
    threads,
    exceptions,
    envvars,
    lock_order,
    deadline_prop,
    store_keys,
    collectives,
    d2h,
    wall_clock_duration,
)
