"""TPURX002: checkpoint bytes only enter through the verifying readers."""

from __future__ import annotations

import ast

from ..registry import Rule, register

_OS_READ_CALLS = {"read", "pread", "preadv", "readv"}


@register
class RawBinaryReadRule(Rule):
    rule_id = "TPURX002"
    name = "raw-ckpt-read"
    rationale = (
        "Checkpoint payload bytes must only enter the process through the "
        "verifying readers in checkpointing/integrity.py — a raw rb-open or "
        "positioned os.read is a trust-boundary bypass of the corrupt-shard "
        "quarantine."
    )
    scope = ("tpu_resiliency/checkpointing/",)
    exclude = ("tpu_resiliency/checkpointing/integrity.py",)

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _OS_READ_CALLS
                and isinstance(func.value, ast.Name)
                and func.value.id == "os"
            ):
                yield pf.finding(
                    self.rule_id, node,
                    f"os.{func.attr} of checkpoint data outside the verifying "
                    f"reader (use integrity.ChunkReader)",
                )
                continue
            if not (isinstance(func, ast.Name) and func.id == "open"):
                continue
            mode = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and "r" in mode.value
                and "b" in mode.value
            ):
                yield pf.finding(
                    self.rule_id, node,
                    "raw rb-open of checkpoint data outside the verifying "
                    "reader (use integrity.read_verified_blob / "
                    "read_verified_shard / ChunkReader)",
                )
