"""TPURX015: device->host reads of checkpoint state stay in the staging layer."""

from __future__ import annotations

import ast

from ..registry import Rule, register

# the sanctioned device->host touchpoints (see staging.py module docstring)
_ALLOWED = (
    "tpu_resiliency/checkpointing/async_ckpt/staging.py",
    "tpu_resiliency/checkpointing/async_ckpt/device_digest.py",
)


@register
class RawDeviceReadRule(Rule):
    rule_id = "TPURX015"
    name = "raw-d2h-read"
    rationale = (
        "Checkpoint state leaves the device only through the staging layer "
        "(async_ckpt/staging.py, device_digest.py) — a raw copy_to_host_async "
        "or jax.device_get elsewhere bypasses the D2H-skip planning, the "
        "double-buffer ordering fence, and the drain's digest accounting, "
        "silently re-serializing transfers the save was designed to avoid. "
        "Kick transfers via staging.async_d2h instead."
    )
    scope = ("tpu_resiliency/checkpointing/",)
    exclude = _ALLOWED

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "copy_to_host_async":
                yield pf.finding(
                    self.rule_id, node,
                    "raw copy_to_host_async on checkpoint state outside the "
                    "staging layer (use staging.async_d2h)",
                )
            elif (
                isinstance(func, ast.Attribute)
                and func.attr == "device_get"
                and isinstance(func.value, ast.Name)
                and func.value.id == "jax"
            ) or (isinstance(func, ast.Name) and func.id == "device_get"):
                yield pf.finding(
                    self.rule_id, node,
                    "raw jax.device_get of checkpoint state outside the "
                    "staging layer (route reads through staging/device_digest)",
                )
