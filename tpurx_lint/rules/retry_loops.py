"""TPURX007: retry discipline — no hand-rolled while+sleep retry loops.

utils/retry.py is the single home of retry policy (exponential backoff, full
jitter, overall deadline, per-site telemetry).  A hand-rolled loop silently
lacks at least one of those: un-jittered retries synchronize thundering
herds, deadline-less ones hide outages, and untelemetered ones are invisible
to the policy engine.
"""

from __future__ import annotations

import ast

from ..registry import Rule, register


def _walk_stop_at_functions(node):
    """Walk descendants without descending into nested function/class defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Lambda)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


def _has_sleep(nodes) -> bool:
    for n in nodes:
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "sleep"):
            return True
    return False


def _try_exits_on_success(try_node: ast.Try) -> bool:
    """break/return in the try body or else-clause (the success escape that
    distinguishes a retry loop from a forever poll loop)."""
    for part in (try_node.body, try_node.orelse):
        for stmt in part:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Break, ast.Return)):
                    return True
    return False


@register
class RetryDisciplineRule(Rule):
    rule_id = "TPURX007"
    name = "retry-discipline"
    rationale = (
        "No while/for + sleep retry loops outside utils/retry.py — "
        "hand-rolled retries skip the shared jitter/deadline/telemetry "
        "policy; use retry_call / Retrier / RetryPolicy."
    )
    scope = ("tpu_resiliency/", "tpurx_lint/")
    exclude = ("tpu_resiliency/utils/retry.py",)

    def check_file(self, pf):
        for node in ast.walk(pf.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            body_nodes = list(_walk_stop_at_functions(node))
            if not _has_sleep(body_nodes):
                continue
            tries = [n for n in body_nodes if isinstance(n, ast.Try)
                     and n.handlers]
            for t in tries:
                if _try_exits_on_success(t):
                    yield pf.finding(
                        self.rule_id, node,
                        "hand-rolled retry loop (loop + sleep + try/except "
                        "with success escape) — use utils.retry.retry_call / "
                        "Retrier so jitter, deadline, and telemetry apply",
                    )
                    break
