"""TPURX013: store-key lifecycle — ephemeral keys must have a GC path.

Protocol rounds write per-round/per-rank keys into the control-plane store
(``set``/``append``/``add`` — and the fused one-RTT ``append_check`` /
``add_set``, which each write a second key at ``args[2]`` — with
interpolated round, cycle, iteration, or rank components).  A key written every round and deleted never is a leak
that grows O(rounds x ranks) until a 10k-rank job OOMs the shard — the
``store/tree.py`` discipline (parents delete consumed child keys, the round
fence doubles as the GC barrier) is the model.

Mechanics: every write site's key expression is reduced to a template — the
first stable literal fragment of an f-string, the resolved value of a local
variable, a module-level constant, or the NAME of a key-helper function
(``k_open(n)``-style, resolved through the call graph).  Delete evidence
(``delete``/``multi_delete``, keys handed to ``tree_gather`` whose round
fence GCs them) is collected project-wide.  An ephemeral write template with
no matching delete template is a finding naming the leaking prefix.

Fixed-key ``set``/``add`` (no interpolation) are bounded singletons and
exempt; ``append`` grows content even on a fixed key and is never exempt.
"""

from __future__ import annotations

import ast

from ..astutil import attr_chain, call_name
from ..registry import Rule, register

_WRITE_OPS = {"set", "append", "add", "append_check", "add_set"}
# the one-RTT atomic ops write TWO keys: the log/counter at args[0] and the
# done/marker key at args[2] — each is checked under its effective primitive
# (append_check ~ append+set, add_set ~ add+set)
_TWO_KEY_OPS = {"append_check": "append", "add_set": "add"}
_DELETE_OPS = {"delete", "multi_delete", "delete_prefix"}

# functions whose key argument is consumed by their own GC discipline
_SELF_CLEANING = {"tree_gather"}


def _receiver_is_store(func: ast.Attribute) -> bool:
    chain = attr_chain(func.value)
    tail = chain.rsplit(".", 1)[-1].lower()
    return tail == "store" or tail.endswith("store")


class KeyTemplate:
    """Stable identity of a key expression for write/delete matching."""

    __slots__ = ("ident", "ephemeral", "text")

    def __init__(self, ident: str, ephemeral: bool, text: str):
        self.ident = ident
        self.ephemeral = ephemeral
        self.text = text


def _first_literal_ident(fragments) -> str:
    """First nonempty path segment among the literal fragments."""
    for frag in fragments:
        for seg in frag.split("/"):
            if seg:
                return seg
    return ""


def _module_consts(pf) -> dict:
    out = {}
    for node in pf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _template_of(expr, cg, fi, local_templates, consts,
                 _depth=0) -> KeyTemplate | None:
    if _depth > 3 or expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        ident = _first_literal_ident([expr.value])
        return KeyTemplate(ident, False, expr.value) if ident else None
    if isinstance(expr, ast.JoinedStr):
        frags = [v.value for v in expr.values
                 if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        has_placeholder = any(isinstance(v, ast.FormattedValue)
                              for v in expr.values)
        ident = _first_literal_ident(frags)
        # leading `{prefix}` placeholder: resolve the variable's own template
        # so f"{prefix}/r{rank}" keys match deletes of the same prefix
        if expr.values and isinstance(expr.values[0], ast.FormattedValue) \
                and isinstance(expr.values[0].value, ast.Name):
            lead = _template_of(expr.values[0].value, cg, fi, local_templates,
                                consts, _depth + 1)
            if lead is not None and lead.ident:
                ident = lead.ident
        if not ident:
            return None
        text = "".join(f if isinstance(v, ast.Constant) else "{*}"
                       for v, f in zip(expr.values,
                                       [getattr(v, "value", "{*}")
                                        for v in expr.values]))
        return KeyTemplate(ident, has_placeholder, text)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _template_of(expr.left, cg, fi, local_templates, consts,
                            _depth + 1)
        if left is not None:
            return KeyTemplate(left.ident, True, left.text + "+{*}")
        return None
    if isinstance(expr, ast.Name):
        if expr.id in local_templates:
            return local_templates[expr.id]
        if expr.id in consts:
            ident = _first_literal_ident([consts[expr.id]])
            return KeyTemplate(ident, False, consts[expr.id]) if ident else None
        return None
    if isinstance(expr, ast.Call):
        # key-helper call: identity is the helper's name; ephemerality comes
        # from its returned template when resolvable (default ephemeral)
        callee, _vs = cg.resolve_call(fi, expr) if fi else (None, False)
        name = call_name(expr).rsplit(".", 1)[-1]
        if not name:
            return None
        ephemeral = True
        if callee is not None:
            for node in ast.walk(callee.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    t = _template_of(node.value, cg, callee, {}, consts,
                                     _depth + 1)
                    if t is not None:
                        ephemeral = t.ephemeral or bool(expr.args)
                    break
        return KeyTemplate(f"{name}()", ephemeral, f"{name}(...)")
    return None


def _local_templates(fi, cg, consts) -> dict:
    out = {}
    for node in ast.walk(fi.node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            t = _template_of(node.value, cg, fi, out, consts)
            if t is not None:
                out[node.targets[0].id] = t
    return out


@register
class StoreKeyLifecycleRule(Rule):
    rule_id = "TPURX013"
    name = "store-key-lifecycle"
    rationale = (
        "Ephemeral control-plane keys written during a protocol round "
        "(interpolated round/rank/cycle components, or any append) must "
        "have a reachable delete/GC path, per the store/tree.py "
        "consumed-child-key discipline — otherwise the store grows "
        "O(rounds x ranks) until the shard OOMs."
    )
    scope = (
        "tpu_resiliency/store/",
        "tpu_resiliency/inprocess/",
        "tpu_resiliency/checkpointing/local/",
        "tpu_resiliency/fault_tolerance/rendezvous.py",
        # the policy engine's journal and evacuation records (ISSUE 18):
        # every published decision/evac key needs its keep-window GC
        "tpu_resiliency/policy/",
    )
    # the store implementation itself (set/delete here are the ops, not
    # protocol-round usage); tree.py is the sanctioned GC discipline home
    exclude = (
        "tpu_resiliency/store/client.py",
        "tpu_resiliency/store/sharding.py",
        "tpu_resiliency/store/server.py",
        "tpu_resiliency/store/native.py",
        "tpu_resiliency/store/protocol.py",
        "tpu_resiliency/store/tree.py",
    )

    def finalize(self, project):
        cg = project.callgraph()
        writes = []          # (KeyTemplate, pf, line, op)
        deletes = set()      # idents

        for qname, fi in cg.functions.items():
            consts = _module_consts(fi.pf)
            locals_ = None
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                # self-cleaning rounds: their key arg counts as deleted
                short = call_name(node).rsplit(".", 1)[-1]
                if short in _SELF_CLEANING:
                    if locals_ is None:
                        locals_ = _local_templates(fi, cg, consts)
                    for arg in list(node.args[1:2]) + [
                            kw.value for kw in node.keywords
                            if kw.arg in ("prefix", "key", "name")]:
                        t = _template_of(arg, cg, fi, locals_, consts)
                        if t is not None:
                            deletes.add(t.ident)
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr in _DELETE_OPS and _receiver_is_store(func):
                    if locals_ is None:
                        locals_ = _local_templates(fi, cg, consts)
                    for key_expr in self._delete_key_exprs(node):
                        t = _template_of(key_expr, cg, fi, locals_, consts)
                        if t is not None:
                            deletes.add(t.ident)
                    continue
                if (func.attr in _WRITE_OPS and len(node.args) >= 2
                        and _receiver_is_store(func)
                        and self.applies_to(fi.pf.rel)):
                    if locals_ is None:
                        locals_ = _local_templates(fi, cg, consts)
                    if func.attr in _TWO_KEY_OPS:
                        key_ops = [(node.args[0], _TWO_KEY_OPS[func.attr])]
                        if len(node.args) >= 3:
                            key_ops.append((node.args[2], "set"))
                    else:
                        key_ops = [(node.args[0], func.attr)]
                    for key_expr, eff_op in key_ops:
                        t = _template_of(key_expr, cg, fi, locals_, consts)
                        if t is None:
                            continue
                        if not t.ephemeral and eff_op in ("set", "add"):
                            continue   # bounded singleton
                        writes.append((t, fi.pf, node.lineno, func.attr))

        for t, pf, line, op in writes:
            if t.ident in deletes:
                continue
            yield pf.finding(
                self.rule_id, line,
                f"store key {t.text!r} ({op}) is ephemeral but no "
                f"delete/GC path exists for prefix '{t.ident}' anywhere in "
                f"the repo — it leaks in the control-plane store every "
                f"round (add a consumed-key delete per store/tree.py, or "
                f"suppress with the reason the growth is bounded)",
            )

    @staticmethod
    def _delete_key_exprs(node: ast.Call):
        for arg in node.args[:1]:
            if isinstance(arg, (ast.List, ast.Tuple, ast.Set)):
                yield from arg.elts
            elif isinstance(arg, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
                yield arg.elt
            else:
                yield arg
