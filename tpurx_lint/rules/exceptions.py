"""TPURX009: exception hygiene in fault-handling modules.

A swallowed exception in a fault handler converts a diagnosable failure into
a mis-attributed one: the fault surfaces later, somewhere else, stripped of
its cause — the exact mis-attribution class the Chameleon/reliable-CCL
papers trace silent degradation to.  Bare ``except:`` is banned everywhere
in the library; ``except Exception:`` whose body only ``pass``es is banned
in the fault-handling trees.
"""

from __future__ import annotations

import ast

from ..astutil import body_is_swallow
from ..registry import Rule, register

FAULT_HANDLING_PREFIXES = (
    "tpu_resiliency/inprocess/",
    "tpu_resiliency/fault_tolerance/",
    "tpu_resiliency/health/",
    "tpu_resiliency/checkpointing/",
    "tpu_resiliency/store/",
    "tpu_resiliency/ops/",
    "tpu_resiliency/straggler/",
    "tpu_resiliency/utils/",
)


@register
class ExceptionHygieneRule(Rule):
    rule_id = "TPURX009"
    name = "exception-hygiene"
    rationale = (
        "No bare except anywhere; no swallow-all 'except Exception: pass' in "
        "fault-handling modules — narrow the type, log it, or suppress with "
        "the reason the drop is safe."
    )
    scope = ("tpu_resiliency/", "tpurx_lint/")

    def check_file(self, pf):
        in_fault_tree = pf.rel.startswith(FAULT_HANDLING_PREFIXES)
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield pf.finding(
                    self.rule_id, node,
                    "bare 'except:' catches SystemExit/KeyboardInterrupt — "
                    "name the exception type",
                )
                continue
            if not in_fault_tree:
                continue
            broad = (isinstance(node.type, ast.Name)
                     and node.type.id in ("Exception", "BaseException"))
            if broad and body_is_swallow(node):
                yield pf.finding(
                    self.rule_id, node,
                    f"'except {node.type.id}: pass' swallows every fault in "
                    f"a fault-handling module — narrow the type, log it, or "
                    f"suppress with a reason",
                )
