"""TPURX008: thread lifecycle.

Two checks:

1. Every ``threading.Thread(...)`` is daemon (can't wedge interpreter exit /
   abort teardown) or provably joined with a finite timeout somewhere in the
   same module.  A non-daemon, never-joined thread is exactly the shape that
   hangs the monitor kill path after the main thread is gone.

2. ``# guarded-by: <lock>`` annotations: an attribute assignment carrying the
   comment declares that every OTHER method of the class must touch
   ``self.<attr>`` only inside ``with self.<lock>:``.  The declaring function
   (usually ``__init__``, pre-publication) is exempt.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize

from ..astutil import attr_chain, call_name, enclosing_class, enclosing_function, \
    has_finite_timeout
from ..registry import Rule, register

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _thread_target_chain(pf, call):
    """Dotted chain the Thread object is bound to ('' when unbound)."""
    parent = pf.parent(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        return attr_chain(parent.targets[0])
    if isinstance(parent, ast.AnnAssign) and parent.target is not None:
        return attr_chain(parent.target)
    return ""


def _module_has_daemon_set(pf, chain: str) -> bool:
    tail = chain.split(".")[-1]
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                        and attr_chain(t.value).split(".")[-1] == tail
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True):
                    return True
    return False


def _module_has_bounded_join(pf, chain: str) -> bool:
    tail = chain.split(".")[-1]
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and attr_chain(node.func.value).split(".")[-1] == tail
                and has_finite_timeout(node)):
            return True
    return False


def _guarded_attrs(pf):
    """{class_name: {attr: (lock, declaring_func_node)}} from guarded-by
    comments on self.<attr> assignments."""
    line_lock = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(pf.text).readline):
            if tok.type == tokenize.COMMENT:
                m = _GUARDED_BY_RE.search(tok.string)
                if m:
                    line_lock[tok.start[0]] = m.group(1)
    except tokenize.TokenError:
        return {}
    out = {}
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Assign) or node.lineno not in line_lock:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name) and t.value.id == "self"):
                cls = enclosing_class(pf, node)
                fn = enclosing_function(pf, node)
                if cls is not None:
                    out.setdefault(cls.name, {})[t.attr] = (
                        line_lock[node.lineno], fn)
    return out


def _under_lock(pf, node, lock_attr: str) -> bool:
    want = f"self.{lock_attr}"
    for anc in pf.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if attr_chain(item.context_expr) == want:
                    return True
    return False


@register
class ThreadLifecycleRule(Rule):
    rule_id = "TPURX008"
    name = "thread-lifecycle"
    rationale = (
        "Every threading.Thread must be daemon or joined with a finite "
        "timeout (a non-daemon never-joined thread wedges abort teardown); "
        "attributes declared '# guarded-by: <lock>' must be accessed under "
        "'with self.<lock>:'."
    )
    scope = ("tpu_resiliency/", "tpurx_lint/")

    def check_file(self, pf):
        yield from self._check_threads(pf)
        yield from self._check_guarded(pf)

    def _check_threads(self, pf):
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in ("threading.Thread", "Thread")):
                continue
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = kw.value
            if daemon is not None:
                if isinstance(daemon, ast.Constant) and daemon.value is False:
                    yield pf.finding(
                        self.rule_id, node,
                        "daemon=False thread — must be joined with a finite "
                        "timeout or made daemon",
                    )
                continue  # daemon=True or a deliberate expression
            chain = _thread_target_chain(pf, node)
            if chain and (_module_has_daemon_set(pf, chain)
                          or _module_has_bounded_join(pf, chain)):
                continue
            yield pf.finding(
                self.rule_id, node,
                "thread is neither daemon nor joined-with-timeout in this "
                "module — it can outlive and wedge abort teardown",
            )

    def _check_guarded(self, pf):
        guarded = _guarded_attrs(pf)
        if not guarded:
            return
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ClassDef) or node.name not in guarded:
                continue
            attrs = guarded[node.name]
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and sub.attr in attrs):
                    continue
                lock, decl_fn = attrs[sub.attr]
                fn = enclosing_function(pf, sub)
                if fn is decl_fn:   # pre-publication init is exempt
                    continue
                if not _under_lock(pf, sub, lock):
                    yield pf.finding(
                        self.rule_id, sub,
                        f"self.{sub.attr} is declared guarded-by {lock} but "
                        f"accessed outside 'with self.{lock}:'",
                    )
