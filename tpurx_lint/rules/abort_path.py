"""TPURX006: abort-path safety.

Code reachable from an ``AbortStage.run`` implementation, a signal handler,
or the monitor kill path runs while the process is already wedged or dying:
an unbounded blocking call there turns a recoverable fault into a silent
hang, and a freshly spawned thread there outlives (and wedges) teardown.

Reachability is computed per file: roots are ``run``/``abort`` methods of
classes whose bases name ``AbortStage``, callables handed to
``signal.signal``, and an explicit extra-roots table for the monitor kill
path; edges follow bare-name calls to module functions and ``self.x()``
calls to same-class methods.
"""

from __future__ import annotations

import ast

from ..astutil import call_name, class_base_names
from ..blocking import unbounded_blocking_calls
from ..registry import Rule, register

# the monitor kill path: functions that run between "rank declared dead" and
# "SIGKILL delivered" — same no-unbounded-blocking contract as abort stages
EXTRA_ROOTS = {
    "tpu_resiliency/inprocess/monitor_thread.py": {"_run", "stop"},
    "tpu_resiliency/fault_tolerance/rank_monitor_server.py": {"_default_kill"},
}

_THREAD_CTORS = {"threading.Thread", "Thread"}


# the overridable stage surface of AbortStage subclasses
_STAGE_METHODS = ("run", "abort", "release", "applicable", "__call__")


def _index_functions(tree):
    """(module_funcs: name->node, methods: (class,name)->node)"""
    module_funcs, methods = {}, {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module_funcs[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[(node.name, sub.name)] = sub
    return module_funcs, methods


def _roots(pf):
    """Yield (func_node, why) abort-path entry points in this file."""
    module_funcs, methods = _index_functions(pf.tree)
    # signal handlers are often nested in a main(): index every def by name
    all_funcs = {
        n.name: n for n in ast.walk(pf.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }

    for (cls, name), node in methods.items():
        if name in _STAGE_METHODS:
            cls_node = next(
                n for n in pf.tree.body
                if isinstance(n, ast.ClassDef) and n.name == cls
            )
            if any("AbortStage" in b for b in class_base_names(cls_node)):
                yield node, f"{cls}.{name} (AbortStage implementation)"

    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Call)
                and call_name(node) in ("signal.signal",)
                and len(node.args) == 2):
            handler = node.args[1]
            if isinstance(handler, ast.Name) and handler.id in all_funcs:
                yield all_funcs[handler.id], (
                    f"{handler.id} (signal handler)")
            elif isinstance(handler, ast.Lambda):
                yield handler, f"signal handler lambda at line {handler.lineno}"

    for name in EXTRA_ROOTS.get(pf.rel, ()):
        for key, node in list(methods.items()) + list(module_funcs.items()):
            fname = key[1] if isinstance(key, tuple) else key
            if fname == name:
                yield node, f"{name} (monitor kill path)"


def _callees(func_node, module_funcs, methods, own_class):
    """Function nodes this function calls, resolved within the file."""
    for node in ast.walk(func_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in module_funcs:
            yield module_funcs[f.id]
        elif (isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name) and f.value.id == "self"
              and own_class is not None
              and (own_class, f.attr) in methods):
            yield methods[(own_class, f.attr)]


@register
class AbortPathSafetyRule(Rule):
    rule_id = "TPURX006"
    name = "abort-path-safety"
    rationale = (
        "Code reachable from AbortStage.run implementations, signal "
        "handlers, and the monitor kill path may not perform unbounded "
        "blocking waits or spawn threads — it runs while the process is "
        "already wedged, so anything it parks on is lost."
    )
    scope = ("tpu_resiliency/",)

    def check_file(self, pf):
        module_funcs, methods = _index_functions(pf.tree)
        node_class = {n: cls for (cls, _n), n in methods.items()}

        seen = {}
        queue = [(node, why) for node, why in _roots(pf)]
        while queue:
            node, why = queue.pop()
            if node in seen:
                continue
            seen[node] = why
            for callee in _callees(node, module_funcs, methods,
                                   node_class.get(node)):
                if callee not in seen:
                    queue.append((callee, why))

        for func, why in seen.items():
            for node in ast.walk(func):
                if (isinstance(node, ast.Call)
                        and call_name(node) in _THREAD_CTORS):
                    yield pf.finding(
                        self.rule_id, node,
                        f"thread spawned on the abort path (reachable from "
                        f"{why}) — a thread born during teardown outlives it",
                    )
            for node, desc in unbounded_blocking_calls(pf, func):
                yield pf.finding(
                    self.rule_id, node,
                    f"unbounded blocking call on the abort path (reachable "
                    f"from {why}): {desc}",
                )
