"""Shared detection of unbounded blocking calls.

Used by TPURX005 (deadline discipline everywhere) and TPURX006 (abort-path
safety), so both rules agree on what "blocks without a deadline" means.

The contract is intentionally about INTENT, not value: any non-None timeout
expression counts as bounded — the rule enforces that someone chose a bound,
not what the bound is.
"""

from __future__ import annotations

import ast

from .astutil import attr_chain, call_name, has_finite_timeout, keyword, is_none_constant

# attribute-call names that park the caller until an external event
_WAIT_ATTRS = {"wait", "wait_stale", "watch_stale"}

_SUBPROCESS_FUNCS = {
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.call",
}

# raw byte-wait receivers: a C-level wait no async raise can interrupt
_RECV_ATTRS = {"recv", "recv_into", "recvfrom", "recvfrom_into", "recvmsg"}

# The sanctioned interruptible I/O core: the ONLY modules allowed to touch
# raw socket recv/send waits directly.  Every wait there is sliced at the
# TPURX_STORE_POLL_S quantum inside a Python-level loop, which is the whole
# point — everyone else must either bound the socket (settimeout/poll in
# the same function) or go through the store client.
SANCTIONED_SOCKET_CORE = (
    "tpu_resiliency/store/client.py",
    "tpu_resiliency/store/mux.py",
)


def _receiver_hints_queue(func: ast.Attribute) -> bool:
    chain = attr_chain(func.value).lower()
    last = chain.rsplit(".", 1)[-1]
    return "queue" in last or last == "q" or last.endswith("_q")


def _receiver_hints_socket(func: ast.Attribute) -> bool:
    chain = attr_chain(func.value).lower()
    last = chain.rsplit(".", 1)[-1]
    return "sock" in last or "conn" in last


def _enclosing_function(pf, node):
    cur = pf.parent(node)
    while cur is not None and not isinstance(
        cur, (ast.FunctionDef, ast.AsyncFunctionDef)
    ):
        cur = pf.parent(cur)
    return cur


def _function_bounds_socket(pf, node) -> bool:
    """True when the enclosing function shows deadline intent for its
    socket/pipe reads: a finite ``settimeout(...)``, a finite ``poll(...)``
    gate (the multiprocessing.Connection idiom), or a ``.poll`` handed to
    ``run_in_executor`` with a timeout operand.  Intent, not value — the
    rule enforces that someone chose a bound, not what the bound is."""
    fn = _enclosing_function(pf, node)
    if fn is None:
        return False
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        if isinstance(sub.func, ast.Attribute):
            if (sub.func.attr == "settimeout" and sub.args
                    and not is_none_constant(sub.args[0])):
                return True
            if sub.func.attr == "poll":
                kw = keyword(sub, "timeout")
                if (sub.args and not is_none_constant(sub.args[0])) or (
                    kw is not None and not is_none_constant(kw)
                ):
                    return True
            if sub.func.attr == "run_in_executor" and any(
                isinstance(a, ast.Attribute) and a.attr == "poll"
                for a in sub.args
            ):
                return True
    return False


def _inside_asyncio_wait_for(pf, node) -> bool:
    parent = pf.parent(node)
    # unwrap `await x.wait()` one level
    if isinstance(parent, ast.Await):
        parent = pf.parent(parent)
    return (
        isinstance(parent, ast.Call)
        and call_name(parent) in ("asyncio.wait_for", "wait_for")
        and node in ast.walk(parent)
    )


def unbounded_blocking_calls(pf, scope_node=None):
    """Yield (call_node, description) for every unbounded blocking call.

    ``scope_node`` limits the walk (used by the abort-path rule to scan one
    reachable function); default is the whole module.
    """
    root = scope_node if scope_node is not None else pf.tree
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        dotted = call_name(node)

        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr in _WAIT_ATTRS:
                if _inside_asyncio_wait_for(pf, node):
                    continue
                if not has_finite_timeout(node):
                    yield node, (
                        f".{attr}() without a finite timeout (event/condition/"
                        f"process wait can park forever — pass timeout=)"
                    )
                continue
            if attr == "join" and not node.args and not node.keywords:
                # zero-arg .join() can't be str.join (that needs an iterable)
                yield node, (
                    ".join() without a timeout (a wedged thread/process "
                    "parks the joiner forever — pass a bound)"
                )
                continue
            if attr == "join" and (node.args or node.keywords):
                # thread/process join with explicit timeout=None
                kw = keyword(node, "timeout")
                if kw is not None and is_none_constant(kw):
                    yield node, ".join(timeout=None) is unbounded"
                elif (not node.keywords and len(node.args) == 1
                      and is_none_constant(node.args[0])):
                    yield node, ".join(None) is unbounded"
                continue
            if attr == "communicate" and not has_finite_timeout(node):
                yield node, (
                    ".communicate() without timeout= blocks until the child "
                    "exits"
                )
                continue
            if attr == "result" and not node.args and keyword(node, "timeout") is None:
                yield node, (
                    ".result() without timeout= parks on the future forever"
                )
                continue
            if attr == "settimeout" and node.args and is_none_constant(node.args[0]):
                yield node, "settimeout(None) makes the socket blocking-forever"
                continue
            if attr in _RECV_ATTRS and _receiver_hints_socket(func):
                if pf.rel in SANCTIONED_SOCKET_CORE:
                    continue  # the quantum-sliced I/O core itself
                # positional args to recv-family calls are byte counts,
                # never timeouts — only a timeout= keyword bounds them
                if has_finite_timeout(node, positional_ok=False):
                    continue  # exchange.recv(..., timeout=t) style wrappers
                if _function_bounds_socket(pf, node):
                    continue
                yield node, (
                    f"raw .{attr}() with no deadline in scope (no finite "
                    f"settimeout/poll in the enclosing function): an "
                    f"unbounded C-level socket wait blocks async raises — "
                    f"bound it or route through the store client's "
                    f"interruptible I/O core"
                )
                continue
            if (attr == "get" and not node.args
                    and keyword(node, "timeout") is None
                    and _receiver_hints_queue(func)):
                yield node, (
                    "queue .get() without timeout= blocks forever if the "
                    "producer dies"
                )
                continue

        if dotted in _SUBPROCESS_FUNCS and keyword(node, "timeout") is None:
            yield node, f"{dotted}() without timeout= can hang on the child"
            continue
        if dotted in ("socket.create_connection",) and len(node.args) < 2 \
                and keyword(node, "timeout") is None:
            yield node, (
                "socket.create_connection without timeout= inherits the "
                "global default (None)"
            )
            continue
        if dotted in ("select.select",) and len(node.args) == 3:
            yield node, "select.select without a timeout blocks forever"
