"""Shared detection of unbounded blocking calls.

Used by TPURX005 (deadline discipline everywhere) and TPURX006 (abort-path
safety), so both rules agree on what "blocks without a deadline" means.

The contract is intentionally about INTENT, not value: any non-None timeout
expression counts as bounded — the rule enforces that someone chose a bound,
not what the bound is.
"""

from __future__ import annotations

import ast

from .astutil import attr_chain, call_name, has_finite_timeout, keyword, is_none_constant

# attribute-call names that park the caller until an external event
_WAIT_ATTRS = {"wait", "wait_stale", "watch_stale"}

_SUBPROCESS_FUNCS = {
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.call",
}


def _receiver_hints_queue(func: ast.Attribute) -> bool:
    chain = attr_chain(func.value).lower()
    last = chain.rsplit(".", 1)[-1]
    return "queue" in last or last == "q" or last.endswith("_q")


def _inside_asyncio_wait_for(pf, node) -> bool:
    parent = pf.parent(node)
    # unwrap `await x.wait()` one level
    if isinstance(parent, ast.Await):
        parent = pf.parent(parent)
    return (
        isinstance(parent, ast.Call)
        and call_name(parent) in ("asyncio.wait_for", "wait_for")
        and node in ast.walk(parent)
    )


def unbounded_blocking_calls(pf, scope_node=None):
    """Yield (call_node, description) for every unbounded blocking call.

    ``scope_node`` limits the walk (used by the abort-path rule to scan one
    reachable function); default is the whole module.
    """
    root = scope_node if scope_node is not None else pf.tree
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        dotted = call_name(node)

        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr in _WAIT_ATTRS:
                if _inside_asyncio_wait_for(pf, node):
                    continue
                if not has_finite_timeout(node):
                    yield node, (
                        f".{attr}() without a finite timeout (event/condition/"
                        f"process wait can park forever — pass timeout=)"
                    )
                continue
            if attr == "join" and not node.args and not node.keywords:
                # zero-arg .join() can't be str.join (that needs an iterable)
                yield node, (
                    ".join() without a timeout (a wedged thread/process "
                    "parks the joiner forever — pass a bound)"
                )
                continue
            if attr == "join" and (node.args or node.keywords):
                # thread/process join with explicit timeout=None
                kw = keyword(node, "timeout")
                if kw is not None and is_none_constant(kw):
                    yield node, ".join(timeout=None) is unbounded"
                elif (not node.keywords and len(node.args) == 1
                      and is_none_constant(node.args[0])):
                    yield node, ".join(None) is unbounded"
                continue
            if attr == "communicate" and not has_finite_timeout(node):
                yield node, (
                    ".communicate() without timeout= blocks until the child "
                    "exits"
                )
                continue
            if attr == "result" and not node.args and keyword(node, "timeout") is None:
                yield node, (
                    ".result() without timeout= parks on the future forever"
                )
                continue
            if attr == "settimeout" and node.args and is_none_constant(node.args[0]):
                yield node, "settimeout(None) makes the socket blocking-forever"
                continue
            if (attr == "get" and not node.args
                    and keyword(node, "timeout") is None
                    and _receiver_hints_queue(func)):
                yield node, (
                    "queue .get() without timeout= blocks forever if the "
                    "producer dies"
                )
                continue

        if dotted in _SUBPROCESS_FUNCS and keyword(node, "timeout") is None:
            yield node, f"{dotted}() without timeout= can hang on the child"
            continue
        if dotted in ("socket.create_connection",) and len(node.args) < 2 \
                and keyword(node, "timeout") is None:
            yield node, (
                "socket.create_connection without timeout= inherits the "
                "global default (None)"
            )
            continue
        if dotted in ("select.select",) and len(node.args) == 3:
            yield node, "select.select without a timeout blocks forever"
