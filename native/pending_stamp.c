/* Pure-C main-thread liveness stamp for the progress watchdog.
 *
 * The watchdog proves the interpreter's MAIN thread still executes bytecode
 * by scheduling a pending call (reference: inprocess/progress_watchdog.py
 * uses a ctypes Python callback for the same purpose).  A *Python-level*
 * callback has a fatal interaction with the monitor thread's
 * PyThreadState_SetAsyncExc restart raise: the pending call and the async
 * exception are delivered by the same eval-breaker event, so the raise
 * reliably lands INSIDE the callback's frame, where it corrupts the ctypes
 * trampoline's error state (SystemError leaks into user code).
 *
 * This callback is pure C: no Python frame exists while it runs, so an
 * async exception can only be delivered to real user bytecode.  It touches
 * no Python API beyond Py_AddPendingCall (resolved in-process from the
 * already-loaded interpreter; the GIL is held by the eval loop when the
 * callback runs, and the scheduling side is async-signal-safe by CPython's
 * contract).
 *
 * Built as libtpurx-pending.so via native/Makefile; loaded with ctypes.
 * The Python-callback path remains as a fallback when the .so is absent.
 */

#include <stddef.h>
#include <sys/time.h>

/* declared instead of #include <Python.h>: the symbol resolves at load time
 * against the hosting interpreter, keeping the build header-free */
extern int Py_AddPendingCall(int (*func)(void *), void *arg);

typedef struct {
    double *timestamp;   /* shared epoch-seconds slot (mp.Value('d')) */
    long *consumed;      /* bumped per run: scheduler's consumption check */
} tpurx_stamp_refs;

static int stamp_cb(void *arg) {
    tpurx_stamp_refs *r = (tpurx_stamp_refs *)arg;
    struct timeval tv;
    gettimeofday(&tv, NULL);
    *r->timestamp = (double)tv.tv_sec + (double)tv.tv_usec * 1e-6;
    __sync_fetch_and_add(r->consumed, 1);
    return 0;
}

/* returns Py_AddPendingCall's result: 0 queued, -1 queue full */
int tpurx_schedule_stamp(void *refs) {
    return Py_AddPendingCall(stamp_cb, refs);
}
