/* Free-running liveness beater: a pthread stamping wall-clock milliseconds
 * into a caller-owned int64 slot at a fixed interval.
 *
 * Why native: the Python auto-beat thread's stamp jitter is GIL-scheduling
 * noise — measured p99 ~1 ms on a contended host — and the calibrated
 * detection budget must sit above safety*p99, putting a hard floor of
 * several ms on end-to-end hang detection.  A C thread never touches the
 * GIL, so its p99 is scheduler noise only (tens of µs), unlocking sub-ms
 * budgets for the PROCESS/DEVICE-liveness class of hangs.
 *
 * What it deliberately does NOT prove: interpreter schedulability.  A
 * GIL-wedged interpreter keeps a native beater stamping happily — exactly
 * the hang class the Python beater exists to catch — so callers pair this
 * with the pending-call watchdog ring (progress_watchdog.py), which owns
 * GIL-wedge detection (reference split: ProgressWatchdog auto timestamps
 * vs monitor-process soft/hard kills).
 *
 * Contract: the slot must stay valid until tpurx_beat_stop() returns.
 * Stores are a single aligned 64-bit write (atomic on every supported
 * target); readers see either the old or the new stamp, never a tear.
 */

#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <time.h>

typedef struct {
    pthread_t thread;
    int64_t *slot;
    int64_t interval_us;
    volatile int stop;
} tpurx_beater;

static int64_t now_ms(void) {
    /* folded into int32 range exactly like the Python side's
     * now_stamp_ms() — consumers mix the two stamp sources and their age
     * math is wrap-safe only on a shared epoch representation */
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return ((int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000)
           % ((int64_t)1 << 31);
}

static void *beat_loop(void *arg) {
    tpurx_beater *b = (tpurx_beater *)arg;
    struct timespec nap;
    nap.tv_sec = b->interval_us / 1000000;
    nap.tv_nsec = (b->interval_us % 1000000) * 1000;
    while (!b->stop) {
        __atomic_store_n(b->slot, now_ms(), __ATOMIC_RELAXED);
        nanosleep(&nap, NULL);
    }
    return NULL;
}

void *tpurx_beat_start(int64_t *slot, int64_t interval_us) {
    tpurx_beater *b = (tpurx_beater *)calloc(1, sizeof(tpurx_beater));
    if (!b) return NULL;
    b->slot = slot;
    b->interval_us = interval_us > 0 ? interval_us : 1000;
    *slot = now_ms();
    if (pthread_create(&b->thread, NULL, beat_loop, b) != 0) {
        free(b);
        return NULL;
    }
    return b;
}

/* ABI marker: v2 folds stamps into the int32 epoch (Python-side wrap
 * parity).  load_native requires this symbol, forcing a rebuild over any
 * stale v1 .so whose exported functions look identical. */
int tpurx_beat_abi_v2(void) { return 2; }

void tpurx_beat_stop(void *handle) {
    if (!handle) return;
    tpurx_beater *b = (tpurx_beater *)handle;
    b->stop = 1;
    pthread_join(b->thread, NULL);
    free(b);
}
