/* Free-running liveness beater (ABI v3): a pinned pthread stamping
 * wall-clock NANOSECONDS into a caller-owned int64 slot at a fixed
 * CLOCK_MONOTONIC cadence, bumping a caller-owned 32-bit generation word
 * and futex-waking any waiter on every beat.
 *
 * Why native: the Python auto-beat thread's stamp jitter is GIL-scheduling
 * noise — measured p99 ~1 ms on a contended host — and the calibrated
 * detection budget must sit above safety*p99, putting a hard floor of
 * several ms on end-to-end hang detection.  A C thread never touches the
 * GIL; pinned (CPU affinity + best-effort SCHED_FIFO) its p99 is tens of
 * µs, unlocking sub-ms budgets for the PROCESS/DEVICE-liveness class of
 * hangs.
 *
 * What it deliberately does NOT prove: interpreter schedulability.  A
 * GIL-wedged interpreter keeps a native beater stamping happily — exactly
 * the hang class the Python beater exists to catch — so callers pair this
 * with the pending-call watchdog ring (progress_watchdog.py), which owns
 * GIL-wedge detection.
 *
 * Clock domains (v3 contract, mirrored by ops/quorum.py):
 * - stamps: CLOCK_REALTIME ns folded into [0, 2^63) — wall clock so every
 *   process shares the epoch; age math on the Python side is wrap-safe
 *   mod 2^63 with a future==fresh clamp.
 * - cadence + jitter: CLOCK_MONOTONIC absolute deadlines — an NTP step can
 *   neither shorten/stretch the beat interval nor appear as jitter or a
 *   negative age.  EINTR re-enters the SAME absolute deadline (no silent
 *   interval shortening, no drift; the remainder is implicit in
 *   TIMER_ABSTIME).
 *
 * Contract: the slot AND the generation word must stay valid until
 * tpurx_beat_stop() returns (waiters may also touch the gen word after
 * stop — the Python side pins both for the beater's lifetime).  Stamp
 * stores are single aligned 64-bit writes (atomic on every supported
 * target); gen updates are atomic RMW with release ordering, so a waiter
 * woken by the gen bump always observes the new stamp.
 */

#ifndef _GNU_SOURCE
#define _GNU_SOURCE
#endif
#include <errno.h>
#include <limits.h>
#include <pthread.h>
#include <sched.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#ifdef __linux__
#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>
#endif

#define JITTER_RING 256

/* scheduling-state flag bits reported by tpurx_beat_flags */
#define TPURX_BEAT_PINNED 1
#define TPURX_BEAT_FIFO 2

typedef struct {
    pthread_t thread;
    int64_t *slot;
    uint32_t *gen;
    int64_t interval_ns;
    volatile int stop;
    int flags;
    /* CLOCK_MONOTONIC wake lateness per beat, most recent JITTER_RING */
    int64_t jitter[JITTER_RING];
    volatile uint32_t jitter_n;
} tpurx_beater;

static int64_t now_realtime_ns(void) {
    /* folded into [0, 2^63) exactly like the Python side's now_stamp_ns()
     * — consumers mix the two stamp sources and their age math is
     * wrap-safe only on a shared epoch representation */
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    uint64_t ns = (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
    return (int64_t)(ns & ((UINT64_C(1) << 63) - 1));
}

static int64_t mono_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000ll + ts.tv_nsec;
}

static void futex_wake_all(uint32_t *addr) {
#ifdef __linux__
    syscall(SYS_futex, addr, FUTEX_WAKE_PRIVATE, INT_MAX, NULL, NULL, 0);
#else
    (void)addr;
#endif
}

static void *beat_loop(void *arg) {
    tpurx_beater *b = (tpurx_beater *)arg;
    struct timespec deadline;
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    while (!b->stop) {
        __atomic_store_n(b->slot, now_realtime_ns(), __ATOMIC_RELAXED);
        if (b->gen) {
            __atomic_add_fetch(b->gen, 1, __ATOMIC_RELEASE);
            futex_wake_all(b->gen);
        }
        /* next absolute deadline; EINTR re-enters the SAME deadline, so a
         * signal can neither shorten the interval nor drift the cadence */
        deadline.tv_nsec += b->interval_ns;
        while (deadline.tv_nsec >= 1000000000l) {
            deadline.tv_nsec -= 1000000000l;
            deadline.tv_sec += 1;
        }
        int rc;
        do {
            rc = clock_nanosleep(CLOCK_MONOTONIC, TIMER_ABSTIME, &deadline,
                                 NULL);
        } while (rc == EINTR && !b->stop);
        /* wake lateness vs the scheduled deadline — monotonic, so NTP
         * steps cannot masquerade as beat jitter */
        int64_t late = mono_ns() -
                       ((int64_t)deadline.tv_sec * 1000000000ll +
                        deadline.tv_nsec);
        if (late < 0) late = 0;
        b->jitter[b->jitter_n % JITTER_RING] = late;
        __atomic_store_n(&b->jitter_n, b->jitter_n + 1, __ATOMIC_RELEASE);
        if (late > b->interval_ns * 4) {
            /* badly overslept (suspend, scheduler stall): resync instead of
             * bursting catch-up beats at zero interval */
            clock_gettime(CLOCK_MONOTONIC, &deadline);
        }
    }
    return NULL;
}

static void apply_sched(tpurx_beater *b, int pin_cpu, int rt_prio) {
    if (pin_cpu >= 0) {
        cpu_set_t set;
        CPU_ZERO(&set);
        CPU_SET((unsigned)pin_cpu, &set);
        if (pthread_setaffinity_np(b->thread, sizeof(set), &set) == 0)
            b->flags |= TPURX_BEAT_PINNED;
    }
    if (rt_prio > 0) {
        /* best-effort: EPERM without CAP_SYS_NICE is the common case —
         * fall back to CFS silently, the affinity pin still helps */
        struct sched_param sp;
        memset(&sp, 0, sizeof(sp));
        sp.sched_priority = rt_prio;
        if (pthread_setschedparam(b->thread, SCHED_FIFO, &sp) == 0)
            b->flags |= TPURX_BEAT_FIFO;
    }
}

void *tpurx_beat_start(int64_t *slot, uint32_t *gen, int64_t interval_us,
                       int pin_cpu, int rt_prio) {
    tpurx_beater *b = (tpurx_beater *)calloc(1, sizeof(tpurx_beater));
    if (!b) return NULL;
    b->slot = slot;
    b->gen = gen;
    b->interval_ns = (interval_us > 0 ? interval_us : 1000) * 1000;
    *slot = now_realtime_ns();
    if (pthread_create(&b->thread, NULL, beat_loop, b) != 0) {
        free(b);
        return NULL;
    }
    apply_sched(b, pin_cpu, rt_prio);
    return b;
}

void tpurx_beat_stop(void *handle) {
    if (!handle) return;
    tpurx_beater *b = (tpurx_beater *)handle;
    b->stop = 1;
    pthread_join(b->thread, NULL);
    free(b);
}

/* Stop stamping WITHOUT joining: the stamp freezes within one interval, as
 * it would on a real wedge — benchmarks measure freeze->detect without the
 * caller's join time polluting the latency.  tpurx_beat_stop() must still
 * follow to join and free. */
void tpurx_beat_freeze(void *handle) {
    if (!handle) return;
    ((tpurx_beater *)handle)->stop = 1;
}

int tpurx_beat_flags(void *handle) {
    if (!handle) return 0;
    return ((tpurx_beater *)handle)->flags;
}

/* Copy the most recent wake-lateness samples (ns) into out (up to cap);
 * returns the number copied.  Lock-free racy-read of a ring the beater
 * keeps appending to — samples are independent int64s, a torn count at
 * worst re-reads one slot. */
int tpurx_beat_jitter(void *handle, int64_t *out, int cap) {
    if (!handle || !out || cap <= 0) return 0;
    tpurx_beater *b = (tpurx_beater *)handle;
    uint32_t n = __atomic_load_n(&b->jitter_n, __ATOMIC_ACQUIRE);
    int have = n < JITTER_RING ? (int)n : JITTER_RING;
    if (have > cap) have = cap;
    for (int i = 0; i < have; i++) {
        /* newest-last order, walking back from the write cursor */
        uint32_t idx = (n - have + (uint32_t)i) % JITTER_RING;
        out[i] = b->jitter[idx];
    }
    return have;
}

/* Event-driven staleness wait: park on the generation word until either a
 * beat bumps it (return 0) or timeout_ns elapses with no beat (return 1 —
 * staleness observed at wake latency, not poll-interval granularity).
 * Returns 0 as well on EINTR/spurious wake (caller re-reads gen and
 * re-enters; the budget restarts, which only ever DELAYS a trip, never
 * fabricates one).  <0 = -errno (futex unavailable on this platform). */
int tpurx_beat_wait_stale(uint32_t *gen, uint32_t expected,
                          int64_t timeout_ns) {
#ifdef __linux__
    if (__atomic_load_n(gen, __ATOMIC_ACQUIRE) != expected) return 0;
    if (timeout_ns <= 0) return 1;
    struct timespec ts;
    ts.tv_sec = timeout_ns / 1000000000ll;
    ts.tv_nsec = timeout_ns % 1000000000ll;
    long rc = syscall(SYS_futex, gen, FUTEX_WAIT_PRIVATE, expected, &ts,
                      NULL, 0);
    if (rc == 0) return 0;               /* woken by a beat */
    if (errno == EAGAIN) return 0;       /* gen moved before we parked */
    if (errno == ETIMEDOUT) return 1;    /* stale: no beat within budget */
    if (errno == EINTR) return 0;        /* signal: caller re-arms */
    return -errno;
#else
    (void)gen; (void)expected; (void)timeout_ns;
    return -ENOSYS;
#endif
}

/* Bump gen + wake waiters WITHOUT a stamp: lets a stopping tripwire (or a
 * test) release a parked waiter at wake latency. */
void tpurx_beat_kick(uint32_t *gen) {
    if (!gen) return;
    __atomic_add_fetch(gen, 1, __ATOMIC_RELEASE);
    futex_wake_all(gen);
}

/* Epoch parity probes: tests cross-check the C and Python stamp domains
 * through the loaded .so instead of trusting the source comment. */
int64_t tpurx_beat_now_ns(void) { return now_realtime_ns(); }
int tpurx_beat_wrap_bits(void) { return 63; }

/* ABI marker: v3 stamps CLOCK_REALTIME nanoseconds folded mod 2^63 and
 * adds the generation word + futex surface.  load_native requires this
 * symbol, forcing a rebuild over any stale v2 .so (int32-ms stamps) whose
 * start/stop exports would otherwise load fine and silently corrupt the
 * ns-domain age math. */
int tpurx_beat_abi_v3(void) { return 3; }
