// tpurx native KV store server.
//
// Drop-in replacement for the Python asyncio server
// (tpu_resiliency/store/server.py) speaking the same wire protocol
// (tpu_resiliency/store/protocol.py):
//
//   request:  u8 opcode | u32 nargs | { u32 len | bytes }*
//   response: u8 status | u32 nargs | { u32 len | bytes }*
//
// Architecture: single-threaded epoll event loop — every mutation is atomic
// with respect to every other request (the same serializability argument the
// asyncio server makes), no locks, no GIL.  Blocking ops (GET/WAIT) park a
// waiter on the key; SET-like ops notify waiters; expiry runs off a deadline
// heap driving the epoll timeout.
//
// Reference analog: the role torch's C++ TCPStore daemon plays under NVRx's
// control plane (rendezvous CAS/counters, barriers, heartbeats) — the hot
// spot where Python-loop latency costs pod-scale restart time.
//
// Build: g++ -O2 -std=c++17 -o tpurx-store-server store_server.cpp

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <strings.h>
#include <sys/epoll.h>
#include <sys/file.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <deque>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// BEGIN GENERATED OP TABLE (source: tpu_resiliency/store/protocol.py;
// regenerate: python -m tpu_resiliency.store.protocol --cpp)
enum Op : uint8_t {
  OP_SET = 1,
  OP_GET = 2,
  OP_TRY_GET = 3,
  OP_ADD = 4,
  OP_APPEND = 5,
  OP_COMPARE_SET = 6,
  OP_WAIT = 7,
  OP_CHECK = 8,
  OP_DELETE = 9,
  OP_NUM_KEYS = 10,
  OP_PING = 11,
  OP_LIST_KEYS = 12,
  OP_MULTI_SET = 13,
  OP_MULTI_GET = 14,
  OP_MULTI_TRY_GET = 15,
  OP_APPEND_CHECK = 16,
  OP_ADD_SET = 17,
  OP_WAIT_GE = 18,
  OP_MUX = 19,
  OP__LAST = 19,
};
// END GENERATED OP TABLE

// protocol.ADD_SLOT: spliced into ADD_SET's set_value (first occurrence)
constexpr char kAddSlot[] = "%TPURX_N%";

enum Status : uint8_t {
  ST_OK = 0, ST_KEY_MISS = 1, ST_TIMEOUT = 2, ST_ERROR = 3, ST_CAS_FAIL = 4,
};

using Clock = std::chrono::steady_clock;
using Ms = std::chrono::milliseconds;

struct Conn;

struct Waiter {
  Conn* conn;                       // null once cancelled
  std::vector<std::string> keys;    // keys still missing
  Clock::time_point deadline;
  uint8_t op;                       // OP_GET, OP_WAIT, or OP_WAIT_GE
  std::string get_key;              // for OP_GET / OP_WAIT_GE
  long long threshold = 0;          // for OP_WAIT_GE
  std::string corr;                 // MUX correlation id ("" = plain op)
  uint64_t id;
};

struct Conn {
  int fd = -1;
  std::string in;                   // read buffer
  std::string out;                  // pending writes
  std::unordered_set<uint64_t> waiting_ids;
  // correlation id of the MUX envelope currently being handled; reply()
  // prepends it so subscription replies carry their id (out-of-order safe)
  std::string cur_corr;
  bool closed = false;
};

struct Store {
  std::unordered_map<std::string, std::string> data;
  // key -> waiter ids parked on it
  std::unordered_map<std::string, std::vector<uint64_t>> key_waiters;
  std::unordered_map<uint64_t, Waiter> waiters;
  std::priority_queue<
      std::pair<Clock::time_point, uint64_t>,
      std::vector<std::pair<Clock::time_point, uint64_t>>,
      std::greater<>>
      deadlines;
  uint64_t next_waiter_id = 1;
};

Store g_store;
int g_epfd = -1;
// TPURX_STORE_TEST_BROWNOUT: accept connections and read requests but never
// answer — the fault class where a shard looks alive at the TCP layer while
// its serving loop is wedged.  Clients must escape via per-op deadlines.
bool g_brownout = false;

// ---- journal ---------------------------------------------------------------
// Same on-disk format as the Python server (store/server.py: final-state
// records, replay order reconstructs the map), so a control plane can switch
// between the asyncio and native servers over one journal file:
//   'S' u32(klen) key u32(vlen) value     -- key set to value
//   'D' u32(klen) key                     -- key deleted
// Appends are fwrite+fflush per mutation; fsync runs on a 1s cadence driven
// by the epoll loop (matching the Python server's fsync interval).
// Compaction rewrites the journal as a snapshot of live data when appends
// exceed the cap, re-arming at max(cap, 2x snapshot) so a snapshot larger
// than the cap doesn't trigger an O(state) rewrite per mutation.  The
// snapshot write is inline (single-threaded loop): unlike the Python
// server's executor offload this briefly parks traffic, but the native
// write path makes the pause milliseconds at control-plane state sizes.

struct Journal {
  FILE* f = nullptr;
  std::string path;
  int lock_fd = -1;
  size_t bytes = 0;
  size_t max_bytes = 64ull << 20;
  size_t compact_at = 64ull << 20;
  bool dirty = false;
  Clock::time_point last_sync = Clock::now();
  size_t replayed = 0;
};
Journal g_journal;

void append_u32_j(std::string* s, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  s->append(b, 4);
}

std::string journal_record(const std::string& key, const std::string* value) {
  std::string rec;
  rec.push_back(value ? 'S' : 'D');
  append_u32_j(&rec, static_cast<uint32_t>(key.size()));
  rec.append(key);
  if (value) {
    append_u32_j(&rec, static_cast<uint32_t>(value->size()));
    rec.append(*value);
  }
  return rec;
}

void journal_disable() {
  if (g_journal.f) {
    fclose(g_journal.f);
    g_journal.f = nullptr;
    fprintf(stderr, "journal write failed; journal disabled\n");
  }
}

size_t journal_replay(const std::string& buf) {
  size_t i = 0, n = buf.size(), good = 0;
  while (i < n) {
    char tag = buf[i];
    if (tag == 'S') {
      if (i + 5 > n) break;
      uint32_t kl;
      memcpy(&kl, buf.data() + i + 1, 4);
      if (i + 5 + kl + 4 > n) break;
      std::string key = buf.substr(i + 5, kl);
      uint32_t vl;
      memcpy(&vl, buf.data() + i + 5 + kl, 4);
      size_t end = i + 9 + kl + vl;
      if (end > n) break;
      g_store.data[key] = buf.substr(i + 9 + kl, vl);
      i = end;
    } else if (tag == 'D') {
      if (i + 5 > n) break;
      uint32_t kl;
      memcpy(&kl, buf.data() + i + 1, 4);
      size_t end = i + 5 + kl;
      if (end > n) break;
      g_store.data.erase(buf.substr(i + 5, kl));
      i = end;
    } else {
      break;
    }
    good = i;
  }
  return good;
}

void journal_append(const std::string& key, const std::string* value);

bool journal_open(const std::string& path,
                  const std::vector<std::string>& strip_prefixes) {
  // exclusive sidecar lockfile: two servers interleaving appends on one
  // journal would corrupt exactly the state it exists to preserve; the
  // sidecar (not the journal fd) stays valid across compaction's rename
  std::string lock_path = path + ".lock";
  g_journal.lock_fd = open(lock_path.c_str(), O_CREAT | O_RDWR, 0644);
  if (g_journal.lock_fd < 0 || flock(g_journal.lock_fd, LOCK_EX | LOCK_NB) != 0) {
    fprintf(stderr, "journal %s is locked by another store instance\n",
            path.c_str());
    return false;
  }
  std::string buf;
  FILE* rf = fopen(path.c_str(), "rb");
  if (rf) {
    char chunk[1 << 16];
    size_t got;
    while ((got = fread(chunk, 1, sizeof(chunk), rf)) > 0) buf.append(chunk, got);
    fclose(rf);
  }
  size_t good = journal_replay(buf);
  if (good < buf.size())
    fprintf(stderr,
            "journal %s: truncated tail at byte %zu of %zu; discarding\n",
            path.c_str(), good, buf.size());
  g_journal.replayed = g_store.data.size();
  g_journal.path = path;
  g_journal.f = fopen(path.c_str(), good < buf.size() ? "rb+" : "ab");
  if (!g_journal.f) {
    fprintf(stderr, "journal %s: cannot open for append\n", path.c_str());
    return false;
  }
  if (good < buf.size()) {
    if (ftruncate(fileno(g_journal.f), static_cast<off_t>(good)) != 0)
      fprintf(stderr, "journal %s: truncate failed\n", path.c_str());
    fseek(g_journal.f, 0, SEEK_END);
  }
  g_journal.bytes = good;
  g_journal.compact_at = g_journal.max_bytes;
  // job-terminal keys must not replay into the next job
  for (const auto& prefix : strip_prefixes) {
    std::vector<std::string> doomed;
    for (const auto& [k, _] : g_store.data)
      if (k.rfind(prefix, 0) == 0) doomed.push_back(k);
    for (const auto& k : doomed) {
      g_store.data.erase(k);
      journal_append(k, nullptr);
      if (g_journal.replayed) g_journal.replayed--;
    }
  }
  if (g_journal.replayed)
    fprintf(stderr, "journal restored %zu key(s)\n", g_journal.replayed);
  return true;
}

void journal_compact() {
  std::string tmp = g_journal.path + ".tmp";
  FILE* tf = fopen(tmp.c_str(), "wb");
  if (!tf) return journal_disable();
  size_t snapshot_bytes = 0;
  for (const auto& [k, v] : g_store.data) {
    std::string rec = journal_record(k, &v);
    if (fwrite(rec.data(), 1, rec.size(), tf) != rec.size()) {
      fclose(tf);
      unlink(tmp.c_str());
      return journal_disable();
    }
    snapshot_bytes += rec.size();
  }
  fflush(tf);
  fsync(fileno(tf));
  fclose(tf);
  fclose(g_journal.f);
  g_journal.f = nullptr;
  if (rename(tmp.c_str(), g_journal.path.c_str()) != 0) {
    unlink(tmp.c_str());
    return journal_disable();
  }
  g_journal.f = fopen(g_journal.path.c_str(), "ab");
  if (!g_journal.f) return journal_disable();
  g_journal.bytes = snapshot_bytes;
  g_journal.compact_at = std::max(g_journal.max_bytes, 2 * snapshot_bytes);
  g_journal.dirty = false;
  fprintf(stderr, "journal compacted to %zu bytes (%zu keys)\n",
          snapshot_bytes, g_store.data.size());
}

void journal_append(const std::string& key, const std::string* value) {
  if (!g_journal.f) return;
  std::string rec = journal_record(key, value);
  if (fwrite(rec.data(), 1, rec.size(), g_journal.f) != rec.size() ||
      fflush(g_journal.f) != 0)
    return journal_disable();
  g_journal.bytes += rec.size();
  g_journal.dirty = true;
  if (g_journal.bytes > g_journal.compact_at) journal_compact();
}

void journal_maybe_fsync() {
  if (!g_journal.f || !g_journal.dirty) return;
  auto now = Clock::now();
  if (now - g_journal.last_sync < Ms(1000)) return;
  if (fsync(fileno(g_journal.f)) != 0) return journal_disable();
  g_journal.dirty = false;
  g_journal.last_sync = now;
}

void append_u32(std::string* s, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);  // little-endian hosts only (x86/arm64 LE)
  s->append(b, 4);
}

void encode_response(std::string* out, uint8_t status,
                     const std::vector<std::string>& args) {
  out->push_back(static_cast<char>(status));
  append_u32(out, static_cast<uint32_t>(args.size()));
  for (const auto& a : args) {
    append_u32(out, static_cast<uint32_t>(a.size()));
    out->append(a);
  }
}

void arm_write(Conn* c) {
  epoll_event ev{};
  ev.events = EPOLLIN | (c->out.empty() ? 0 : EPOLLOUT);
  ev.data.ptr = c;
  epoll_ctl(g_epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void reply(Conn* c, uint8_t status, const std::vector<std::string>& args) {
  if (g_brownout) return;  // test mode: read everything, answer nothing
  if (!c->cur_corr.empty()) {
    std::vector<std::string> wrapped;
    wrapped.reserve(args.size() + 1);
    wrapped.push_back(c->cur_corr);
    wrapped.insert(wrapped.end(), args.begin(), args.end());
    encode_response(&c->out, status, wrapped);
  } else {
    encode_response(&c->out, status, args);
  }
  arm_write(c);
}

void notify_key(const std::string& key);

void journal_append(const std::string& key, const std::string* value);

void do_set(const std::string& key, const std::string& value) {
  g_store.data[key] = value;
  journal_append(key, &value);
  notify_key(key);
}

// ---- waiters ---------------------------------------------------------------

bool parse_int(const std::string& s, long long* out);

long long int_value_of(const std::string& key) {
  // WAIT_GE semantics: a missing or non-integer key counts as 0
  long long cur = 0;
  auto it = g_store.data.find(key);
  if (it != g_store.data.end()) parse_int(it->second, &cur);
  return cur;
}

void complete_waiter(uint64_t id, bool timed_out) {
  auto it = g_store.waiters.find(id);
  if (it == g_store.waiters.end()) return;
  Waiter w = std::move(it->second);
  g_store.waiters.erase(it);
  // drop this waiter's id from any key list it is still parked on: sliced
  // clients re-park every ~2s, and on never-set keys the stale ids would
  // otherwise accumulate until the key is finally SET (or forever)
  for (const auto& k : w.keys) {
    auto kit = g_store.key_waiters.find(k);
    if (kit == g_store.key_waiters.end()) continue;
    auto& vec = kit->second;
    vec.erase(std::remove(vec.begin(), vec.end(), id), vec.end());
    if (vec.empty()) g_store.key_waiters.erase(kit);
  }
  if (!w.conn || w.conn->closed) return;
  w.conn->waiting_ids.erase(id);
  // restore the waiter's envelope: a parked MUX long-poll may complete from
  // inside another request's notify (possibly on the same connection), so
  // the corr in force at park time — not the current one — must frame it
  struct CorrScope {
    Conn* c;
    std::string saved;
    CorrScope(Conn* conn, const std::string& corr) : c(conn), saved(conn->cur_corr) {
      c->cur_corr = corr;
    }
    ~CorrScope() { c->cur_corr = saved; }
  } scope(w.conn, w.corr);
  if (timed_out) {
    reply(w.conn, ST_TIMEOUT, {});
  } else if (w.op == OP_GET) {
    auto d = g_store.data.find(w.get_key);
    if (d == g_store.data.end())
      reply(w.conn, ST_ERROR, {"key vanished"});
    else
      reply(w.conn, ST_OK, {d->second});
  } else if (w.op == OP_WAIT_GE) {
    reply(w.conn, ST_OK, {std::to_string(int_value_of(w.get_key))});
  } else {
    reply(w.conn, ST_OK, {});
  }
}

void notify_key(const std::string& key) {
  auto kit = g_store.key_waiters.find(key);
  if (kit == g_store.key_waiters.end()) return;
  std::vector<uint64_t> ids = std::move(kit->second);
  g_store.key_waiters.erase(kit);
  for (uint64_t id : ids) {
    auto wit = g_store.waiters.find(id);
    if (wit == g_store.waiters.end()) continue;
    Waiter& w = wit->second;
    if (w.op == OP_WAIT_GE) {
      // threshold waiter: the key existing is not enough — the value must
      // have reached the threshold, else re-park for the next bump
      if (int_value_of(w.get_key) >= w.threshold)
        complete_waiter(id, /*timed_out=*/false);
      else
        g_store.key_waiters[w.get_key].push_back(id);
      continue;
    }
    // drop this key; if all satisfied, complete
    auto& ks = w.keys;
    for (size_t i = 0; i < ks.size();) {
      if (g_store.data.count(ks[i]))
        ks.erase(ks.begin() + i);
      else
        ++i;
    }
    if (ks.empty()) complete_waiter(id, /*timed_out=*/false);
    else {
      // re-park on a remaining missing key
      g_store.key_waiters[ks.front()].push_back(id);
    }
  }
}

void park_waiter(Conn* c, uint8_t op, std::vector<std::string> missing,
                 const std::string& get_key, int64_t timeout_ms,
                 long long threshold = 0) {
  uint64_t id = g_store.next_waiter_id++;
  Waiter w;
  w.conn = c;
  w.keys = std::move(missing);
  w.deadline = Clock::now() + Ms(timeout_ms);
  w.op = op;
  w.get_key = get_key;
  w.threshold = threshold;
  w.corr = c->cur_corr;
  w.id = id;
  g_store.key_waiters[w.keys.front()].push_back(id);
  g_store.deadlines.emplace(w.deadline, id);
  c->waiting_ids.insert(id);
  g_store.waiters.emplace(id, std::move(w));
}

int next_timeout_ms() {
  while (!g_store.deadlines.empty()) {
    auto [dl, id] = g_store.deadlines.top();
    if (!g_store.waiters.count(id)) {
      g_store.deadlines.pop();
      continue;
    }
    auto now = Clock::now();
    if (dl <= now) return 0;
    return static_cast<int>(
        std::chrono::duration_cast<Ms>(dl - now).count() + 1);
  }
  return 1000;
}

void expire_waiters() {
  auto now = Clock::now();
  while (!g_store.deadlines.empty()) {
    auto [dl, id] = g_store.deadlines.top();
    if (dl > now) break;
    g_store.deadlines.pop();
    if (g_store.waiters.count(id)) complete_waiter(id, /*timed_out=*/true);
  }
}

// ---- request handling ------------------------------------------------------

bool parse_int(const std::string& s, long long* out) {
  if (s.empty()) return false;
  errno = 0;
  char* end = nullptr;
  long long v = strtoll(s.c_str(), &end, 10);
  if (errno || end != s.c_str() + s.size()) return false;
  *out = v;
  return true;
}

void handle_request(Conn* c, uint8_t op, std::vector<std::string> args) {
  auto& data = g_store.data;
  switch (op) {
    case OP_SET: {
      if (args.size() != 2) return reply(c, ST_ERROR, {"SET wants 2 args"});
      do_set(args[0], args[1]);
      return reply(c, ST_OK, {});
    }
    case OP_TRY_GET: {
      if (args.size() != 1) return reply(c, ST_ERROR, {"TRY_GET wants 1 arg"});
      auto it = data.find(args[0]);
      if (it == data.end()) return reply(c, ST_KEY_MISS, {});
      return reply(c, ST_OK, {it->second});
    }
    case OP_GET: {
      long long timeout_ms;
      if (args.size() != 2 || !parse_int(args[1], &timeout_ms))
        return reply(c, ST_ERROR, {"GET wants key,timeout_ms"});
      auto it = data.find(args[0]);
      if (it != data.end()) return reply(c, ST_OK, {it->second});
      park_waiter(c, OP_GET, {args[0]}, args[0], timeout_ms);
      return;
    }
    case OP_ADD: {
      long long amount, cur = 0;
      if (args.size() != 2 || !parse_int(args[1], &amount))
        return reply(c, ST_ERROR, {"ADD wants key,amount"});
      auto it = data.find(args[0]);
      if (it != data.end() && !parse_int(it->second, &cur))
        return reply(c, ST_ERROR, {"value not an integer"});
      long long nv = cur + amount;
      do_set(args[0], std::to_string(nv));
      return reply(c, ST_OK, {std::to_string(nv)});
    }
    case OP_APPEND: {
      if (args.size() != 2) return reply(c, ST_ERROR, {"APPEND wants 2 args"});
      std::string& v = data[args[0]];
      v.append(args[1]);
      journal_append(args[0], &v);  // final-state record
      std::string nlen = std::to_string(v.size());
      notify_key(args[0]);
      return reply(c, ST_OK, {nlen});
    }
    case OP_COMPARE_SET: {
      if (args.size() != 3) return reply(c, ST_ERROR, {"CAS wants 3 args"});
      auto it = data.find(args[0]);
      bool absent_ok = (it == data.end() && args[1].empty());
      if (absent_ok || (it != data.end() && it->second == args[1])) {
        do_set(args[0], args[2]);
        return reply(c, ST_OK, {args[2]});
      }
      return reply(c, ST_CAS_FAIL, {it == data.end() ? "" : it->second});
    }
    case OP_WAIT: {
      long long timeout_ms;
      if (args.empty() || !parse_int(args[0], &timeout_ms))
        return reply(c, ST_ERROR, {"WAIT wants timeout_ms,keys..."});
      std::vector<std::string> missing;
      for (size_t i = 1; i < args.size(); ++i)
        if (!data.count(args[i])) missing.push_back(args[i]);
      if (missing.empty()) return reply(c, ST_OK, {});
      park_waiter(c, OP_WAIT, std::move(missing), "", timeout_ms);
      return;
    }
    case OP_CHECK: {
      for (const auto& k : args)
        if (!data.count(k)) return reply(c, ST_OK, {"0"});
      return reply(c, ST_OK, {"1"});
    }
    case OP_DELETE: {
      if (args.size() != 1) return reply(c, ST_ERROR, {"DELETE wants 1 arg"});
      bool existed = data.erase(args[0]) > 0;
      if (existed) journal_append(args[0], nullptr);
      return reply(c, ST_OK, {existed ? "1" : "0"});
    }
    case OP_NUM_KEYS:
      return reply(c, ST_OK, {std::to_string(data.size())});
    case OP_PING:
      return reply(c, ST_OK, {"pong"});
    case OP_LIST_KEYS: {
      std::string prefix = args.empty() ? "" : args[0];
      std::vector<std::string> keys;
      for (const auto& [k, _] : data)
        if (k.rfind(prefix, 0) == 0) keys.push_back(k);
      return reply(c, ST_OK, keys);
    }
    case OP_MULTI_SET: {
      if (args.size() % 2) return reply(c, ST_ERROR, {"MULTI_SET wants pairs"});
      for (size_t i = 0; i + 1 < args.size(); i += 2) do_set(args[i], args[i + 1]);
      return reply(c, ST_OK, {});
    }
    case OP_MULTI_GET: {
      std::vector<std::string> vals;
      for (const auto& k : args) {
        auto it = data.find(k);
        if (it == data.end()) return reply(c, ST_KEY_MISS, {k});
        vals.push_back(it->second);
      }
      return reply(c, ST_OK, vals);
    }
    case OP_MULTI_TRY_GET: {
      // per-key misses: (flag, value) pairs, flag "0" + empty when absent
      std::vector<std::string> pairs;
      pairs.reserve(args.size() * 2);
      for (const auto& k : args) {
        auto it = data.find(k);
        if (it == data.end()) {
          pairs.push_back("0");
          pairs.push_back("");
        } else {
          pairs.push_back("1");
          pairs.push_back(it->second);
        }
      }
      return reply(c, ST_OK, pairs);
    }
    case OP_APPEND_CHECK: {
      // one-RTT barrier arrival: append + population check + done-key set
      // as one atomic step (see store/server.py for the reference semantics)
      if (args.size() < 5)
        return reply(c, ST_ERROR, {"APPEND_CHECK wants >=5 args"});
      long long required;
      if (!parse_int(args[4], &required))
        return reply(c, ST_ERROR, {"required not an integer"});
      std::string& v = data[args[0]];
      v.append(args[1]);
      journal_append(args[0], &v);
      size_t new_len = v.size();
      std::unordered_set<std::string> seen;
      size_t start = 0;
      while (start < v.size()) {
        size_t comma = v.find(',', start);
        if (comma == std::string::npos) comma = v.size();
        if (comma > start) seen.insert(v.substr(start, comma - start));
        start = comma + 1;
      }
      bool done;
      if (args.size() > 5) {  // narrowed participant set: exact membership
        done = true;
        for (size_t i = 5; i < args.size(); ++i)
          if (!seen.count(args[i])) {
            done = false;
            break;
          }
      } else {  // full population: distinct tokens (dedup re-entries)
        done = static_cast<long long>(seen.size()) >= required;
      }
      notify_key(args[0]);
      // do_set may rehash `data` — the reference v is dead past this point
      if (done) do_set(args[2], args[3]);
      return reply(c, ST_OK, {std::to_string(new_len), done ? "1" : "0"});
    }
    case OP_ADD_SET: {
      // one-RTT rendezvous join: counter bump + record write, splicing the
      // post-add value into the record at the first kAddSlot marker
      if (args.size() != 4)
        return reply(c, ST_ERROR, {"ADD_SET wants 4 args"});
      long long amount, cur = 0;
      if (!parse_int(args[1], &amount))
        return reply(c, ST_ERROR, {"ADD_SET amount not an integer"});
      auto it = data.find(args[0]);
      if (it != data.end() && !parse_int(it->second, &cur))
        return reply(c, ST_ERROR, {"value not an integer"});
      long long nv = cur + amount;
      do_set(args[0], std::to_string(nv));
      std::string sv = args[3];
      size_t slot = sv.find(kAddSlot);
      if (slot != std::string::npos)
        sv.replace(slot, sizeof(kAddSlot) - 1, std::to_string(nv));
      do_set(args[2], sv);
      return reply(c, ST_OK, {std::to_string(nv)});
    }
    case OP_WAIT_GE: {
      long long threshold, timeout_ms;
      if (args.size() != 3 || !parse_int(args[1], &threshold) ||
          !parse_int(args[2], &timeout_ms))
        return reply(c, ST_ERROR, {"WAIT_GE wants key,threshold,timeout_ms"});
      long long cur = int_value_of(args[0]);
      if (cur >= threshold) return reply(c, ST_OK, {std::to_string(cur)});
      park_waiter(c, OP_WAIT_GE, {args[0]}, args[0], timeout_ms, threshold);
      return;
    }
    case OP_MUX: {
      // correlated envelope: args[0]=corr id (ASCII decimal), args[1]=one
      // inner opcode byte, args[2:] the inner args.  The inner op runs with
      // cur_corr set, so its reply — immediate or from a parked waiter —
      // carries the corr id as its first arg and may be answered out of
      // order relative to other requests on this connection.
      if (args.size() < 2 || args[1].size() != 1)
        return reply(c, ST_ERROR, {"MUX wants corr,op,args..."});
      uint8_t inner = static_cast<uint8_t>(args[1][0]);
      std::string saved = c->cur_corr;
      c->cur_corr = args[0];
      if (inner < OP_SET || inner > OP__LAST || inner == OP_MUX)
        reply(c, ST_ERROR, {"bad inner op"});
      else
        handle_request(c, inner,
                       std::vector<std::string>(args.begin() + 2, args.end()));
      c->cur_corr = saved;
      return;
    }
    default:
      return reply(c, ST_ERROR, {"unknown op"});
  }
}

// Try to parse one complete frame from c->in; returns false if incomplete.
bool try_parse_frame(Conn* c) {
  const std::string& b = c->in;
  if (b.size() < 5) return false;
  uint8_t op = static_cast<uint8_t>(b[0]);
  uint32_t nargs;
  memcpy(&nargs, b.data() + 1, 4);
  if (nargs > 1u << 20) {  // sanity cap
    c->closed = true;
    return false;
  }
  size_t off = 5;
  std::vector<std::string> args;
  args.reserve(nargs);
  for (uint32_t i = 0; i < nargs; ++i) {
    if (b.size() < off + 4) return false;
    uint32_t len;
    memcpy(&len, b.data() + off, 4);
    if (len > 1u << 30) {
      c->closed = true;
      return false;
    }
    off += 4;
    if (b.size() < off + len) return false;
    args.emplace_back(b.data() + off, len);
    off += len;
  }
  c->in.erase(0, off);
  if (op < OP_SET || op > OP__LAST) {
    // unparseable stream from here on: drop the connection (matches the
    // Python server's behavior)
    c->closed = true;
    return false;
  }
  handle_request(c, op, std::move(args));
  return true;
}

void close_conn(Conn* c) {
  for (uint64_t id : c->waiting_ids) {
    auto it = g_store.waiters.find(id);
    if (it != g_store.waiters.end()) it->second.conn = nullptr;
  }
  epoll_ctl(g_epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  delete c;
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "0.0.0.0";
  int port = 29500;
  const char* journal_path = nullptr;
  std::vector<std::string> strip_prefixes;
  for (int i = 1; i < argc - 1; ++i) {
    if (!strcmp(argv[i], "--host")) host = argv[++i];
    else if (!strcmp(argv[i], "--port")) port = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--journal")) journal_path = argv[++i];
    else if (!strcmp(argv[i], "--journal-max-bytes"))
      g_journal.max_bytes = strtoull(argv[++i], nullptr, 10);
    else if (!strcmp(argv[i], "--strip-prefix"))
      strip_prefixes.push_back(argv[++i]);
  }
  signal(SIGPIPE, SIG_IGN);
  const char* bo = getenv("TPURX_STORE_TEST_BROWNOUT");
  if (bo && *bo && strcmp(bo, "0") != 0 && strcasecmp(bo, "false") != 0) {
    g_brownout = true;
    fprintf(stderr, "TEST MODE: brownout — accepting but never replying\n");
  }
  if (journal_path && !journal_open(journal_path, strip_prefixes)) return 1;

  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 1024) != 0) {
    perror("listen");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  fprintf(stderr, "tpurx-store-server (native) listening on %s:%d\n", host,
          ntohs(addr.sin_port));
  fflush(stderr);

  g_epfd = epoll_create1(0);
  epoll_event lev{};
  lev.events = EPOLLIN;
  lev.data.ptr = nullptr;  // marks the listener
  epoll_ctl(g_epfd, EPOLL_CTL_ADD, lfd, &lev);

  std::vector<epoll_event> events(256);
  while (true) {
    int tmo = next_timeout_ms();
    if (g_journal.dirty) tmo = std::min(tmo, 250);
    int n = epoll_wait(g_epfd, events.data(), static_cast<int>(events.size()),
                       tmo);
    expire_waiters();
    journal_maybe_fsync();
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {
        while (true) {
          int cfd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn();
          c->fd = cfd;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          epoll_ctl(g_epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      Conn* c = static_cast<Conn*>(events[i].data.ptr);
      if (events[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(c);
        continue;
      }
      if (events[i].events & EPOLLIN) {
        char buf[1 << 16];
        while (true) {
          ssize_t r = read(c->fd, buf, sizeof(buf));
          if (r > 0) {
            c->in.append(buf, static_cast<size_t>(r));
          } else if (r == 0) {
            c->closed = true;
            break;
          } else {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            c->closed = true;
            break;
          }
        }
        while (!c->closed && try_parse_frame(c)) {
        }
      }
      if (!c->closed && (events[i].events & EPOLLOUT)) arm_write(c);
      // flush pending output
      if (!c->closed && !c->out.empty()) {
        ssize_t wr = write(c->fd, c->out.data(), c->out.size());
        if (wr > 0) c->out.erase(0, static_cast<size_t>(wr));
        else if (wr < 0 && errno != EAGAIN && errno != EWOULDBLOCK)
          c->closed = true;
        arm_write(c);
      }
      if (c->closed) close_conn(c);
    }
  }
  return 0;
}
