/* Always-on per-op duration rings for the straggler collector.
 *
 * TPU-native analog of the reference's CUPTI circular per-kernel buffers
 * (cupti_src/CuptiProfiler.h:39-78 + BufferPool.cpp): constant-memory
 * circular buffers, continuously filled at dispatch rate, readable AT ANY
 * TIME without pausing collection.  Two properties the Python deque path
 * cannot give:
 *
 *   - push is a couple of stores (no allocator, no GIL-held bookkeeping
 *     beyond the ctypes call) — the hot path stays <1% of a step;
 *   - the arena lives in a SHARED MEMORY mapping, so the rank-monitor
 *     process can read a hung trainer's op stats post-mortem, exactly like
 *     CUPTI buffers outliving a wedged launch.
 *
 * Layout (all little-endian, 8-byte aligned):
 *   ArenaHeader { u64 magic; u32 max_ops; u32 capacity; u64 n_ops; }
 *   per op slot:
 *     OpHeader { u64 write_seq; u64 drops; char name[64]; }
 *     f32 durations[capacity]   (ring, index = seq % capacity)
 *
 * Concurrency: single WRITER per arena (the completion-watcher thread);
 * any number of readers.  write_seq is bumped AFTER the sample store with a
 * release barrier, so a reader taking min(seq, capacity) samples may miss
 * the newest sample but never reads a torn one (f32 stores are atomic on
 * every target we run on).
 */

#include <math.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define TPURX_RING_MAGIC 0x54505552494e4731ull /* "TPURING1" */
#define TPURX_NAME_LEN 64

typedef struct {
    uint64_t magic;
    uint32_t max_ops;
    uint32_t capacity;
    uint64_t n_ops;
} arena_header;

typedef struct {
    uint64_t write_seq;
    uint64_t drops;
    char name[TPURX_NAME_LEN];
} op_header;

typedef struct {
    uint64_t count;     /* total samples ever pushed */
    uint64_t drops;
    uint64_t window;    /* samples currently in the ring (<= capacity) */
    double total;       /* over the window */
    double mean;
    double median;
    double min;
    double max;
    double stddev;
} op_stats;

static size_t slot_size(uint32_t capacity) {
    return sizeof(op_header) + (size_t)capacity * sizeof(float);
}

static op_header *slot(void *base, uint32_t idx) {
    arena_header *h = (arena_header *)base;
    return (op_header *)((char *)base + sizeof(arena_header)
                         + (size_t)idx * slot_size(h->capacity));
}

static float *ring_of(op_header *s) {
    return (float *)((char *)s + sizeof(op_header));
}

size_t tpurx_ring_arena_size(uint32_t max_ops, uint32_t capacity) {
    return sizeof(arena_header) + (size_t)max_ops * slot_size(capacity);
}

int tpurx_ring_init(void *base, uint32_t max_ops, uint32_t capacity) {
    arena_header *h = (arena_header *)base;
    memset(base, 0, tpurx_ring_arena_size(max_ops, capacity));
    h->max_ops = max_ops;
    h->capacity = capacity;
    h->n_ops = 0;
    __atomic_store_n(&h->magic, TPURX_RING_MAGIC, __ATOMIC_RELEASE);
    return 0;
}

/* Register (or find) an op slot by name; returns index or -1 when full. */
int tpurx_ring_intern(void *base, const char *name) {
    arena_header *h = (arena_header *)base;
    if (h->magic != TPURX_RING_MAGIC) return -1;
    uint64_t n = h->n_ops;
    for (uint64_t i = 0; i < n; i++) {
        if (strncmp(slot(base, (uint32_t)i)->name, name, TPURX_NAME_LEN - 1) == 0)
            return (int)i;
    }
    if (n >= h->max_ops) return -1;
    op_header *s = slot(base, (uint32_t)n);
    strncpy(s->name, name, TPURX_NAME_LEN - 1);
    s->name[TPURX_NAME_LEN - 1] = '\0';
    /* publish the slot after the name is fully written */
    __atomic_store_n(&h->n_ops, n + 1, __ATOMIC_RELEASE);
    return (int)n;
}

void tpurx_ring_push(void *base, int op_idx, float duration_s) {
    arena_header *h = (arena_header *)base;
    if (h->magic != TPURX_RING_MAGIC || op_idx < 0
        || (uint32_t)op_idx >= h->n_ops)
        return;
    op_header *s = slot(base, (uint32_t)op_idx);
    uint64_t seq = s->write_seq;
    ring_of(s)[seq % h->capacity] = duration_s;
    __atomic_store_n(&s->write_seq, seq + 1, __ATOMIC_RELEASE);
}

void tpurx_ring_add_drop(void *base, int op_idx) {
    arena_header *h = (arena_header *)base;
    if (h->magic != TPURX_RING_MAGIC || op_idx < 0
        || (uint32_t)op_idx >= h->n_ops)
        return;
    op_header *s = slot(base, (uint32_t)op_idx);
    __atomic_fetch_add(&s->drops, 1, __ATOMIC_RELAXED);
}

uint64_t tpurx_ring_n_ops(void *base) {
    arena_header *h = (arena_header *)base;
    if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != TPURX_RING_MAGIC)
        return 0;
    return __atomic_load_n(&h->n_ops, __ATOMIC_ACQUIRE);
}

int tpurx_ring_name(void *base, int op_idx, char *out, size_t out_len) {
    arena_header *h = (arena_header *)base;
    if (h->magic != TPURX_RING_MAGIC || op_idx < 0
        || (uint32_t)op_idx >= h->n_ops)
        return -1;
    strncpy(out, slot(base, (uint32_t)op_idx)->name, out_len - 1);
    out[out_len - 1] = '\0';
    return 0;
}

static int cmp_float(const void *a, const void *b) {
    float fa = *(const float *)a, fb = *(const float *)b;
    return (fa > fb) - (fa < fb);
}

/* Copy-and-reduce the ring into stats — readable while the writer keeps
 * pushing (the copy races only with overwrites of the OLDEST samples). */
int tpurx_ring_stats(void *base, int op_idx, op_stats *out) {
    arena_header *h = (arena_header *)base;
    if (h->magic != TPURX_RING_MAGIC || op_idx < 0
        || (uint32_t)op_idx >= h->n_ops)
        return -1;
    op_header *s = slot(base, (uint32_t)op_idx);
    uint64_t seq = __atomic_load_n(&s->write_seq, __ATOMIC_ACQUIRE);
    uint64_t n = seq < h->capacity ? seq : h->capacity;
    memset(out, 0, sizeof(*out));
    out->count = seq;
    out->drops = __atomic_load_n(&s->drops, __ATOMIC_RELAXED);
    out->window = n;
    if (n == 0) return 0;
    float *copy = (float *)malloc(n * sizeof(float));
    if (!copy) return -1;
    memcpy(copy, ring_of(s), n * sizeof(float));
    double total = 0.0, mn = copy[0], mx = copy[0];
    for (uint64_t i = 0; i < n; i++) {
        double v = copy[i];
        total += v;
        if (v < mn) mn = v;
        if (v > mx) mx = v;
    }
    double mean = total / (double)n, var = 0.0;
    for (uint64_t i = 0; i < n; i++) {
        double d = copy[i] - mean;
        var += d * d;
    }
    qsort(copy, n, sizeof(float), cmp_float);
    out->total = total;
    out->mean = mean;
    out->min = mn;
    out->max = mx;
    out->stddev = sqrt(var / (double)n);
    out->median = (n % 2) ? copy[n / 2]
                          : 0.5 * ((double)copy[n / 2 - 1] + (double)copy[n / 2]);
    free(copy);
    return 0;
}
