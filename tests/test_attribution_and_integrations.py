"""Attribution, callbacks, log funnel, state machine, control plane tests."""

import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from tpu_resiliency.attribution import FailureCategory, LogAnalyzer
from tpu_resiliency.fault_tolerance.state_machine import (
    RestarterState,
    RestartStateMachine,
)
from tpu_resiliency.integrations import (
    CallbackRunner,
    FaultToleranceCallback,
    StragglerDetectionCallback,
)
from tpu_resiliency.straggler import Detector
from tpu_resiliency.utils.log_funnel import LogForwarder, RootLogServer

REPO = Path(__file__).resolve().parent.parent


class TestLogAnalyzer:
    def test_hbm_oom_no_resume(self):
        text = (
            "step 100 loss 3.2\n"
            "jaxlib.xla_extension.XlaRuntimeError: RESOURCE_EXHAUSTED: "
            "Out of memory while trying to allocate 12884901888 bytes in hbm\n"
        )
        v = LogAnalyzer().analyze_text(text)
        assert v.category == FailureCategory.OOM_HBM
        assert v.should_resume is False
        assert v.confidence >= 0.9

    def test_device_error_resumes(self):
        text = "[r3] INTERNAL: TPU initialization failed: device unhealthy\n"
        v = LogAnalyzer().analyze_text(text)
        assert v.category == FailureCategory.DEVICE_ERROR
        assert v.should_resume is True
        assert v.culprit_ranks == [3]

    def test_hang_kill_detected(self):
        text = "[tpurx.rank_monitor] hang detected (cycle=1 rank=2 pid=9): heartbeat gap exceeded 5.0s — terminating rank\n"
        v = LogAnalyzer().analyze_text(text)
        assert v.category == FailureCategory.HANG_KILL
        assert v.should_resume is True

    def test_nan_no_resume(self):
        v = LogAnalyzer().analyze_text("Fatal: loss is NaN at step 521\n")
        assert v.category == FailureCategory.NUMERICS
        assert v.should_resume is False

    def test_unknown_resumes(self):
        v = LogAnalyzer().analyze_text("everything is fine\nreally\n")
        assert v.category == FailureCategory.UNKNOWN
        assert v.should_resume is True

    def test_llm_fallback_used_when_rules_miss(self):
        calls = []

        def fake_llm(prompt):
            calls.append(prompt)
            return (
                'Sure: {"category": "device_error", "should_resume": true, '
                '"confidence": 0.8, "culprit_ranks": [3], '
                '"reason": "chip running hot"}'
            )

        # "error" keyword makes it a candidate but no rule matches
        v = LogAnalyzer(llm_fn=fake_llm).analyze_text("weird error xyzzy-42\n")
        assert calls
        assert v.should_resume is True
        assert v.category == FailureCategory.DEVICE_ERROR
        assert v.culprit_ranks == [3]
        assert "xyzzy-42" in calls[0]  # prompt carries the candidates


class TestStateMachine:
    def test_valid_path(self):
        sm = RestartStateMachine()
        for s in (
            RestarterState.INITIALIZED,
            RestarterState.HANDLING_START,
            RestarterState.PROCESSING,
            RestarterState.COMPLETED,
            RestarterState.FINALIZED,
        ):
            assert sm.transition(s)
        assert sm.state == RestarterState.FINALIZED

    def test_invalid_refused_not_raised(self):
        sm = RestartStateMachine()
        assert not sm.transition(RestarterState.PROCESSING)
        assert sm.state == RestarterState.UNINITIALIZED

    def test_in_restart(self):
        sm = RestartStateMachine()
        sm.transition(RestarterState.INITIALIZED)
        sm.transition(RestarterState.HANDLING_START)
        assert sm.in_restart


class _FakeClient:
    def __init__(self):
        self.heartbeats = 0
        self.is_initialized = False
        self.updates = 0

    def init_workload_monitoring(self):
        self.is_initialized = True

    def send_heartbeat(self):
        self.heartbeats += 1

    def calculate_and_set_hb_timeouts(self):
        self.updates += 1

    def state_dict(self):
        return {"hb_timeouts": None, "section_timeouts": None}

    def load_state_dict(self, s):
        pass

    def shutdown_workload_monitoring(self):
        self.is_initialized = False


def test_fault_tolerance_callback(tmp_path):
    client = _FakeClient()
    cb = FaultToleranceCallback(
        client=client, state_path=str(tmp_path / "ft.json"),
        warmup_steps=3, update_interval=4,
    )
    runner = CallbackRunner([cb])
    runner.on_train_start()
    assert client.is_initialized
    for step in range(10):
        runner.on_step_end(step=step)
    assert client.heartbeats >= 10
    assert client.updates >= 1
    runner.on_train_end()
    assert (tmp_path / "ft.json").exists()
    assert not client.is_initialized


def test_straggler_callback_reports():
    flagged = []
    cb = StragglerDetectionCallback(
        detector=Detector(report_interval=4),
        on_straggler=lambda v: flagged.append(v.rank),
    )
    runner = CallbackRunner([cb])
    runner.on_train_start()
    for step in range(8):
        runner.on_step_start(step=step)
        time.sleep(0.001)
        runner.on_step_end(step=step)
    assert cb.last_report is not None  # single rank: report exists, no flags


def test_callback_exceptions_do_not_kill_training():
    class Bad(FaultToleranceCallback):
        def __init__(self):
            pass

        def on_step_end(self, **ctx):
            raise RuntimeError("boom")

    runner = CallbackRunner([Bad()])
    runner.on_step_end(step=1)  # must not raise


def test_log_funnel_roundtrip(tmp_path):
    root = RootLogServer(str(tmp_path / "cluster.log"), host="127.0.0.1")
    import logging

    logger = logging.getLogger("funnel-test")
    logger.setLevel(logging.INFO)
    fwd = LogForwarder("127.0.0.1", root.port, source="nodeA", batch_age=0.1)
    fwd.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(fwd)
    for i in range(25):
        logger.info("line %d", i)
    time.sleep(0.5)
    fwd.close()
    logger.removeHandler(fwd)
    root.close()
    content = (tmp_path / "cluster.log").read_text()
    assert "[nodeA] line 0" in content
    assert "[nodeA] line 24" in content


def _run_control_plane_job(tmp_path, *, native=False, nnodes=2, iters=6,
                           extra_cp_args=()):
    """Shared scaffold: standalone control plane + N client launchers."""
    import os

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.update({"TPURX_REPO": str(REPO), "TOY_ITERS": str(iters),
                "TOY_CKPT": str(tmp_path / "p.txt"),
                "TPURX_FT_ENABLE_DEVICE_HEALTH_CHECK": "0"})
    cp_cmd = [sys.executable, "-m", "tpu_resiliency.fault_tolerance.control_plane",
              "--host", "127.0.0.1", "--port", str(port),
              "--min-nodes", str(nnodes), "--settle-time", "0.3",
              *extra_cp_args]
    if native:
        cp_cmd.append("--native-store")
    cp = subprocess.Popen(cp_cmd, cwd=str(REPO), env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    launchers, outs = [], []
    try:
        time.sleep(2.0)
        launchers = [
            subprocess.Popen(
                [sys.executable, "-m", "tpu_resiliency.fault_tolerance.launcher",
                 "--nnodes", str(nnodes), "--nproc-per-node", "1",
                 "--rdzv-endpoint", f"127.0.0.1:{port}",
                 "--node-id", f"n{i}", "--monitor-interval", "0.05",
                 str(REPO / "tests" / "workloads" / "toy_train.py")],
                cwd=str(REPO), env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            for i in range(nnodes)
        ]
        for p in launchers:
            out, _ = p.communicate(timeout=90)
            outs.append(out)
        cp_out, _ = cp.communicate(timeout=30)
    finally:
        # never leak the control plane (or launchers) into the session
        for p in launchers:
            if p.poll() is None:
                p.kill()
                p.communicate()
        if cp.poll() is None:
            cp.kill()
            cp.communicate()
    if any(p.returncode != 0 for p in launchers) or cp.returncode != 0:
        print("CP:", cp_out[-2000:])
        for i, o in enumerate(outs):
            print(f"L{i}:", o[-2000:])
    assert all(p.returncode == 0 for p in launchers)
    assert cp.returncode == 0
    assert int((tmp_path / "p.txt").read_text()) == iters


def test_control_plane_with_external_launchers(tmp_path):
    """Launchers as pure store clients against a standalone control plane."""
    _run_control_plane_job(tmp_path, nnodes=2, iters=6)


def test_control_plane_native_store(tmp_path):
    """Standalone control plane serving the C++ store to client launchers."""
    _run_control_plane_job(tmp_path, native=True, nnodes=1, iters=5)
