"""End-to-end checkpoint integrity: verified chunks, corrupt-shard
quarantine with peer re-election, and the fallback restore ladder.

Covers the full trust-boundary matrix: frame footer unit behavior, shard
digest verification on the global (async) path, own-blob quarantine +
peer retrieval, corrupt-holder re-election (a corrupt holder is never the
restore source), the cross-rank validity round gating the fallback ladder,
find_latest edge cases (empty holdings, quarantined iterations, keep_last
pruning racing a fallback load), the background scrubber, per-peer
exchange deadlines, and the checkpoint-corruption fault classes."""

import glob
import os
import shutil
import threading

import jax
import numpy as np
import pytest

from tpu_resiliency.checkpointing import integrity
from tpu_resiliency.checkpointing.integrity import (
    CheckpointCorruptError,
    chunk_crcs,
    combine_crcs,
    crc32,
    read_verified_blob,
    read_verified_shard,
    seal,
    verify_blob,
)
from tpu_resiliency.checkpointing.local.manager import LocalCheckpointManager
from tpu_resiliency.checkpointing.local.replication import (
    CliqueReplication,
    PeerExchange,
)
from tpu_resiliency.checkpointing.local.state_dict import TensorAwareTree
from tpu_resiliency.store import StoreClient
from tpu_resiliency.utils.inject_fault import Fault, corrupt_checkpoint


def make_tree(rank, seed=0):
    k = jax.random.PRNGKey(seed * 100 + rank)
    return {
        "w": jax.random.normal(k, (8, 4)),
        "step": np.int64(seed),
        "rank_marker": np.array([rank], dtype=np.int32),
    }


def _bitflip(path, off=64):
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


def _run_ranks(world, fn):
    errors, results = [], {}

    def wrap(rank):
        try:
            results[rank] = fn(rank)
        except Exception as exc:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            errors.append((rank, exc))

    threads = [threading.Thread(target=wrap, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    return results


# -- frame footer -------------------------------------------------------------


class TestFrameFooter:
    def test_seal_verify_roundtrip(self):
        payload = b"hello checkpoint" * 100
        sealed = seal(payload)
        verify_blob(sealed)  # no raise
        assert integrity.unseal(sealed).tobytes() == payload

    def test_bitflip_detected(self):
        sealed = bytearray(seal(b"x" * 4096))
        sealed[100] ^= 0x01
        with pytest.raises(CheckpointCorruptError, match="crc mismatch"):
            verify_blob(bytes(sealed))

    def test_truncation_detected(self):
        sealed = seal(b"y" * 4096)
        with pytest.raises(CheckpointCorruptError, match="truncated|footer"):
            verify_blob(sealed[: len(sealed) // 2])

    def test_unsealed_blob_rejected(self):
        with pytest.raises(CheckpointCorruptError, match="footer"):
            verify_blob(b"no footer here" * 10)

    def test_footer_transparent_to_from_bytes(self):
        tree = make_tree(0, seed=4)
        sealed = TensorAwareTree.from_tree(tree).to_bytes()  # seals by default
        verify_blob(sealed)
        rebuilt = TensorAwareTree.from_bytes(sealed).to_tree_like(tree)
        np.testing.assert_array_equal(
            np.asarray(rebuilt["w"]), np.asarray(tree["w"])
        )
        # zero-copy parse works on sealed blobs too
        rebuilt2 = TensorAwareTree.from_bytes(sealed, copy=False).to_tree_like(tree)
        np.testing.assert_array_equal(
            np.asarray(rebuilt2["w"]), np.asarray(tree["w"])
        )

    def test_unsealed_serialization_still_available(self):
        tree = make_tree(0)
        raw = TensorAwareTree.from_tree(tree).to_bytes(seal=False)
        with pytest.raises(CheckpointCorruptError):
            verify_blob(raw)
        TensorAwareTree.from_bytes(raw)  # parses fine


class TestChunkDigests:
    def test_combine_is_order_defined(self):
        crcs = [crc32(b"a"), crc32(b"b"), crc32(b"c")]
        assert combine_crcs(crcs) != combine_crcs(list(reversed(crcs)))
        assert combine_crcs(crcs) == combine_crcs(list(crcs))

    def test_chunk_crcs_granularity(self):
        data = os.urandom(10_000)
        crcs = chunk_crcs(data, 4096)
        assert len(crcs) == 3
        assert crcs[0] == crc32(data[:4096])
        assert crcs[2] == crc32(data[8192:])

    def test_read_verified_shard_spans(self, tmp_path):
        data = os.urandom(9000)
        path = str(tmp_path / "shard.bin")
        with open(path, "wb") as f:
            f.write(data)
        spans = [
            (0, 4096, crc32(data[:4096])),
            (4096, 4904, crc32(data[4096:])),
        ]
        composed = combine_crcs([c for _o, _l, c in spans])
        out = read_verified_shard(
            path, nbytes=9000, crc=composed, chunks=spans
        )
        assert out == data
        # bitflip inside span 1 -> error names the span offset
        _bitflip(path, off=5000)
        with pytest.raises(CheckpointCorruptError, match="offset 4096"):
            read_verified_shard(path, nbytes=9000, crc=composed, chunks=spans)

    def test_read_verified_shard_truncation_and_gaps(self, tmp_path):
        data = os.urandom(5000)
        path = str(tmp_path / "s.bin")
        with open(path, "wb") as f:
            f.write(data)
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            read_verified_shard(path, nbytes=6000)
        gap_spans = [(0, 1000, crc32(data[:1000])), (2000, 3000, crc32(data[2000:]))]
        with pytest.raises(CheckpointCorruptError, match="tile"):
            read_verified_shard(path, nbytes=5000, chunks=gap_spans)

    def test_legacy_shard_without_digests_passes(self, tmp_path):
        path = str(tmp_path / "legacy.bin")
        with open(path, "wb") as f:
            f.write(b"z" * 100)
        assert read_verified_shard(path, nbytes=100) == b"z" * 100


# -- global (async) path ------------------------------------------------------


def test_load_checkpoint_detects_shard_corruption(tmp_path):
    from tpu_resiliency.checkpointing import AsyncCheckpointer, load_checkpoint
    from tpu_resiliency.checkpointing.async_ckpt.checkpointer import (
        CachedMetadataReader,
    )

    tree = {"w": jax.device_put(np.arange(50000, dtype=np.float32))}
    d = str(tmp_path / "g1")
    ckpt = AsyncCheckpointer()
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
        stats = ckpt.last_drain_stats
        assert stats["digest"] and stats["crc_chunks"] >= 1
        assert stats["crc_ns"] > 0
        restored = load_checkpoint(d, tree, reader=CachedMetadataReader())
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(tree["w"])
        )
        shard = sorted(glob.glob(os.path.join(d, "process_0", "*.bin")))[0]
        _bitflip(shard, off=777)
        # resident=False: the disk-corruption lane — the warm shm-resident
        # source would (correctly) never see the flipped bit
        with pytest.raises(CheckpointCorruptError, match="corrupt chunk"):
            load_checkpoint(
                d, tree, reader=CachedMetadataReader(), resident=False
            )
    finally:
        ckpt.close()


def test_digest_off_save_is_legacy_readable(tmp_path):
    from tpu_resiliency.checkpointing import AsyncCheckpointer, load_checkpoint
    from tpu_resiliency.checkpointing.async_ckpt.writer import read_metadata

    tree = {"w": jax.device_put(np.arange(1000, dtype=np.float32))}
    d = str(tmp_path / "g2")
    ckpt = AsyncCheckpointer(digest=False)
    try:
        ckpt.save(tree, d, extra_metadata={"iteration": 1})
        assert ckpt.last_drain_stats["digest"] is False
        meta = read_metadata(d)
        assert all("crc" not in s and "chunks" not in s for s in meta["shards"])
        restored = load_checkpoint(d, tree)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(tree["w"])
        )
    finally:
        ckpt.close()


# -- local manager: quarantine, re-election, fallback ladder ------------------


def _mk_member(tmp_path, store_port, rank, world, ns, factor=2, **kw):
    store = StoreClient("127.0.0.1", store_port, timeout=15.0)
    ex = PeerExchange(store, rank, namespace=ns)
    repl = CliqueReplication(ex, world, replication_factor=factor)
    mgr = LocalCheckpointManager(
        str(tmp_path / f"node{rank}"), rank, world, store=store,
        replication=repl, **kw,
    )
    return store, ex, mgr


def test_own_blob_corrupt_quarantined_then_peer_restore(store_server, tmp_path):
    """A rank whose own blob rotted quarantines it and restores from the
    clique replica — with the quarantine debris left for post-mortem."""
    world = 2

    def phase1(rank):
        store, ex, mgr = _mk_member(tmp_path, store_server.port, rank, world, "qi1")
        try:
            mgr.save(make_tree(rank, seed=3), iteration=7, is_async=False)
        finally:
            ex.close()
            store.close()

    _run_ranks(world, phase1)
    own = str(tmp_path / "node1" / "default" / "iter_7" / "rank_1.tpurx")
    _bitflip(own)

    def phase2(rank):
        store, ex, mgr = _mk_member(tmp_path, store_server.port, rank, world, "qi2")
        try:
            tree, it = mgr.load(make_tree(rank), iteration=7)
            return int(np.asarray(tree["rank_marker"])[0]), it
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, phase2)
    assert results[1] == (1, 7)  # restored its own data from the peer replica
    assert os.path.exists(own + ".corrupt")
    assert not os.path.exists(own + ".done")


def test_corrupt_holder_never_restore_source(store_server, tmp_path):
    """Re-election: the elected holder's copy is corrupt — it is quarantined
    and the plan re-runs, restoring from the NEXT valid holder.  A corrupt
    holder must never be the restore source."""
    world = 3

    def phase1(rank):
        store, ex, mgr = _mk_member(
            tmp_path, store_server.port, rank, world, "qe1", factor=3
        )
        try:
            mgr.save(make_tree(rank, seed=5), iteration=2, is_async=False)
        finally:
            ex.close()
            store.close()

    _run_ranks(world, phase1)
    # rank 2 loses its disk; the FIRST-elected holder (rank 0) has a rotten
    # copy of rank 2's data — only rank 1's replica is valid
    shutil.rmtree(tmp_path / "node2" / "default")
    corrupt_copy = str(tmp_path / "node0" / "default" / "iter_2" / "rank_2.tpurx")
    _bitflip(corrupt_copy)

    def phase2(rank):
        store, ex, mgr = _mk_member(
            tmp_path, store_server.port, rank, world, "qe2", factor=3,
            peer_timeout=30.0,
        )
        try:
            tree, it = mgr.load(make_tree(rank), fallback=True)
            return int(np.asarray(tree["rank_marker"])[0]), it
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, phase2)
    assert results[2] == (2, 2), results  # correct data, newest iteration
    assert os.path.exists(corrupt_copy + ".corrupt")


def test_fallback_restores_next_oldest_valid(store_server, tmp_path):
    """Every copy of the newest iteration is corrupt -> the ladder restores
    the next-oldest iteration on all ranks and exports the depth."""
    world = 2

    def phase1(rank):
        store, ex, mgr = _mk_member(tmp_path, store_server.port, rank, world, "fb1")
        try:
            for it in (1, 2, 3):
                mgr.save(make_tree(rank, seed=it), iteration=it, is_async=False)
        finally:
            ex.close()
            store.close()

    _run_ranks(world, phase1)
    for blob in glob.glob(str(tmp_path / "node*" / "default" / "iter_3" / "*.tpurx")):
        _bitflip(blob)

    def phase2(rank):
        store, ex, mgr = _mk_member(tmp_path, store_server.port, rank, world, "fb2")
        try:
            tree, it = mgr.load(make_tree(rank), fallback=True)
            return it, int(np.asarray(tree["step"]))
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, phase2)
    for rank in range(world):
        assert results[rank] == (2, 2), results
    # every corrupted blob was quarantined
    debris = glob.glob(str(tmp_path / "node*" / "default" / "iter_3" / "*.corrupt"))
    assert len(debris) == 4  # 2 nodes x 2 blobs (factor 2)
    from tpu_resiliency.telemetry import get_registry

    assert get_registry().get("tpurx_ckpt_fallback_depth").value >= 1
    corrupt = get_registry().get("tpurx_ckpt_corrupt_detected_total")
    assert sum(v.get("value", 0) for _l, v in corrupt._sample_rows()) >= 1


def test_fallback_disabled_raises_on_corrupt_newest(store_server, tmp_path):
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(str(tmp_path / "solo"), 0, 1, store=store)
    try:
        for it in (1, 2):
            mgr.save(make_tree(0, seed=it), iteration=it, is_async=False)
        _bitflip(mgr._blob_path(2, 0))
        with pytest.raises(CheckpointCorruptError, match="validity"):
            mgr.load(make_tree(0))  # fallback defaults off
        # fallback walks to iteration 1
        tree, it = mgr.load(make_tree(0), fallback=True)
        assert it == 1
    finally:
        store.close()


# -- find_latest edge cases (satellite) ---------------------------------------


def test_find_latest_rank_with_empty_holdings(store_server, tmp_path):
    """A rank with an empty disk publishes empty holdings; coverage then
    depends entirely on replicas elsewhere."""
    world = 2

    def phase1(rank):
        store, ex, mgr = _mk_member(tmp_path, store_server.port, rank, world, "eh1")
        try:
            mgr.save(make_tree(rank, seed=1), iteration=4, is_async=False)
        finally:
            ex.close()
            store.close()

    _run_ranks(world, phase1)
    shutil.rmtree(tmp_path / "node1" / "default")

    def phase2(rank):
        store, ex, mgr = _mk_member(tmp_path, store_server.port, rank, world, "eh2")
        try:
            return mgr.find_latest()
        finally:
            ex.close()
            store.close()

    # factor-2 clique: node0 still holds BOTH blobs -> coverage stays full
    results = _run_ranks(world, phase2)
    assert results == {0: 4, 1: 4}

    # without replication nobody covers rank 1 -> no candidate
    def solo(rank):
        store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
        mgr = LocalCheckpointManager(
            str(tmp_path / "bare" / f"n{rank}"), rank, world, store=store,
            store_namespace="eh3",
        )
        try:
            if rank == 0:
                mgr.save(make_tree(0, seed=1), iteration=9, is_async=False)
            return mgr.find_latest()
        finally:
            store.close()

    assert _run_ranks(world, solo) == {0: None, 1: None}


def test_quarantined_iteration_excluded_from_coverage(store_server, tmp_path):
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(str(tmp_path / "q"), 0, 1, store=store)
    try:
        for it in (1, 2):
            mgr.save(make_tree(0, seed=it), iteration=it, is_async=False)
        assert mgr.find_latest() == 2
        _bitflip(mgr._blob_path(2, 0))
        assert not mgr.verify_iteration(2)  # quarantines
        assert mgr._holdings() == {1: [0]}
        assert mgr.find_latest() == 1
    finally:
        store.close()


def test_keep_last_pruning_races_fallback_load(store_server, tmp_path):
    """Holdings in the store still advertise an iteration whose dir was
    pruned on every rank (cleanup raced the gather).  The validity round
    re-publishes the truth and the ladder falls through to the survivor."""
    world = 2

    def phase1(rank):
        store, ex, mgr = _mk_member(
            tmp_path, store_server.port, rank, world, "pr1", keep_last=10
        )
        try:
            for it in (1, 2, 3):
                mgr.save(make_tree(rank, seed=it), iteration=it, is_async=False)
            # simulate keep_last pruning that raced: dir gone, stale store
            # holdings still claim it (no republish)
            shutil.rmtree(mgr._iter_dir(3))
        finally:
            ex.close()
            store.close()

    _run_ranks(world, phase1)

    def phase2(rank):
        store, ex, mgr = _mk_member(
            tmp_path, store_server.port, rank, world, "pr2", keep_last=10
        )
        try:
            # re-publish STALE holdings claiming iter 3 still exists, as the
            # racing window would have it
            import json

            stale = {str(it): [0, 1] for it in (1, 2, 3)}
            store.set(f"localckpt/holdings/{rank}", json.dumps(stale))
            tree, it = mgr.load(make_tree(rank), fallback=True)
            return it
        finally:
            ex.close()
            store.close()

    results = _run_ranks(world, phase2)
    assert results == {0: 2, 1: 2}, results


# -- scrubber -----------------------------------------------------------------


def test_scrubber_quarantines_rot(store_server, tmp_path):
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(str(tmp_path / "sc"), 0, 1, store=store)
    try:
        for it in (1, 2):
            mgr.save(make_tree(0, seed=it), iteration=it, is_async=False)
        _bitflip(mgr._blob_path(2, 0))
        assert mgr.scrub_once() == 1
        assert mgr.find_latest() == 1
        assert os.path.exists(mgr._blob_path(2, 0) + ".corrupt")
        # clean sweep finds nothing further
        assert mgr.scrub_once() == 0
    finally:
        store.close()


def test_scrubber_thread_lifecycle(store_server, tmp_path):
    store = StoreClient("127.0.0.1", store_server.port, timeout=15.0)
    mgr = LocalCheckpointManager(
        str(tmp_path / "sct"), 0, 1, store=store, scrub_interval=0.05
    )
    try:
        mgr.save(make_tree(0, seed=1), iteration=1, is_async=False)
        _bitflip(mgr._blob_path(1, 0))
        deadline = 10.0
        import time

        t0 = time.monotonic()
        while os.path.exists(mgr._blob_path(1, 0)) and time.monotonic() - t0 < deadline:
            time.sleep(0.05)
        assert os.path.exists(mgr._blob_path(1, 0) + ".corrupt")
    finally:
        mgr.stop_scrubber()
        store.close()


# -- per-peer exchange deadline (satellite) -----------------------------------


def test_execute_plan_deadline_bounds_dead_holder(store_server):
    """A recv from a holder that never sends surfaces as TimeoutError within
    the PLAN deadline — even with several pending receives — instead of
    blocking for the sum of sequential per-recv timeouts."""
    import time

    store = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
    ex = PeerExchange(store, 0, namespace="ddl")
    repl = CliqueReplication(ex, 2)
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            repl.execute_plan([], [(1, 7), (1, 8), (1, 9)], timeout=1.5)
        assert time.monotonic() - t0 < 4.0  # one shared deadline, not 3x
    finally:
        ex.close()
        store.close()


# -- fault classes (satellite) ------------------------------------------------


class TestCorruptionFaults:
    def _layout(self, tmp_path):
        root = tmp_path / "faults"
        for it in (1, 2):
            d = root / "n0" / "default" / f"iter_{it}"
            os.makedirs(d)
            blob = seal(os.urandom(2048))
            for r in (0, 1):
                p = d / f"rank_{r}.tpurx"
                p.write_bytes(blob)
                (d / f"rank_{r}.tpurx.done").write_text("ok")
        return str(root)

    def test_bitflip_targets_newest_and_crc_catches(self, tmp_path):
        root = self._layout(tmp_path)
        mutated = corrupt_checkpoint(root, Fault.CKPT_BITFLIP)
        assert len(mutated) == 2
        assert all("iter_2" in p for p in mutated)
        for p in mutated:
            with pytest.raises(CheckpointCorruptError):
                read_verified_blob(p)
        # iter_1 untouched
        read_verified_blob(os.path.join(
            root, "n0", "default", "iter_1", "rank_0.tpurx"))

    def test_truncate_caught_by_length(self, tmp_path):
        root = self._layout(tmp_path)
        mutated = corrupt_checkpoint(root, Fault.CKPT_TRUNCATE)
        for p in mutated:
            with pytest.raises(CheckpointCorruptError):
                read_verified_blob(p)

    def test_torn_index_cuts_local_footer(self, tmp_path):
        root = self._layout(tmp_path)
        mutated = corrupt_checkpoint(root, Fault.CKPT_TORN_INDEX)
        assert mutated
        for p in mutated:
            with pytest.raises(CheckpointCorruptError, match="footer|truncated"):
                read_verified_blob(p)

    def test_torn_index_global_metadata(self, tmp_path):
        import json

        root = tmp_path / "g"
        pdir = root / "ck" / "process_0"
        os.makedirs(pdir)
        (pdir / "shard_0_0.bin").write_bytes(os.urandom(512))
        meta = root / "ck" / "metadata.json"
        meta.write_text(json.dumps({"format": "tpurx-ckpt-v1", "shards": []}))
        mutated = corrupt_checkpoint(str(root), Fault.CKPT_TORN_INDEX)
        assert mutated == [str(meta)]
        with pytest.raises(json.JSONDecodeError):
            json.loads(meta.read_text())
