"""Services, trace analyzer, memory logger, cycle info tests."""

import json
import os
import threading
import time
import urllib.request

import pytest

from tpu_resiliency.attribution.trace_analyzer import (
    ProgressMarker,
    ProgressTraceRecorder,
    analyze_markers,
    collect_markers,
)
from tpu_resiliency.fault_tolerance.cycle_info import CycleInfoReporter
from tpu_resiliency.services.attrsvc import serve as attrsvc_serve
from tpu_resiliency.services.smonsvc import JobMonitor
from tpu_resiliency.utils.memory import DeviceMemoryLogger, device_memory_stats


class TestTraceAnalyzer:
    def _markers(self, steps, now=1000.0, phases=None):
        return {
            r: ProgressMarker(rank=r, iteration=0, step=s, ts=now - 1.0,
                              phase=(phases or {}).get(r, "step"))
            if s is not None else None
            for r, s in steps.items()
        }

    def test_lagging_rank_identified(self):
        m = self._markers({0: 100, 1: 100, 2: 97, 3: 100})
        res = analyze_markers(m, now=1000.0)
        assert res.category == "lagging_rank"
        assert res.culprit_ranks == [2]

    def test_dead_rank_identified(self):
        m = self._markers({0: 100, 1: None, 2: 100})
        res = analyze_markers(m, now=1000.0)
        assert res.category == "dead_rank"
        assert res.culprit_ranks == [1]

    def test_mismatched_phase(self):
        m = self._markers({0: 100, 1: 100}, phases={0: "step", 1: "eval"})
        res = analyze_markers(m, now=1000.0)
        assert res.category == "mismatched_program"
        assert res.should_resume is False

    def test_collective_stall(self):
        m = {r: ProgressMarker(rank=r, iteration=0, step=50, ts=900.0) for r in range(2)}
        res = analyze_markers(m, stale_after_s=30.0, now=1000.0)
        assert res.category == "collective_stall"
        assert res.culprit_ranks == [0, 1]

    def test_healthy(self):
        m = self._markers({0: 10, 1: 10})
        res = analyze_markers(m, now=1000.0)
        assert res.category == "healthy"

    def test_recorder_roundtrip(self, store):
        rec = ProgressTraceRecorder(store, rank=3, every=2)
        rec.record(step=4, iteration=1, phase="fwd")
        rec.record(step=5)  # skipped (every=2)
        markers = collect_markers(store, world_size=4)
        assert markers[3].step == 4
        assert markers[3].phase == "fwd"
        assert markers[0] is None


def test_cycle_info_reporter(tmp_path):
    rep = CycleInfoReporter(str(tmp_path), job_name="testjob")
    rep.start_cycle(0, 0, ["nodeA", "nodeB"], [], 8)
    rep.end_cycle("worker_failure", failed_ranks=[3])
    rep.start_cycle(1, 1, ["nodeA", "nodeB"], ["nodeC"], 8)
    current = tmp_path / "cycle_info.testjob.current"
    assert current.is_symlink()
    info = json.loads(current.read_text())
    assert info["cycle"] == 1
    assert info["standby"] == ["nodeC"]
    info0 = json.loads((tmp_path / "cycle_info.testjob.0.json").read_text())
    assert info0["end_reason"] == "worker_failure"
    assert info0["failed_ranks"] == [3]


def test_device_memory_stats():
    stats = device_memory_stats()
    assert len(stats) >= 1
    assert "device" in stats[0]
    logger = DeviceMemoryLogger(interval=0.05)
    sample = logger.sample()
    assert sample is logger.last_sample


@pytest.fixture
def attrsvc():
    server = attrsvc_serve(host="127.0.0.1", port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode())


def test_attrsvc_analyze_and_cache(attrsvc):
    with urllib.request.urlopen(attrsvc + "/health", timeout=5) as r:
        assert json.loads(r.read())["status"] == "ok"
    text = "XlaRuntimeError: RESOURCE_EXHAUSTED: out of memory in hbm\n"
    v1 = _post(attrsvc + "/analyze", {"text": text})
    assert v1["category"] == "oom_hbm"
    assert v1["should_resume"] is False
    v2 = _post(attrsvc + "/analyze", {"text": text})
    assert v2.get("cached") is True
    trace = _post(
        attrsvc + "/analyze_trace",
        {"markers": {
            "0": {"rank": 0, "iteration": 0, "step": 9, "ts": time.time()},
            "1": {"rank": 1, "iteration": 0, "step": 7, "ts": time.time()},
        }},
    )
    assert trace["category"] == "lagging_rank"
    assert trace["culprit_ranks"] == [1]


def test_smonsvc_watches_cycles(tmp_path, attrsvc):
    cycles = tmp_path / "cycles"
    logs = tmp_path / "logs"
    logs.mkdir()
    rep = CycleInfoReporter(str(cycles), job_name="j")
    (logs / "cycle_0.log").write_text(
        "[r2] XlaRuntimeError: RESOURCE_EXHAUSTED: allocating 1GB in hbm\n"
    )
    from tpu_resiliency.services.smonsvc import DirectoryScheduler

    mon = JobMonitor(
        DirectoryScheduler(str(cycles), str(logs)),
        attrsvc_url=attrsvc, poll_interval=0.1,
    )
    rep.start_cycle(0, 0, ["n0"], [], 4)
    rep.end_cycle("worker_failure", failed_ranks=[2])
    mon.poll_once()
    assert mon.totals["cycles_failed"] == 1
    assert mon.verdicts.get("oom_hbm") == 1
    # second poll: no double counting
    mon.poll_once()
    assert mon.totals["cycles_observed"] == 1


class TestCombinedAttribution:
    def test_deterministic_log_overrides_trace(self):
        from tpu_resiliency.attribution.combined import analyze_combined
        from tpu_resiliency.attribution.trace_analyzer import ProgressMarker
        import time as _t

        now = _t.time()
        markers = {
            0: ProgressMarker(rank=0, iteration=0, step=10, ts=now),
            1: ProgressMarker(rank=1, iteration=0, step=8, ts=now),
        }
        res = analyze_combined(
            "XlaRuntimeError: RESOURCE_EXHAUSTED: allocating in hbm\n", markers
        )
        assert res.should_resume is False
        assert res.category == "oom_hbm"
        assert 1 in res.culprit_ranks

    def test_silent_hang_becomes_device_suspect(self):
        from tpu_resiliency.attribution.combined import analyze_combined
        from tpu_resiliency.attribution.trace_analyzer import ProgressMarker
        import time as _t

        now = _t.time()
        markers = {
            0: ProgressMarker(rank=0, iteration=0, step=10, ts=now),
            1: ProgressMarker(rank=1, iteration=0, step=3, ts=now),
        }
        res = analyze_combined("clean logs, nothing of note\n", markers)
        assert res.category == "suspected_device_hang"
        assert res.culprit_ranks == [1]
        assert res.should_resume is True


# -- smonsvc fleet depth (multi-job, windows, slurm adapter, status) ---------


def test_smonsvc_multijob_discovery_and_states(tmp_path):
    from tpu_resiliency.services.smonsvc import (
        JobMonitor,
        JobState,
        MultiJobDirectoryScheduler,
    )

    root = tmp_path / "jobs"
    for name in ("alpha", "beta"):
        rep = CycleInfoReporter(str(root / name / "cycles"), job_name=name)
        (root / name / "logs").mkdir(parents=True)
        rep.start_cycle(0, 0, ["n0"], [], 4)
        if name == "alpha":
            rep.end_cycle("success")
    (root / "not-a-job").mkdir()

    mon = JobMonitor(MultiJobDirectoryScheduler(str(root)), poll_interval=0.1)
    mon.poll_once()
    jobs = {j["job_id"]: j for j in mon.jobs_payload()}
    assert set(jobs) == {"alpha", "beta"}
    assert jobs["alpha"]["state"] == JobState.FINISHED.value
    assert jobs["beta"]["state"] == JobState.RUNNING.value
    st = mon.status()
    assert st["jobs"]["total"] == 2
    assert st["totals"]["jobs_seen"] == 2


def test_smonsvc_restart_windows_and_crash_loop(tmp_path):
    import time as _t

    from tpu_resiliency.services.smonsvc import (
        DirectoryScheduler,
        JobMonitor,
    )

    cycles = tmp_path / "cycles"
    rep = CycleInfoReporter(str(cycles), job_name="j")
    mon = JobMonitor(
        DirectoryScheduler(str(cycles)), poll_interval=0.1,
        crash_loop_threshold_15m=3,
    )
    for c in range(4):
        rep.start_cycle(c, c, ["n0"], [], 4)
        rep.end_cycle("worker_failure", failed_ranks=[0])
        mon.poll_once()
    st = mon.status()
    assert st["restarts_15m"] == 4
    assert st["restarts_1h"] == 4
    assert st["crash_looping"] is True
    assert st["totals"]["cycles_failed"] == 4
    # old events age out of the window
    mon.windows._events.clear()
    mon.windows.record(_t.time() - 1000)  # outside 15m, inside 1h
    st = mon.status()
    assert st["restarts_15m"] == 0 and st["restarts_1h"] == 1
    assert st["crash_looping"] is False


def test_smonsvc_gone_job_marked(tmp_path):
    import shutil as _sh

    from tpu_resiliency.services.smonsvc import (
        JobMonitor,
        JobState,
        MultiJobDirectoryScheduler,
    )

    root = tmp_path / "jobs"
    rep = CycleInfoReporter(str(root / "solo" / "cycles"), job_name="solo")
    rep.start_cycle(0, 0, ["n0"], [], 2)
    mon = JobMonitor(MultiJobDirectoryScheduler(str(root)), poll_interval=0.1)
    mon.poll_once()
    assert mon.jobs["solo"].state == JobState.RUNNING
    _sh.rmtree(root / "solo")
    mon.poll_once()
    assert mon.jobs["solo"].state == JobState.GONE


def test_smonsvc_slurm_adapter_with_fake_binaries(tmp_path, monkeypatch):
    """SlurmScheduler drives squeue/scontrol; fake binaries on PATH emulate
    a 2-job cluster (reference slurm.py discovery, compressed)."""
    from tpu_resiliency.services.smonsvc import SlurmScheduler

    bindir = tmp_path / "bin"
    bindir.mkdir()
    outdir = tmp_path / "out"
    outdir.mkdir()
    (bindir / "squeue").write_text("#!/bin/sh\necho 101\necho 202\n")
    (bindir / "scontrol").write_text(
        "#!/bin/sh\n"
        f"echo JobId=$4 StdOut={outdir}/job$4.out Other=x\n"
    )
    for b in ("squeue", "scontrol"):
        (bindir / b).chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    sched = SlurmScheduler(user="me")
    assert sched.available()
    jobs = sched.discover()
    assert [j[0] for j in jobs] == ["101", "202"]
    # StdOut dir becomes the log dir
    assert jobs[0][2] == str(outdir)
    assert sched.squeue_calls == 1 and sched.scontrol_calls == 2


def test_smonsvc_gke_jobset_adapter_with_fake_kubectl(tmp_path, monkeypatch):
    """GkeJobSetScheduler drives ``kubectl get jobsets -o json``; a fake
    kubectl emulates a fleet with one active, one completed, and one
    suspended JobSet.  Terminal JobSets are excluded from discovery (parity
    with SLURM's RUNNING filter) but counted in the stats payload."""
    from tpu_resiliency.services.smonsvc import GkeJobSetScheduler

    payload = {
        "items": [
            {"metadata": {"name": "llama-70b"},
             "status": {"conditions": [
                 {"type": "Completed", "status": "False"}]}},
            {"metadata": {"name": "old-run"},
             "status": {"conditions": [
                 {"type": "Completed", "status": "True"}]}},
            {"metadata": {"name": "paused"},
             "spec": {"suspend": True}, "status": {}},
        ]
    }
    bindir = tmp_path / "bin"
    bindir.mkdir()
    (bindir / "kubectl").write_text(
        "#!/bin/sh\ncat <<'EOF'\n" + json.dumps(payload) + "\nEOF\n"
    )
    (bindir / "kubectl").chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    root = tmp_path / "artifacts"
    (root / "llama-70b" / "cycles").mkdir(parents=True)
    (root / "llama-70b" / "logs").mkdir()

    sched = GkeJobSetScheduler(str(root), namespace="training")
    assert sched.available()
    jobs = sched.discover()
    # active + suspended are tracked; completed is terminal
    assert sorted(j[0] for j in jobs) == ["llama-70b", "paused"]
    by_id = {j[0]: j for j in jobs}
    assert by_id["llama-70b"][1] == str(root / "llama-70b" / "cycles")
    assert by_id["llama-70b"][2] == str(root / "llama-70b" / "logs")
    stats = sched.stats_payload()
    assert stats["jobset_states"] == {
        "ACTIVE": 1, "COMPLETED": 1, "SUSPENDED": 1,
    }
    assert stats["errors"] == 0


def test_smonsvc_gke_all_namespaces_artifacts_use_bare_name(tmp_path, monkeypatch):
    """ADVICE r5: in --all-namespaces mode job ids are '<ns>/<name>' (the
    collision-safe tracking key), but artifacts live under the launcher
    convention '<root>/<name>/...' — discovery must path by the bare name."""
    from tpu_resiliency.services.smonsvc import GkeJobSetScheduler

    payload = {
        "items": [
            {"metadata": {"name": "llama-70b", "namespace": "team-a"},
             "status": {}},
            {"metadata": {"name": "llama-70b", "namespace": "team-b"},
             "status": {}},
        ]
    }
    bindir = tmp_path / "bin"
    bindir.mkdir()
    (bindir / "kubectl").write_text(
        "#!/bin/sh\ncat <<'EOF'\n" + json.dumps(payload) + "\nEOF\n"
    )
    (bindir / "kubectl").chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    root = tmp_path / "artifacts"
    (root / "llama-70b" / "cycles").mkdir(parents=True)
    (root / "llama-70b" / "logs").mkdir()

    sched = GkeJobSetScheduler(str(root))  # namespace=None -> --all-namespaces
    jobs = sched.discover()
    # tracking keys stay namespaced (no cross-namespace shadowing)...
    assert sorted(j[0] for j in jobs) == [
        "team-a/llama-70b", "team-b/llama-70b",
    ]
    # ...but every job's artifacts resolve under the bare JobSet name
    for _, cdir, ldir in jobs:
        assert cdir == str(root / "llama-70b" / "cycles")
        assert ldir == str(root / "llama-70b" / "logs")


def test_smonsvc_gke_monitor_integration(tmp_path, monkeypatch):
    """A JobMonitor over the GKE adapter tracks a jobset through its cycle
    files and surfaces the adapter stats under /status's ``gke`` key."""
    from tpu_resiliency.services.smonsvc import GkeJobSetScheduler, JobMonitor

    payload = {"items": [{"metadata": {"name": "j1"}, "status": {}}]}
    bindir = tmp_path / "bin"
    bindir.mkdir()
    (bindir / "kubectl").write_text(
        "#!/bin/sh\ncat <<'EOF'\n" + json.dumps(payload) + "\nEOF\n"
    )
    (bindir / "kubectl").chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    root = tmp_path / "artifacts"
    cycles = root / "j1" / "cycles"
    rep = CycleInfoReporter(str(cycles), job_name="j1")
    rep.start_cycle(0, 0, ["n0"], [], 2)
    rep.end_cycle("success")

    mon = JobMonitor(GkeJobSetScheduler(str(root)), poll_interval=0.1)
    mon.poll_once()
    st = mon.status()
    assert st["jobs"]["total"] == 1
    assert st["gke"]["calls"] == 1
    assert mon.jobs["j1"].cycles_observed == 1


def test_smonsvc_queued_resources_adapter_with_fake_gcloud(
    tmp_path, monkeypatch
):
    """QueuedResourceScheduler drives ``gcloud compute tpus queued-resources
    list``; only ACTIVE reservations become tracked jobs."""
    from tpu_resiliency.services.smonsvc import QueuedResourceScheduler

    payload = [
        {"name": "projects/p/locations/us-central2-b/queuedResources/qr-a",
         "state": {"state": "ACTIVE"}},
        {"name": "projects/p/locations/us-central2-b/queuedResources/qr-b",
         "state": {"state": "WAITING"}},
        {"name": "projects/p/locations/us-central2-b/queuedResources/qr-c",
         "state": {"state": "FAILED"}},
    ]
    bindir = tmp_path / "bin"
    bindir.mkdir()
    (bindir / "gcloud").write_text(
        "#!/bin/sh\ncat <<'EOF'\n" + json.dumps(payload) + "\nEOF\n"
    )
    (bindir / "gcloud").chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    root = tmp_path / "artifacts"
    (root / "qr-a").mkdir(parents=True)

    sched = QueuedResourceScheduler(str(root), project="p",
                                    zone="us-central2-b")
    assert sched.available()
    jobs = sched.discover()
    assert [j[0] for j in jobs] == ["qr-a"]
    assert jobs[0][1] == str(root / "qr-a")  # no cycles/ subdir: flat
    stats = sched.stats_payload()
    assert stats["qr_states"] == {"ACTIVE": 1, "WAITING": 1, "FAILED": 1}


def test_smonsvc_gke_all_namespaces_keys_by_namespace(tmp_path, monkeypatch):
    """--all-namespaces mode must key jobsets as <namespace>/<name>: a
    terminal duplicate name in another namespace must not shadow a live
    job."""
    from tpu_resiliency.services.smonsvc import GkeJobSetScheduler

    payload = {
        "items": [
            {"metadata": {"name": "train", "namespace": "team-a"},
             "status": {}},
            {"metadata": {"name": "train", "namespace": "team-b"},
             "status": {"conditions": [
                 {"type": "Completed", "status": "True"}]}},
        ]
    }
    bindir = tmp_path / "bin"
    bindir.mkdir()
    (bindir / "kubectl").write_text(
        "#!/bin/sh\ncat <<'EOF'\n" + json.dumps(payload) + "\nEOF\n"
    )
    (bindir / "kubectl").chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    sched = GkeJobSetScheduler(str(tmp_path / "artifacts"))
    states = sched.states()
    assert states == {"team-a/train": "ACTIVE", "team-b/train": "COMPLETED"}
    assert [j[0] for j in sched.discover()] == ["team-a/train"]


def test_smonsvc_adapters_degrade_without_binaries(tmp_path, monkeypatch):
    """No kubectl/gcloud on PATH: adapters report unavailable and discovery
    returns empty instead of crashing the monitor loop."""
    from tpu_resiliency.services.smonsvc import (
        GkeJobSetScheduler,
        QueuedResourceScheduler,
    )

    monkeypatch.setenv("PATH", str(tmp_path))  # empty dir
    gke = GkeJobSetScheduler(str(tmp_path))
    qr = QueuedResourceScheduler(str(tmp_path))
    assert not gke.available() and not qr.available()
    assert gke.discover() == [] and qr.discover() == []
    assert gke.errors == 1 and qr.errors == 1


def test_smonsvc_status_server_endpoints(tmp_path):
    import urllib.request as _rq

    from tpu_resiliency.services.smonsvc import (
        DirectoryScheduler,
        JobMonitor,
        make_status_server,
    )

    cycles = tmp_path / "cycles"
    rep = CycleInfoReporter(str(cycles), job_name="j")
    rep.start_cycle(0, 0, ["n0"], [], 2)
    mon = JobMonitor(DirectoryScheduler(str(cycles)), poll_interval=0.1)
    mon.poll_once()
    server = make_status_server(mon, "127.0.0.1", 0)
    port = server.server_port
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        st = json.loads(_rq.urlopen(f"http://127.0.0.1:{port}/status").read())
        assert st["jobs"]["total"] == 1
        jobs = json.loads(_rq.urlopen(f"http://127.0.0.1:{port}/jobs").read())
        assert jobs[0]["job_id"] == "default"
        health = json.loads(_rq.urlopen(f"http://127.0.0.1:{port}/health").read())
        assert health["status"] == "ok"
        # /metrics: smonsvc's own registry, plus spliced job-level aggregates
        mon.aggregated_text_fn = lambda: 'tpurx_job_probe{agg="sum"} 42'
        body = _rq.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
        assert body.rstrip().endswith("# EOF")
        assert "tpurx_smonsvc_polls_total" in body
        assert 'tpurx_job_probe{agg="sum"} 42' in body
        eof_at = body.index("# EOF")
        assert body.index("tpurx_job_probe") < eof_at
    finally:
        server.shutdown()
