"""Flight recorder, fault episodes, clock alignment, trace merge, exporter.

Covers the observability stack end to end:

- ``telemetry/flight.py``: ring semantics, declaration discipline, the
  ``TPURX_FLIGHT=0`` no-op path, JSONL dumps (meta header, throttling,
  retention, hooks).
- ``telemetry/episode.py``: phase decomposition summing to wall time by
  construction, the store-minted id, cross-rank claim convergence,
  sidecar adoption, ``read_episodes``.
- ``telemetry/clock.py``: RTT-midpoint calibration against a live
  reference recovers a known injected skew.
- ``telemetry/trace.py``: per-file offset alignment, unaligned-host
  warning, span pairing, episode phase spans and cross-rank flows.
- ``telemetry/exporter.py``: OpenMetrics escaping golden, concurrent
  scrape under mutation, ``GET /flight``.
- A two-rank soak (one rank's clock skewed to simulate a second host):
  black-box dumps at trip time, ONE merged aligned timeline with the
  episode's six phases and flow arrows, and ``GET /episodes`` matching
  the store's phase totals.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import types
import urllib.request
from pathlib import Path

import pytest

from tpu_resiliency.telemetry import clock as clock_mod
from tpu_resiliency.telemetry import episode as episode_mod
from tpu_resiliency.telemetry import flight, trace
from tpu_resiliency.telemetry.clock import ClockOffset
from tpu_resiliency.telemetry.exporter import (
    MetricsHTTPServer,
    render_openmetrics,
)
from tpu_resiliency.telemetry.registry import Registry
from tpu_resiliency.utils.env import disarm_platform_sitecustomize

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "workloads" / "inproc_worker.py")

# one test-only event, declared once at import like production call sites
EV_TEST = flight.declare_event("test.unit_event", "k")


@pytest.fixture(autouse=True)
def _reset_telemetry_state():
    """Flight/episode/clock keep process-global state; leave none behind."""
    flight.configure()
    flight.set_current_episode("")
    flight._last_dump_ns.clear()
    clock_mod.set_offset(None)
    with episode_mod._lock:
        episode_mod._current = None
    yield
    flight.configure()
    flight.set_current_episode("")
    flight._last_dump_ns.clear()
    clock_mod.set_offset(None)
    with episode_mod._lock:
        episode_mod._current = None


# ---- the ring ---------------------------------------------------------------


class TestRing:
    def test_capacity_rounds_up_to_power_of_two(self):
        assert flight.FlightRecorder(4).capacity == 4
        assert flight.FlightRecorder(5).capacity == 8
        assert flight.FlightRecorder(0).capacity == 2
        assert flight.FlightRecorder(4096).capacity == 4096

    def test_overwrites_oldest(self):
        ring = flight.FlightRecorder(4)
        for i in range(12):
            ring.record("test.unit_event", i)
        assert len(ring) == 4
        assert [slot[3][0] for slot in ring.snapshot()] == [8, 9, 10, 11]

    def test_snapshot_sorted_by_timestamp(self):
        ring = flight.FlightRecorder(16)
        for i in range(10):
            ring.record("test.unit_event", i)
        stamps = [slot[0] for slot in ring.snapshot()]
        assert stamps == sorted(stamps)

    def test_records_tagged_with_current_episode(self):
        ring = flight.FlightRecorder(4)
        flight.set_current_episode("ep42")
        ring.record("test.unit_event", 1)
        flight.set_current_episode("")
        ring.record("test.unit_event", 2)
        episodes = [slot[2] for slot in ring.snapshot()]
        assert episodes == ["ep42", ""]


class TestDeclaration:
    def test_invalid_names_rejected(self):
        for bad in ("nodot", "Upper.case", "has space.x", "1leading.x", "a."):
            with pytest.raises(ValueError):
                flight.declare_event(bad)

    def test_duplicate_declaration_rejected(self):
        with pytest.raises(ValueError, match="declared twice"):
            flight.declare_event("test.unit_event", "k")

    def test_registered(self):
        assert "test.unit_event" in flight.event_names()
        assert flight.event_fields("test.unit_event") == ("k",)


class TestConfigure:
    def test_disabled_is_noop(self):
        flight.configure(enabled=False)
        assert flight.get_flight() is flight.NOOP
        flight.record(EV_TEST, 1)  # must not raise, must not record
        assert len(flight.get_flight()) == 0
        assert flight.dump("disabled", min_interval_s=0.0) is None

    def test_reenable_rebinds_record(self):
        flight.configure(enabled=False)
        flight.configure(enabled=True, capacity=8)
        flight.record(EV_TEST, 7)
        ring = flight.get_flight()
        assert ring.capacity == 8
        assert len(ring) == 1


# ---- dumps ------------------------------------------------------------------


class TestDump:
    def test_dump_writes_meta_then_sorted_records(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPURX_FLIGHT_DIR", str(tmp_path))
        flight.configure(enabled=True, capacity=64)
        for i in range(5):
            flight.record(EV_TEST, i)
        path = flight.dump("unit", min_interval_s=0.0)
        assert path is not None and os.path.exists(path)
        assert os.path.basename(path).startswith("flight-")
        assert path.endswith("-unit.jsonl")
        records = [json.loads(line) for line in open(path)]
        meta, rest = records[0], records[1:]
        assert meta["event"] == "_flight_meta"
        assert meta["reason"] == "unit"
        assert meta["pid"] == os.getpid()
        assert meta["capacity"] == 64
        assert meta["events"] == len(rest)
        stamps = [r["mono_ns"] for r in rest]
        assert stamps == sorted(stamps)
        # declared field names, not positional argN keys
        ks = [r["k"] for r in rest if r["event"] == "test.unit_event"]
        assert ks == [0, 1, 2, 3, 4]

    def test_meta_carries_clock_offset(self):
        flight.configure(enabled=True, capacity=8)
        clock_mod.set_offset(ClockOffset(offset_ns=123, rtt_ns=456))
        meta = json.loads(flight.render_jsonl("request").splitlines()[0])
        assert meta["clock_offset_ns"] == 123
        assert meta["clock_rtt_ns"] == 456
        assert meta["clock_ref"] == "rank0"

    def test_per_reason_throttle(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPURX_FLIGHT_DIR", str(tmp_path))
        flight.configure(enabled=True, capacity=8)
        flight.record(EV_TEST, 1)
        assert flight.dump("trip") is not None
        assert flight.dump("trip") is None          # throttled, same reason
        assert flight.dump("other") is not None     # distinct reason passes
        assert flight.dump("trip", min_interval_s=0.0) is not None

    def test_retention(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPURX_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("TPURX_FLIGHT_DUMP_KEEP", "2")
        flight.configure(enabled=True, capacity=8)
        flight.record(EV_TEST, 1)
        paths = [
            flight.dump(f"keep{i}", min_interval_s=0.0) for i in range(4)
        ]
        assert all(paths)
        assert not os.path.exists(paths[0])
        assert not os.path.exists(paths[1])
        assert os.path.exists(paths[2])
        assert os.path.exists(paths[3])
        assert flight.last_dump_path() == paths[3]

    def test_dump_hooks_fed_parsed_records(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPURX_FLIGHT_DIR", str(tmp_path))
        flight.configure(enabled=True, capacity=8)
        flight.record(EV_TEST, 9)
        seen = []
        hook = seen.append
        flight.add_dump_hook(hook)
        try:
            flight.dump("hooked", min_interval_s=0.0)
        finally:
            flight.remove_dump_hook(hook)
        assert len(seen) == 1
        records = seen[0]
        assert records[0]["event"] == "_flight_meta"
        assert any(
            r["event"] == "test.unit_event" and r["k"] == 9 for r in records
        )

    def test_failing_hook_does_not_break_dump(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPURX_FLIGHT_DIR", str(tmp_path))
        flight.configure(enabled=True, capacity=8)
        flight.record(EV_TEST, 1)

        def bad_hook(records):
            raise RuntimeError("hook boom")

        flight.add_dump_hook(bad_hook)
        try:
            assert flight.dump("hooked", min_interval_s=0.0) is not None
        finally:
            flight.remove_dump_hook(bad_hook)


# ---- episodes ---------------------------------------------------------------


class TestEpisode:
    def test_local_fallback_lifecycle(self):
        ep = episode_mod.begin(fault_class="unit")
        assert ep.id.startswith("ep-local-")
        assert flight.current_episode_id() == ep.id
        assert episode_mod.current() is ep
        ep.phase("decide")
        ep.phase("decide")  # idempotent: no duplicate mark
        ep.phase("resume")
        phases = ep.close()
        assert set(phases) == {"detect", "decide", "resume"}
        # transition-based accounting: phases sum to wall exactly
        assert sum(phases.values()) == ep.wall_ns
        assert ep.coverage_pct() == pytest.approx(100.0)
        assert flight.current_episode_id() == ""
        assert episode_mod.current() is None
        assert ep in episode_mod.recent()

    def test_begin_is_idempotent_while_live(self):
        ep = episode_mod.begin(fault_class="unit")
        again = episode_mod.begin(fault_class="refined")
        assert again is ep
        assert ep.fault_class == "refined"
        ep.close()
        assert episode_mod.begin(fault_class="unit") is not ep

    def test_close_is_idempotent(self):
        ep = episode_mod.begin(fault_class="unit")
        first = ep.close()
        assert ep.close() == first

    def test_phase_histogram_observed_on_close(self):
        from tpu_resiliency.telemetry import get_registry

        fam = get_registry().get("tpurx_episode_phase_ns")
        assert fam is not None
        child = fam.labels("detect", "histo_unit")
        before = child.count
        ep = episode_mod.begin(fault_class="histo_unit")
        ep.close()
        assert child.count == before + 1

    def test_store_mint_publish_read(self, store):
        ep = episode_mod.begin(store=store, fault_class="unit")
        assert re.fullmatch(r"ep\d+", ep.id)
        assert store.try_get(episode_mod.CURRENT_KEY) == ep.id.encode()
        ep.phase("decide")
        time.sleep(0.01)
        ep.phase("resume")
        ep.close()
        # rank 0 close clears the job-wide current key
        assert store.try_get(episode_mod.CURRENT_KEY) == b""
        summary = json.loads(store.try_get(f"episode/{ep.id}/rank/0"))
        assert summary["fault_class"] == "unit"
        assert set(summary["phases_ns"]) == {"detect", "decide", "resume"}
        eps = episode_mod.read_episodes(store, n=5)
        assert eps and eps[0]["id"] == ep.id
        assert eps[0]["phase_ns"] == {
            k: int(v) for k, v in summary["phases_ns"].items()
        }
        assert eps[0]["wall_ns"] == summary["wall_ns"]

    def test_claim_converges_on_first_proposal(self, store):
        from tpu_resiliency.inprocess.store_ops import InprocStore

        ops = InprocStore(store)
        assert ops.claim_episode(3, "epA") == "epA"
        assert ops.claim_episode(3, "epB") == "epA"   # loser adopts winner
        assert ops.claim_episode(4, "epB") == "epB"   # new iteration, new claim
        ops.gc_iteration(3)
        assert ops.claim_episode(3, "epC") == "epC"

    def test_adopt_tags_sidecar_without_local_episode(self, store):
        store.set(episode_mod.CURRENT_KEY, "ep7")
        assert episode_mod.adopt(store) == "ep7"
        assert flight.current_episode_id() == "ep7"
        # a process with its own live episode keeps its tag
        flight.set_current_episode("")
        ep = episode_mod.begin(fault_class="unit")
        assert episode_mod.adopt(store) == "ep7"
        assert flight.current_episode_id() == ep.id
        ep.close()

    def test_current_or_store_id(self, store):
        assert episode_mod.current_or_store_id() == ""
        store.set(episode_mod.CURRENT_KEY, "ep9")
        assert episode_mod.current_or_store_id(store) == "ep9"
        ep = episode_mod.begin(fault_class="unit")
        assert episode_mod.current_or_store_id(store) == ep.id
        ep.close()


# ---- clock calibration ------------------------------------------------------


class TestClock:
    def test_calibrate_against_live_reference(self, store):
        ref = clock_mod.ClockReference(store).start()
        try:
            off = clock_mod.calibrate(store, rounds=4, set_global=False)
        finally:
            ref.stop()
        # same process = same clock domain: true offset is 0, error <= RTT
        assert off.rtt_ns > 0
        assert abs(off.offset_ns) <= off.rtt_ns
        assert clock_mod.offset() is None  # set_global=False left it alone

    def test_calibrate_recovers_injected_skew(self, store, monkeypatch):
        skew = 250_000_000  # this "host" reads 250ms ahead of the reference
        monkeypatch.setattr(
            clock_mod, "mono_ns", lambda: time.monotonic_ns() + skew
        )
        ref = clock_mod.ClockReference(store).start()
        try:
            off = clock_mod.calibrate(store, rounds=4, set_global=True)
        finally:
            ref.stop()
        # offset must cancel the skew: local + offset ~ reference domain
        assert abs(off.offset_ns + skew) <= max(off.rtt_ns, 10_000_000)
        assert clock_mod.offset() == off


# ---- trace merge ------------------------------------------------------------


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _rec(event, mono_ns, rank, **fields):
    return {"event": event, "mono_ns": mono_ns, "rank": rank, **fields}


class TestTrace:
    def test_load_aligned_applies_per_file_offset(self, tmp_path):
        fa = _write_jsonl(tmp_path / "a.jsonl", [
            {"event": "_flight_meta", "mono_ns": 0, "host": "hosta", "rank": 0},
            _rec("monitor.heartbeat", 1_000_000, 0),
        ])
        fb = _write_jsonl(tmp_path / "b.jsonl", [
            {
                "event": "_flight_meta", "mono_ns": 0, "host": "hostb",
                "rank": 1, "clock_offset_ns": -500_000,
            },
            _rec("monitor.heartbeat", 1_600_000, 1),
        ])
        events = trace.load_aligned([fa, fb], warn=False)
        by_rank = {e["rank"]: e["mono_ns"] for e in events}
        assert by_rank[0] == 1_000_000      # reference domain: unshifted
        assert by_rank[1] == 1_100_000      # shifted into the reference

    def test_two_unaligned_hosts_warn(self, tmp_path, capsys):
        fa = _write_jsonl(tmp_path / "a.jsonl", [
            {"event": "_flight_meta", "mono_ns": 0, "host": "ha", "rank": 0},
            _rec("monitor.heartbeat", 1, 0),
        ])
        fb = _write_jsonl(tmp_path / "b.jsonl", [
            {"event": "_flight_meta", "mono_ns": 0, "host": "hb", "rank": 1},
            _rec("monitor.heartbeat", 2, 1),
        ])
        trace.load_aligned([fa, fb])
        err = capsys.readouterr().err
        assert "no clock offset" in err
        assert "ha" in err and "hb" in err

    def test_single_unaligned_host_does_not_warn(self, tmp_path, capsys):
        fa = _write_jsonl(tmp_path / "a.jsonl", [
            {"event": "_flight_meta", "mono_ns": 0, "host": "ha", "rank": 0},
            _rec("monitor.heartbeat", 1, 0),
        ])
        fb = _write_jsonl(tmp_path / "b.jsonl", [
            {
                "event": "_flight_meta", "mono_ns": 0, "host": "hb",
                "rank": 1, "clock_offset_ns": 5,
            },
            _rec("monitor.heartbeat", 2, 1),
        ])
        trace.load_aligned([fa, fb])
        assert "no clock offset" not in capsys.readouterr().err

    def test_flight_span_pairing(self):
        out = trace.to_chrome_trace([
            _rec("monitor.section_begin", 1_000, 0, section="load"),
            _rec("collective.dispatch", 2_000, 0, op="all_reduce", axis="dp"),
            _rec("collective.settle", 9_000, 0,
                 op="all_reduce", axis="dp", status="ok"),
            _rec("monitor.section_end", 11_000, 0, section="load"),
        ])["traceEvents"]
        spans = {e["name"]: e for e in out if e.get("ph") == "X"}
        assert spans["section"]["dur"] == pytest.approx(10.0)
        assert spans["section"]["args"]["section"] == "load"
        assert spans["collective"]["dur"] == pytest.approx(7.0)
        assert spans["collective"]["args"]["status"] == "ok"

    def test_dangling_start_becomes_unfinished_instant(self):
        out = trace.to_chrome_trace([
            _rec("monitor.section_begin", 1_000, 0, section="load"),
            _rec("monitor.heartbeat", 2_000, 0),
        ])["traceEvents"]
        names = [e["name"] for e in out]
        assert "section (unfinished)" in names

    def test_episode_phase_spans_and_cross_rank_flows(self):
        out = trace.to_chrome_trace([
            _rec("episode.begin", 0, 0, episode="ep5", fault_class="x"),
            _rec("episode.phase", 0, 0, episode="ep5", phase="detect"),
            _rec("episode.begin", 1_000, 1, episode="ep5", fault_class="x"),
            _rec("episode.phase", 1_000, 1, episode="ep5", phase="detect"),
            _rec("episode.phase", 10_000, 0, episode="ep5", phase="decide"),
            _rec("episode.close", 20_000, 0,
                 episode="ep5", fault_class="x", wall_ns=20_000),
            _rec("episode.close", 15_000, 1,
                 episode="ep5", fault_class="x", wall_ns=14_000),
        ])["traceEvents"]
        phase_spans = [
            e for e in out if e.get("ph") == "X" and e["cat"] == "episode"
        ]
        by_track = {}
        for e in phase_spans:
            by_track.setdefault(e["pid"], []).append(e["name"])
        assert by_track[0] == ["detect", "decide"]
        assert by_track[1] == ["detect"]
        flows = [e for e in out if e.get("ph") in ("s", "t", "f")]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert all(e["args"]["episode"] == "ep5" for e in flows)
        assert {e["pid"] for e in flows} == {0, 1}
        assert len({e["id"] for e in flows}) == 1


# ---- exporter ---------------------------------------------------------------


class TestExporter:
    def test_openmetrics_escaping_golden(self):
        reg = Registry(enabled=True)
        c = reg.counter(
            "tpurx_test_esc_total", 'help "q" \\ and\nnewline',
            labels=("path",),
        )
        c.labels('a\\b"c\nd').inc(3)
        assert render_openmetrics(reg) == (
            "# TYPE tpurx_test_esc counter\n"
            "# HELP tpurx_test_esc help \\\"q\\\" \\\\ and\\nnewline\n"
            'tpurx_test_esc_total{path="a\\\\b\\"c\\nd"} 3\n'
            "# EOF\n"
        )

    def test_histogram_rendering_golden(self):
        reg = Registry(enabled=True)
        h = reg.histogram("tpurx_test_hist_ns", buckets=(10.0, 100.0))
        h.observe(5)
        h.observe(50)
        h.observe(5000)
        assert render_openmetrics(reg) == (
            "# TYPE tpurx_test_hist_ns histogram\n"
            'tpurx_test_hist_ns_bucket{le="10"} 1\n'
            'tpurx_test_hist_ns_bucket{le="100"} 2\n'
            'tpurx_test_hist_ns_bucket{le="+Inf"} 3\n'
            "tpurx_test_hist_ns_sum 5055\n"
            "tpurx_test_hist_ns_count 3\n"
            "# EOF\n"
        )

    def test_concurrent_scrape_under_mutation(self):
        reg = Registry(enabled=True)
        c = reg.counter("tpurx_test_conc_total", labels=("worker",))
        h = reg.histogram("tpurx_test_conc_ns")
        server = MetricsHTTPServer(reg, host="127.0.0.1", port=0).start()
        stop = threading.Event()

        def mutate(i):
            while not stop.is_set():
                c.labels(str(i)).inc()
                h.observe(1000.0 * (i + 1))

        threads = [
            threading.Thread(target=mutate, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            for _ in range(20):
                body = urllib.request.urlopen(url, timeout=10).read().decode()
                assert body.endswith("# EOF\n")
                # every exposition scraped mid-mutation is well-formed:
                # sample lines end in one parseable number
                for line in body.splitlines():
                    if not line or line.startswith("#"):
                        continue
                    float(line.rsplit(" ", 1)[1])
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            server.close()

    def test_get_flight_serves_live_ring(self):
        flight.configure(enabled=True, capacity=16)
        flight.record(EV_TEST, 31)
        server = MetricsHTTPServer(
            Registry(enabled=True), host="127.0.0.1", port=0
        ).start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/flight", timeout=10
            ).read().decode()
        finally:
            server.close()
        records = [json.loads(line) for line in body.splitlines()]
        assert records[0]["event"] == "_flight_meta"
        assert records[0]["reason"] == "http"
        assert any(
            r["event"] == "test.unit_event" and r["k"] == 31 for r in records
        )


# ---- two-rank soak: dumps at trip + merged aligned timeline -----------------

# rank 1's monotonic domain runs 5s ahead — a simulated second host whose
# dumps only line up after calibration-based alignment
_SOAK_SKEW_NS = 5_000_000_000


def _spawn_rank(store_port, rank, world, scenario, extra_env):
    env = dict(os.environ)
    env.update({
        "TPURX_REPO": str(REPO),
        "TPURX_RANK": str(rank),
        "TPURX_WORLD_SIZE": str(world),
        "TPURX_STORE_ADDR": "127.0.0.1",
        "TPURX_STORE_PORT": str(store_port),
        "SCENARIO": scenario,
        "STEPS": "30",
    })
    disarm_platform_sitecustomize(env)
    env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, WORKER],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=str(REPO),
    )


def _read_dump_meta(path):
    with open(path) as f:
        return json.loads(f.readline())


def test_two_rank_soak_black_boxes_and_aligned_timeline(
    store_server, tmp_path
):
    flight_dir = tmp_path / "flight"
    base = {"TPURX_FLIGHT_DIR": str(flight_dir)}
    procs = [
        _spawn_rank(store_server.port, 0, 2, "exception", base),
        _spawn_rank(
            store_server.port, 1, 2, "exception",
            {**base, "TPURX_CLOCK_TEST_SKEW_NS": str(_SOAK_SKEW_NS)},
        ),
    ]
    outs = {}
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT>"
        outs[rank] = out
    for rank, p in enumerate(procs):
        assert p.returncode == 0, f"rank {rank}:\n{outs[rank][-2500:]}"
        assert "RESULT" in outs[rank]

    # 1. black boxes: every process dumped, and at least one dump fired at
    #    the detection instant (trip/ladder), not just at exit
    dumps = sorted(str(p) for p in flight_dir.glob("flight-*.jsonl"))
    assert dumps, "no flight dumps written"
    metas = {path: _read_dump_meta(path) for path in dumps}
    assert {m["pid"] for m in metas.values()} == {p.pid for p in procs}
    assert any(
        m["reason"] in ("monitor_trip", "abort_ladder")
        for m in metas.values()
    ), f"no trip-time dump among {[m['reason'] for m in metas.values()]}"
    exit_dumps = {
        m["rank"]: path
        for path, m in metas.items() if m["reason"] == "worker_exit"
    }
    assert set(exit_dumps) == {0, 1}

    # 2. calibration recovered the injected skew: rank 1's dumps carry an
    #    offset that cancels it (error bounded by loopback RTT)
    rank1_meta = metas[exit_dumps[1]]
    assert abs(rank1_meta["clock_offset_ns"] + _SOAK_SKEW_NS) < 1_000_000_000

    # 3. one merged timeline: all six phases of the fault episode appear as
    #    spans, connected across the two ranks' tracks by flow arrows
    merged = trace.to_chrome_trace(trace.load_aligned(dumps, warn=False))
    events = merged["traceEvents"]
    ep_spans = [
        e for e in events
        if e.get("ph") == "X" and e.get("cat") == "episode"
        and e["args"].get("episode") == "ep1"
    ]
    phase_names = {e["name"].replace(" (unfinished)", "") for e in ep_spans}
    assert phase_names >= set(episode_mod.REACTIVE_PHASES), (
        f"episode phases missing from merged trace: "
        f"{set(episode_mod.REACTIVE_PHASES) - phase_names}"
    )
    assert {e["pid"] for e in ep_spans} == {0, 1}
    flows = [
        e for e in events
        if e.get("ph") in ("s", "t", "f") and e["args"].get("episode") == "ep1"
    ]
    assert {e["ph"] for e in flows} >= {"s", "f"}
    assert {e["pid"] for e in flows} == {0, 1}

    # 4. alignment made the timeline causal: both ranks saw the fault within
    #    seconds of each other; unaligned, rank 1 would sit ~5s off
    begin_ts = {}
    for e in events:
        if e.get("name") == "episode.begin":
            begin_ts.setdefault(e["pid"], e["ts"])
    assert set(begin_ts) == {0, 1}
    assert abs(begin_ts[0] - begin_ts[1]) < _SOAK_SKEW_NS / 1e3 / 2, (
        f"episode.begin instants {begin_ts} still ~skew apart — "
        "per-file offset not applied"
    )

    # 5. the store's episode record decomposes MTTR across all six phases,
    #    and GET /episodes serves the same totals
    from tpu_resiliency.services.smonsvc import make_status_server
    from tpu_resiliency.store import StoreClient

    client = StoreClient("127.0.0.1", store_server.port, timeout=10.0)
    try:
        eps = episode_mod.read_episodes(client, n=5)
        assert eps and eps[0]["id"] == "ep1"
        phase_ns = eps[0]["phase_ns"]
        assert set(phase_ns) >= set(episode_mod.REACTIVE_PHASES)
        assert all(v > 0 for v in phase_ns.values())

        monitor = types.SimpleNamespace(episode_store=client)
        server = make_status_server(monitor, "127.0.0.1", 0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.server_port}/episodes", timeout=10
            ).read()
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5)
        payload = json.loads(body)
        assert payload["enabled"] is True
        served = {e["id"]: e for e in payload["episodes"]}
        assert served["ep1"]["phase_ns"] == phase_ns
    finally:
        client.close()
