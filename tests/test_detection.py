"""Sub-millisecond detection path: native beater (ABI v3), futex/event
tripwire, fused ICI step quorum, and the ABI-staleness forcing contract.

The acceptance property asserted here (ISSUE 7): the tripwire's wake path
is EVENT-DRIVEN — the wait loop parks in ``futex(FUTEX_WAIT)`` (or
``threading.Event.wait``) and contains no polling sleep, so staleness is
observed at wake latency instead of poll-interval granularity.
"""

import ctypes
import os
import shutil
import subprocess
import threading
import time

import numpy as np
import pytest

from tpu_resiliency.ops import quorum as q
from tpu_resiliency.ops.quorum import (
    FusedStepQuorum,
    NativeBeater,
    QuorumMonitor,
    StampTripwire,
    load_beat_lib,
    now_stamp_ns,
)


def _require_native():
    if load_beat_lib() is None:
        pytest.skip("native beat helper unavailable (no toolchain)")


@pytest.fixture
def beater():
    _require_native()
    b = NativeBeater(interval_s=0.0005)
    assert b.start()
    yield b
    b.stop()


# -- NativeBeater ------------------------------------------------------------

def test_native_beater_stamps_gen_and_jitter(beater):
    time.sleep(0.1)
    g0 = beater.generation
    assert g0 > 50  # ~200 beats in 100ms at 500µs
    first = beater.stamp_ns
    assert beater.age_ns() < 500_000_000
    time.sleep(0.02)
    assert beater.generation > g0
    assert beater.stamp_ns >= first or beater.stamp_ns < first  # folded ints
    jitter = beater.jitter_ns()
    assert jitter.size > 10
    # CLOCK_MONOTONIC wake lateness: non-negative, and sane on any host
    assert (jitter >= 0).all()
    assert np.median(jitter) < 100_000_000
    p99 = beater.jitter_p99_us()
    assert p99 is not None and p99 >= 0


def test_native_beater_freeze_then_stop(beater):
    time.sleep(0.02)
    beater.freeze()
    time.sleep(0.01)
    frozen_stamp = beater.stamp_ns
    frozen_gen = beater.generation
    time.sleep(0.05)
    assert beater.stamp_ns == frozen_stamp  # stamping stopped without join
    assert beater.generation == frozen_gen
    assert beater.age_ns() >= 40_000_000
    beater.stop()  # join + free after freeze must be clean
    assert not beater.alive
    # jitter snapshot survives stop for post-mortem reporting
    assert beater.jitter_ns().size > 0


def test_native_beater_restart_reuses_slot_and_gen(beater):
    """slot/gen are allocated once per instance: tripwire references stay
    valid across a freeze/stop -> resume cycle."""
    slot_id = id(beater.slot)
    gen_id = id(beater.gen)
    beater.stop()
    assert beater.start()
    assert id(beater.slot) == slot_id and id(beater.gen) == gen_id
    time.sleep(0.01)
    assert beater.age_ns() < 500_000_000


# -- StampTripwire: event-driven staleness ----------------------------------

def _watch_sleeps(monkeypatch):
    """Record every time.sleep() call made from a tripwire thread — the
    wait loop must never poll."""
    calls = []
    real_sleep = time.sleep

    def spy(seconds):
        if threading.current_thread().name.startswith("tpurx-stamp-tripwire"):
            calls.append(seconds)
        real_sleep(seconds)

    monkeypatch.setattr(time, "sleep", spy)
    return calls


def test_futex_tripwire_detects_freeze_event_driven(monkeypatch, beater):
    sleeps = _watch_sleeps(monkeypatch)
    hits = []
    trip = StampTripwire(
        on_stale=lambda age_ms: hits.append((age_ms, time.monotonic())),
        budget_ms=2.0, beater=beater,
    ).start()
    time.sleep(0.1)
    assert not hits, f"false trip on healthy beater: {hits}"
    t_hang = time.monotonic()
    beater.freeze()
    deadline = time.monotonic() + 3.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.0002)
    trip.stop()
    assert hits, "futex tripwire never fired"
    age_ms, t_detect = hits[0]
    latency_ms = (t_detect - t_hang) * 1e3
    # budget 2ms + one beat interval + wake latency; generous CI slack
    assert latency_ms < 500, latency_ms
    assert age_ms > 2.0
    # the acceptance assert: no polling sleep anywhere in the wait loop
    assert not sleeps, f"tripwire wait loop slept: {sleeps}"


def test_event_tripwire_detects_freeze_event_driven(monkeypatch):
    """threading.Event fallback: same contract without the native shim."""
    sleeps = _watch_sleeps(monkeypatch)
    ev = threading.Event()
    last = [now_stamp_ns()]
    hits = []
    trip = StampTripwire(
        on_stale=lambda age_ms: hits.append(time.monotonic()),
        budget_ms=20.0, event=ev,
        age_ns_fn=lambda: q.clamp_future_ns(
            q.stamp_age_ns(now_stamp_ns(), last[0])
        ),
    ).start()
    for _ in range(10):
        last[0] = now_stamp_ns()
        ev.set()
        time.sleep(0.005)
    assert not hits, "false trip while beating"
    t_hang = time.monotonic()
    deadline = time.monotonic() + 3.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.001)
    trip.stop()
    assert hits, "event tripwire never fired"
    # detection lands within ~2x budget (a beat can race the freeze by
    # almost a full budget) — far from any poll-interval quantization
    assert (hits[0] - t_hang) * 1e3 < 200
    assert not sleeps, f"tripwire wait loop slept: {sleeps}"


def test_tripwire_budget_inf_suppresses_then_rearms(beater):
    """budget=inf (protected sections) suppresses trips without stopping
    the thread; restoring a finite budget re-enables detection."""
    budget = [float("inf")]
    hits = []
    trip = StampTripwire(
        on_stale=lambda age_ms: hits.append(age_ms),
        budget_ms_fn=lambda: budget[0], beater=beater,
    ).start()
    beater.freeze()
    time.sleep(0.5)  # > REARM_MS: several suppressed timeout rounds
    assert not hits, "tripwire fired during suppression"
    budget[0] = 2.0
    deadline = time.monotonic() + 3.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.001)
    trip.stop()
    assert hits, "tripwire never fired after unsuppression"


def test_tripwire_stop_wakes_parked_waiter_fast(beater):
    trip = StampTripwire(
        on_stale=lambda age_ms: None, budget_ms=5000.0, beater=beater,
    ).start()
    time.sleep(0.02)
    t0 = time.monotonic()
    trip.stop()  # kick() must release the 5s futex wait at wake latency
    assert (time.monotonic() - t0) < 1.0


def test_quorum_monitor_futex_lane_end_to_end():
    """QuorumMonitor(native_beat, futex_tripwire): a stamp freeze fires
    on_stale through the local tripwire lane without waiting for a
    collective round."""
    _require_native()
    import jax
    from tpu_resiliency.parallel.mesh import make_mesh

    mesh = make_mesh(("all",), (len(jax.devices()),))
    hits = []
    mon = QuorumMonitor(
        mesh, budget_ms=1e9, interval=0.01,
        on_stale=lambda age: hits.append((age, time.monotonic())),
        use_pallas=False, auto_beat_interval=0.0005, fetch_workers=2,
        native_beat=True, futex_tripwire=True,
    )
    try:
        mon.calibrate(n_ticks=5, min_budget_ms=0.5, margin_ms=0.3)
        mon.budget_ms = min(mon.budget_ms, 5.0)
        mon.start()
        if mon._native_beater is None or not mon._native_beater.alive:
            pytest.skip("native beater unavailable")
        time.sleep(0.15)
        assert not hits, f"false trip: {hits}"
        t_hang = time.monotonic()
        mon.stop_auto_beat()
        deadline = time.monotonic() + 3.0
        while not hits and time.monotonic() < deadline:
            time.sleep(0.0005)
        assert hits, "futex lane never fired"
        # local wake-path detection: far under the collective cadence
        assert (hits[0][1] - t_hang) * 1e3 < 500
    finally:
        mon.stop()


def test_progress_watchdog_watch_stale():
    """The watchdog's event-driven GIL-liveness tripwire: pings feed the
    beat event; a paused watchdog (frozen stamps) trips at wake latency."""
    from tpu_resiliency.inprocess.progress_watchdog import ProgressWatchdog

    w = ProgressWatchdog(interval=0.02).start()
    hits = []
    trip = w.watch_stale(0.15, lambda age_ms: hits.append(age_ms))
    try:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.3:
            w.ping()
            time.sleep(0.02)
        assert not hits, f"false trip while pinging: {hits}"
        w.pause()
        deadline = time.monotonic() + 3.0
        while not hits and time.monotonic() < deadline:
            time.sleep(0.005)
        assert hits, "watchdog tripwire never fired"
        assert hits[0] >= 150.0  # age_ms at trip >= budget
    finally:
        trip.stop()
        w.stop()


# -- FusedStepQuorum: the ICI lane ------------------------------------------

@pytest.fixture(scope="module")
def mesh8():
    import jax
    from tpu_resiliency.parallel.mesh import make_mesh

    return make_mesh(("all",), (len(jax.devices()),))


def test_fused_step_quorum_healthy_and_stale(mesh8):
    import jax
    import jax.numpy as jnp

    trips = []
    fq = FusedStepQuorum(
        mesh8, budget_ms=100.0, on_stale=lambda a, d: trips.append((a, d)),
    )
    step = jax.jit(lambda x: x * 2 + 1)
    run = fq.fuse(step)
    x = jnp.ones(8)
    for _ in range(4):
        fq.beat()
        x = run(x)
    assert fq.check_now() is not None
    assert not trips, trips
    assert fq.last_max_age_ms < 100.0
    # freeze the stamp: the NEXT fused step's reduce carries the stale age
    fq._last_beat_ns = (now_stamp_ns() - 500_000_000) % q._WRAP_NS
    x = run(x)
    fq.check_now()
    assert trips and trips[0][0] > 100.0
    assert trips[0][1] is not None and 0 <= trips[0][1] < 8
    assert float(x[0]) > 0  # step outputs still flow


def test_fused_step_quorum_one_step_lag(mesh8):
    """The wrapper materializes the PREVIOUS step's packed result: the
    first call never blocks on its own reduce (check_now drains it)."""
    import jax
    import jax.numpy as jnp

    fq = FusedStepQuorum(mesh8, budget_ms=float("inf"))
    run = fq.fuse(jax.jit(lambda x: x + 1))
    fq.beat()
    run(jnp.zeros(4))
    assert fq.last_max_age_ms is None      # nothing evaluated yet
    run(jnp.zeros(4))
    assert fq.last_max_age_ms is not None  # step 2 evaluated step 1's reduce
    assert fq.check_now() is not None      # drain the in-flight one


def test_fused_step_quorum_budget_clamped_to_cap(mesh8):
    """A finite identify-mode budget above the packed age cap could never
    trip (ages saturate below it) — the constructor clamps it."""
    fq = FusedStepQuorum(mesh8, budget_ms=5000.0)
    assert fq.budget_ms == pytest.approx(q.AGE_CAP_MS)
    fq_inf = FusedStepQuorum(mesh8, budget_ms=float("inf"))
    assert fq_inf.budget_ms == float("inf")  # disabled-lane sentinel kept


def test_fused_matches_collective_fn(mesh8):
    """The fused reduce and make_quorum_fn(identify=True) agree on the
    same frozen stamp (same packing, same single-pmax semantics)."""
    from tpu_resiliency.ops.quorum import make_quorum_fn

    stale_ns = 300_000_000
    fq = FusedStepQuorum(mesh8, budget_ms=float("inf"))
    fq._last_beat_ns = (now_stamp_ns() - stale_ns) % q._WRAP_NS
    import jax

    run = fq.fuse(jax.jit(lambda x: x))
    import jax.numpy as jnp

    run(jnp.zeros(2))
    age_fused = fq.check_now()
    fn = make_quorum_fn(mesh8, use_pallas=False, identify=True)
    n = len(mesh8.devices.flatten())
    age_ns, _dev = fn(np.full(
        n, (now_stamp_ns() - stale_ns) % q._WRAP_NS, dtype=np.int64,
    ))
    assert abs(age_fused - age_ns / 1e6) < 250.0  # same stamp, ~same age


# -- ABI v3 staleness forcing ------------------------------------------------

_V2_STUB = r"""
#include <stdint.h>
void *tpurx_beat_start(int64_t *slot, int64_t interval_us) {
    (void)slot; (void)interval_us; return 0;
}
void tpurx_beat_stop(void *handle) { (void)handle; }
int tpurx_beat_abi_v2(void) { return 2; }
"""


def test_stale_v2_so_forces_rebuild(tmp_path, monkeypatch):
    """A prebuilt v2 ``.so`` (int32-ms stamps, no gen word) loads fine and
    exports start/stop — only the required-symbol check can reject it.
    load_beat_lib must rebuild from source and come back ABI v3 (mirror of
    the original ``tpurx_beat_abi_v2`` forcing pattern, one ABI later)."""
    from tpu_resiliency.utils import native as native_mod

    cc = shutil.which(os.environ.get("CC", "cc"))
    if cc is None:
        pytest.skip("no C toolchain")
    # stage: stale v2 .so + the REAL v3 source in a scratch native dir
    src_v2 = tmp_path / "beat_v2.c"
    src_v2.write_text(_V2_STUB)
    stale_so = tmp_path / "libtpurx-beat.so"
    subprocess.run(
        [cc, "-shared", "-fPIC", "-o", str(stale_so), str(src_v2)],
        check=True, timeout=60,
    )
    shutil.copy(
        os.path.join(native_mod.NATIVE_DIR, "beat_thread.c"),
        tmp_path / "beat_thread.c",
    )
    lib_stale = ctypes.CDLL(str(stale_so))
    assert hasattr(lib_stale, "tpurx_beat_abi_v2")
    assert not hasattr(lib_stale, "tpurx_beat_abi_v3")

    monkeypatch.setattr(native_mod, "NATIVE_DIR", str(tmp_path))
    monkeypatch.setattr(native_mod, "_cache", {})
    lib = load_beat_lib()
    assert lib is not None, "rebuild from source failed"
    assert int(lib.tpurx_beat_abi_v3()) == 3
    assert hasattr(lib, "tpurx_beat_wait_stale")
    # the on-disk .so was actually replaced by the rebuild (symbol names
    # live in .dynstr as plain bytes; a re-dlopen of the same path would
    # dedupe to the stale mapping, which is exactly why the loader loads
    # the temp build path — see utils/native._build_and_load)
    disk = stale_so.read_bytes()
    assert b"tpurx_beat_abi_v3" in disk
    assert b"tpurx_beat_abi_v2" not in disk


# -- telemetry ---------------------------------------------------------------

def test_detection_telemetry_series_emit(beater):
    from tpu_resiliency.telemetry import get_registry

    reg = get_registry()
    hits = []
    trip = StampTripwire(
        on_stale=lambda age_ms: hits.append(age_ms), budget_ms=2.0,
        beater=beater,
    ).start()
    time.sleep(0.05)
    beater.jitter_p99_us()
    beater.freeze()
    deadline = time.monotonic() + 3.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.001)
    trip.stop()
    assert hits
    assert reg.value_of(
        "tpurx_quorum_futex_waits_total", {"outcome": "stale"}
    ) >= 1
    assert reg.value_of(
        "tpurx_quorum_futex_waits_total", {"outcome": "fresh"}
    ) >= 1
    names = {fam["name"] for fam in reg.collect()}
    assert "tpurx_quorum_detect_ns" in names
    assert "tpurx_beat_jitter_p99_us" in names
    assert "tpurx_beat_sched_flags" in names
